"""Batched LLMService reconciler.

Reference shape (llmservice_controller.go:66-174): fetch CR → build desired
Deployment → create if missing → copy ready count to status → update status.
Reference gaps fixed here, per SURVEY.md:

- **Batched, not per-CR**: one tick lists every service/workload/node and
  solves ALL pending replicas in one dense tensor (§3.2 "insertion point for
  the batched TPU solver"), instead of one API round-trip chain per CR.
- **Drift correction**: the reference admits it never updates an existing
  Deployment (llmservice_controller.go:99-100); here replica count, image
  and model drift are reconciled every tick.
- **Garbage collection**: workloads whose owner LLMService is gone are
  deleted (the reference leans on K8s ownerReferences; our store has no GC
  of its own).
- **Placement is explicit**: bindings come from the SchedulerBackend
  selected per-CR by ``spec.schedulerPolicy`` — the north-star scheduler the
  reference delegates to kube-scheduler.

Full re-solve each tick (BASELINE.json config 4): every replica — bound or
not — re-enters the solve; the move-hysteresis cost keeps placements stable
unless priority pressure genuinely displaces them. A replica whose node
assignment changes is reset to Starting (its agent restarts the runtime).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from kubeinfer_tpu import metrics
from kubeinfer_tpu.api.types import LLMService, SchedulerPolicy
from kubeinfer_tpu.api.workload import NodeState, ReplicaSpec, Workload
from kubeinfer_tpu.controlplane.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    Store,
)
from kubeinfer_tpu.observability import tracing
from kubeinfer_tpu.router import scoring
from kubeinfer_tpu.scheduler import SolveRequest, get_backend
from kubeinfer_tpu.solver.problem import GIB, MAX_MODELS
from kubeinfer_tpu.utils.clock import Clock, RealClock

log = logging.getLogger(__name__)

_TRACER = tracing.get_tracer("controller")

CONTROLLER_NAME = "llmservice"  # reconcile_total{controller=...}
NODE_HEARTBEAT_TTL_S = 30.0  # nodes silent longer than this are unschedulable


@dataclass
class ReconcileResult:
    """Diagnostics for one batched tick."""

    services: int = 0
    nodes: int = 0
    workloads_created: int = 0
    workloads_deleted: int = 0
    replicas_total: int = 0
    replicas_placed: int = 0
    evacuations: int = 0
    solve_ms: dict[str, float] = field(default_factory=dict)
    duration_ms: float = 0.0


class Controller:
    """Batched reconciler over a control-plane store."""

    def __init__(
        self,
        store: Store,
        clock: Clock | None = None,
        node_ttl_s: float = NODE_HEARTBEAT_TTL_S,
        slo_burn_limit: float = 0.0,
        drainer: Callable[[NodeState], bool] | None = None,
    ) -> None:
        self._store = store
        self._clock = clock or RealClock()
        self._node_ttl = node_ttl_s
        # SLO-burn evacuation (live-session migration's third caller):
        # a node whose serving heartbeat reports slo_burn >= limit gets
        # its sessions drained OUT before the SLO is blown, not after.
        # The drainer is injected because the controller must not know
        # how to reach a serving plane (in deployment it POSTs
        # /admin/drain to the node's replica endpoint; in tests it's a
        # recording lambda); it returns True once the drain is accepted.
        # limit <= 0 or drainer None disables the pass.
        self._slo_burn_limit = float(slo_burn_limit)
        self._drainer = drainer
        # one drain request per burn episode: the node stays hot (and
        # possibly above the limit) for the whole drain, and hammering
        # /admin/drain each tick would reset wait_drained clocks. The
        # episode ends when the node's heartbeat stops reporting
        # draining AND its burn is back under the limit.
        self._evacuating: set[str] = set()

    # -- desired state (reference desiredDeployment, :182-313) ------------

    def _desired_workload(self, svc: LLMService) -> Workload:
        name = svc.metadata.name
        cache_group = f"{name}-cache"  # llmservice_controller.go:191
        w = Workload(
            owner=name,
            image=svc.spec.image,
            model_repo=svc.spec.model,
            model_path="/models",
            cache_group=cache_group,
            cache_shared=svc.spec.cache_strategy.value == "shared",
            gpu_per_replica=svc.spec.gpu_per_replica,
            gpu_memory_bytes=svc.spec.gpu_memory_bytes(),
            env={  # env contract parity (llmservice_controller.go:231-266)
                "POD_NAMESPACE": svc.metadata.namespace,
                "CONFIGMAP_NAME": cache_group,
                "MODEL_PATH": "/models",
                "MODEL_REPO": svc.spec.model,
                # engine selection (vllm = reference pass-through,
                # native = in-framework TPU engine; runtime.py from_env)
                "RUNTIME_KIND": svc.spec.runtime.value,
                **(
                    {"VLLM_MAX_MODEL_LEN": str(svc.spec.max_model_len)}
                    if svc.spec.max_model_len > 0
                    else {}
                ),
            },
            replicas=[ReplicaSpec(index=i) for i in range(svc.spec.replicas)],
        )
        w.metadata.name = name
        w.metadata.namespace = svc.metadata.namespace
        w.metadata.owner_references = [
            {"kind": LLMService.KIND, "name": name, "uid": svc.metadata.uid}
        ]
        return w

    def _reconcile_workload(
        self, svc: LLMService, existing: Workload | None, result: ReconcileResult
    ) -> Workload:
        """Create-if-missing + drift correction (count/image/model)."""
        if existing is None:
            desired = self._desired_workload(svc)
            try:
                stored = self._store.create(Workload.KIND, desired.to_dict())
            except AlreadyExistsError:
                stored = self._store.get(
                    Workload.KIND, svc.metadata.name, svc.metadata.namespace
                )
            else:
                result.workloads_created += 1
            return Workload.from_dict(stored)

        w = existing
        dirty = False
        if w.image != svc.spec.image or w.model_repo != svc.spec.model:
            w.image = svc.spec.image
            w.model_repo = svc.spec.model
            w.env["MODEL_REPO"] = svc.spec.model
            # New model/image invalidates running replicas: restart them.
            for r in w.replicas:
                r.phase = "Starting" if r.node else "Pending"
            dirty = True
        want = svc.spec.replicas
        if len(w.replicas) != want:
            if len(w.replicas) > want:
                w.replicas = w.replicas[:want]
            else:
                w.replicas.extend(
                    ReplicaSpec(index=i) for i in range(len(w.replicas), want)
                )
            dirty = True
        w.gpu_per_replica = svc.spec.gpu_per_replica
        w.gpu_memory_bytes = svc.spec.gpu_memory_bytes()
        if dirty:
            w = self._update_workload(w)
        return w

    def _update_workload(self, w: Workload) -> Workload:
        """CAS write with merge-and-retry (agents also write workloads).

        Merge semantics: the controller owns bindings and replica-set
        shape; the agents own runtime truth (phase/pod fields). Where our
        binding agrees with the fresh copy, adopt the agent's runtime
        fields — clobbering them with our pre-tick snapshot would un-Ready
        replicas that just came up. Agents patch continuously (role flips,
        readiness), so a single retry is not enough under churn.
        """
        last: Exception | None = None
        for _ in range(8):
            try:
                stored = self._store.update(Workload.KIND, w.to_dict())
                return Workload.from_dict(stored)
            except ConflictError as e:
                last = e
                fresh = Workload.from_dict(
                    self._store.get(
                        Workload.KIND, w.metadata.name, w.metadata.namespace
                    )
                )
                fresh_by_index = {r.index: r for r in fresh.replicas}
                for r in w.replicas:
                    fr = fresh_by_index.get(r.index)
                    if fr is not None and fr.node == r.node:
                        r.phase = fr.phase
                        r.pod_name = fr.pod_name
                        r.pod_ip = fr.pod_ip
                w.metadata.resource_version = fresh.metadata.resource_version
        assert last is not None
        raise last

    # -- batched solve -----------------------------------------------------

    def _schedulable_nodes(self, now: float) -> list[NodeState]:
        nodes = [
            NodeState.from_dict(d) for d in self._store.list(NodeState.KIND)
        ]
        return [
            n
            for n in nodes
            if n.ready
            and (n.heartbeat == 0.0 or now - n.heartbeat <= self._node_ttl)
        ]

    def _evacuate_burning(self, nodes: list[NodeState],
                          result: ReconcileResult) -> None:
        """One evacuation pass over the schedulable nodes: drain the
        serving plane of every node whose heartbeat reports an SLO
        burn rate at or over the limit. Draining is the migration
        entry point — the node's engine streams its live sessions'
        KV out and the router resumes them on colder replicas — so
        this pass converts 'about to blow the SLO' into a latency
        blip instead of a correctness event. Failures stay candidates
        next tick; the pass never raises into the solve."""
        if self._slo_burn_limit <= 0 or self._drainer is None:
            return
        for n in nodes:
            stats = n.serving_stats if isinstance(n.serving_stats, dict) else {}
            name = n.metadata.name
            burning = (
                float(stats.get("slo_burn") or 0.0) >= self._slo_burn_limit
            )
            if stats.get("draining"):
                continue  # drain in progress (ours or an operator's)
            if not burning:
                self._evacuating.discard(name)  # episode over
                continue
            if name in self._evacuating:
                continue  # requested; heartbeat hasn't confirmed yet
            try:
                ok = bool(self._drainer(n))
            except Exception:
                log.exception("evacuation drain of %s failed", name)
                ok = False
            if ok:
                self._evacuating.add(name)
                result.evacuations += 1
            metrics.evacuations_total.inc(
                name, "drained" if ok else "failed"
            )

    def _solve_batch(
        self,
        pairs: list[tuple[LLMService, Workload]],
        nodes: list[NodeState],
        result: ReconcileResult,
    ) -> None:
        """One dense solve per scheduler policy; bindings written in place."""
        if not nodes:
            for _, w in pairs:
                for r in w.replicas:
                    if r.node:
                        r.node = ""
                        r.phase = "Pending"
            return

        node_index = {n.metadata.name: i for i, n in enumerate(nodes)}
        model_table: dict[str, int] = {}

        def model_slot(name: str) -> int:
            if not name:
                return 0
            slot = model_table.get(name)
            if slot is None:
                if len(model_table) + 1 >= MAX_MODELS:
                    return 0
                slot = len(model_table) + 1
                model_table[name] = slot
            return slot

        # Node-side free capacity is threaded THROUGH the policy groups:
        # each group's solve sees what the previous groups left, or two
        # backends would double-book the same chips.
        n_gpu = np.array([n.gpu_free for n in nodes], np.float32)
        n_mem = np.array(
            [n.gpu_memory_free_bytes / GIB for n in nodes], np.float32
        )
        n_gpu_cap = np.array([n.gpu_capacity for n in nodes], np.float32)
        n_mem_cap = np.array([n.gpu_memory_bytes / GIB for n in nodes], np.float32)
        n_topo = np.array([n.topology[0] for n in nodes], np.int32)

        # Group replica rows by policy (one dense solve per backend).
        groups: dict[str, list[tuple[LLMService, Workload]]] = {}
        for svc, w in pairs:
            groups.setdefault(svc.spec.scheduler_policy.value, []).append((svc, w))

        # Highest-priority group solves first: capacity is threaded between
        # groups, so group order is the cross-policy preemption order.
        ordered = sorted(
            groups.items(),
            key=lambda kv: -max(svc.spec.priority for svc, _ in kv[1]),
        )
        for policy, members in ordered:
            rows: list[tuple[Workload, ReplicaSpec]] = []
            gpu, mem, prio, gang, model, cur = [], [], [], [], [], []
            for gi, (svc, w) in enumerate(members):
                slot = model_slot(w.model_repo)
                for r in w.replicas:
                    rows.append((w, r))
                    gpu.append(float(w.gpu_per_replica))
                    mem.append(w.gpu_memory_bytes / GIB)
                    prio.append(float(svc.spec.priority))
                    gang.append(gi if svc.spec.gang else -1)
                    model.append(slot)
                    cur.append(node_index.get(r.node, -1))
            if not rows:
                continue

            # Lookup-only (no registration): a cached model no job in this
            # batch references gives no affinity signal, and registering it
            # would burn table slots needed by later job models.
            #
            # Queue-pressure affinity (ROADMAP item 4, solver-routed):
            # placement and the fleet router optimize the same
            # objective — prefix/cache affinity minus queue pressure
            # (router/scoring.py). Formerly a binary gate ("affine
            # unless the queue is PRESSURE_AFFINITY_CUTOFF deep");
            # now each job row runs as a pseudo-request through the
            # SAME batched route solve the router uses
            # (solver/routing.solved_affinity), so the bitmap holds
            # real solved assignments: the cutoff becomes relative (a
            # drowning caching node keeps its pull against
            # alternatives within CUTOFF of its own pressure) and the
            # greedy feedback spreads pulls across caching nodes
            # instead of piling every job's affinity onto one.
            # Capacity/feasibility is untouched — a drowning node can
            # still be chosen when nothing else fits.
            base_cached = np.zeros((len(nodes), MAX_MODELS), np.uint8)
            for i, n in enumerate(nodes):
                for m in n.cached_models:
                    s = model_table.get(m)
                    if s:
                        base_cached[i, s] = 1
            from kubeinfer_tpu.solver import routing as solver_routing

            cached = solver_routing.solved_affinity(
                np.array(model, np.int32),
                base_cached,
                np.array(
                    [scoring.queue_pressure(n.serving_stats)
                     for n in nodes], np.float32,
                ),
                np.array(
                    [float((n.serving_stats or {}).get("n_slots") or 1)
                     if isinstance(n.serving_stats, dict) else 1.0
                     for n in nodes], np.float32,
                ),
                alpha=scoring.ALPHA_QUEUE_BLOCKS,
                cutoff=scoring.PRESSURE_AFFINITY_CUTOFF,
            )

            req = SolveRequest(
                job_gpu=np.array(gpu, np.float32),
                job_mem_gib=np.array(mem, np.float32),
                job_priority=np.array(prio, np.float32),
                job_gang=np.array(gang, np.int32),
                job_model=np.array(model, np.int32),
                job_current_node=np.array(cur, np.int32),
                node_gpu_free=n_gpu,
                node_mem_free_gib=n_mem,
                node_gpu_capacity=n_gpu_cap,
                node_mem_capacity_gib=n_mem_cap,
                node_topology=n_topo,
                node_cached=cached,
            )
            with _TRACER.span("controller.solve", policy=policy,
                              jobs=len(rows), nodes=len(nodes)):
                res = get_backend(policy).solve(req)
            result.solve_ms[policy] = res.solve_ms
            result.replicas_total += len(rows)
            result.replicas_placed += res.placed
            metrics.solve_duration_seconds.observe(policy, res.solve_ms / 1e3)
            metrics.solve_placement_ratio.set(
                policy, res.placed / max(len(rows), 1)
            )
            metrics.solve_problem_size.set(policy, "jobs", len(rows))
            metrics.solve_problem_size.set(policy, "nodes", len(nodes))

            for (w, r), a in zip(rows, res.assignment):
                new_node = nodes[a].metadata.name if a >= 0 else ""
                if a >= 0:
                    n_gpu[a] -= w.gpu_per_replica
                    n_mem[a] -= w.gpu_memory_bytes / GIB
                if new_node != r.node:
                    r.node = new_node
                    r.phase = "Starting" if new_node else "Pending"
                    r.pod_name = ""
                    r.pod_ip = ""
                elif new_node and r.phase == "Pending":
                    r.phase = "Starting"

    # -- status (reference :148-164) --------------------------------------

    def _sync_status(self, svc: LLMService, w: Workload) -> None:
        from kubeinfer_tpu.api.types import Condition

        ready = sum(1 for r in w.replicas if r.phase == "Ready")
        bound = sum(1 for r in w.replicas if r.node)
        svc.status.available_replicas = ready
        svc.status.placements = [r.node for r in w.replicas]
        if ready == len(w.replicas) and ready > 0:
            phase = "Running"
        elif ready > 0:
            phase = "Degraded"
        elif bound > 0:
            phase = "Scheduling"
        else:
            phase = "Pending"
        svc.status.phase = phase
        svc.status.set_condition(
            Condition(
                type="Available",
                status="True" if phase == "Running" else "False",
                reason=phase,
                message=f"{ready}/{len(w.replicas)} replicas ready",
                last_update_time=self._clock.now(),
            )
        )
        # Elected coordinator from the lease (status.CacheCoordinator parity)
        try:
            lease = self._store.get(
                "Lease", f"{w.cache_group}-lease", svc.metadata.namespace
            )
            svc.status.cache_coordinator = lease["spec"].get("holderIdentity", "")
        except NotFoundError:
            svc.status.cache_coordinator = ""

        metrics.llmservice_ready_replicas.set(
            svc.metadata.namespace, svc.metadata.name, ready
        )
        try:
            self._store.update(LLMService.KIND, svc.to_dict())
        except ConflictError:
            # Spec writer won the race; next tick re-syncs status.
            metrics.reconcile_total.inc(CONTROLLER_NAME, "conflict")

    # -- the tick ----------------------------------------------------------

    def reconcile_once(self) -> ReconcileResult:
        # one span per tick: store-client spans (lists, status writes)
        # and per-policy solve spans nest under it
        with _TRACER.span("controller.reconcile") as sp:
            result = self._reconcile_once()
            sp.set(services=result.services, nodes=result.nodes,
                   placed=result.replicas_placed)
            return result

    def _reconcile_once(self) -> ReconcileResult:
        t0 = time.perf_counter()
        result = ReconcileResult()
        now = self._clock.now()

        services = [
            LLMService.from_dict(d) for d in self._store.list(LLMService.KIND)
        ]
        workloads = {
            (d["metadata"]["namespace"], d["metadata"]["name"]): Workload.from_dict(d)
            for d in self._store.list(Workload.KIND)
        }
        result.services = len(services)

        # GC: workloads whose owner is gone (ownerReferences semantics).
        svc_keys = {(s.metadata.namespace, s.metadata.name) for s in services}
        for key, w in list(workloads.items()):
            if key not in svc_keys:
                try:
                    self._store.delete(Workload.KIND, w.metadata.name, w.metadata.namespace)
                    result.workloads_deleted += 1
                    metrics.llmservice_ready_replicas.delete(
                        w.metadata.namespace, w.metadata.name
                    )
                except NotFoundError:
                    pass
                del workloads[key]

        pairs: list[tuple[LLMService, Workload]] = []
        for svc in services:
            key = (svc.metadata.namespace, svc.metadata.name)
            w = self._reconcile_workload(svc, workloads.get(key), result)
            pairs.append((svc, w))

        nodes = self._schedulable_nodes(now)
        result.nodes = len(nodes)
        self._evacuate_burning(nodes, result)
        self._solve_batch(pairs, nodes, result)

        for svc, w in pairs:
            w = self._update_workload(w)
            self._sync_status(svc, w)

        metrics.llmservice_total.set(len(services))
        result.duration_ms = (time.perf_counter() - t0) * 1e3
        metrics.reconcile_total.inc(CONTROLLER_NAME, "success")
        metrics.reconcile_duration_seconds.observe(
            CONTROLLER_NAME, result.duration_ms / 1e3
        )
        return result

    # -- loop --------------------------------------------------------------

    def run(self, stop, tick_interval_s: float = 1.0) -> None:
        """Reconcile loop: immediate tick on watch events (the
        SetupWithManager For+Owns equivalent), periodic tick as fallback.
        ``stop`` is a threading.Event.

        After each tick, events the tick itself produced (workload/status
        writes) are drained so the controller doesn't wake on its own
        writes; an external write racing that drain is picked up by the
        next periodic tick at the latest.
        """
        watch = self._store.watch()
        try:
            while not stop.is_set():
                try:
                    self.reconcile_once()
                except Exception:
                    # A failed tick must not kill the control plane; the
                    # next tick re-lists everything from scratch.
                    log.exception("reconcile tick failed")
                    metrics.reconcile_total.inc(CONTROLLER_NAME, "error")
                watch.drain()
                ev = watch.next_event(timeout=tick_interval_s)
                if ev is not None:
                    watch.drain()  # coalesce: one tick serves a burst
        finally:
            watch.close()
