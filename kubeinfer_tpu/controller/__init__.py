"""Control-plane controller: the batched LLMService reconciler.

Parity target: reference internal/controller/llmservice_controller.go
(Reconcile + desiredDeployment + SetupWithManager), redesigned around the
north-star insertion point (SURVEY.md §3.2): instead of a per-CR serial
I/O-dominated loop, one tick batches every pending replica across all CRs
into a single dense solve on the accelerator.
"""

from kubeinfer_tpu.controller.reconciler import Controller, ReconcileResult

__all__ = ["Controller", "ReconcileResult"]
