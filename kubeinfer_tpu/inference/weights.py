"""HuggingFace checkpoint loading for the native runtime.

Maps a llama-family HF checkpoint (config.json + *.safetensors, exactly
what the coordinator's ``huggingface-cli download`` drops into the model
cache — coordinator.go:99-105 parity path) onto model.py's param pytree.
Torch Linear weights are [out, in]; ours are [in, out] so the forward is
``x @ W`` — every projection transposes once at load time, never at
inference time.
"""

from __future__ import annotations

import json
import pathlib
from typing import Mapping

import jax.numpy as jnp
import numpy as np

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.model import Params
from kubeinfer_tpu.inference.weight_quant import quantize_weight


def _to_np(t) -> np.ndarray:
    """Tensor-ish (torch / numpy / jax) -> numpy, bf16-safe."""
    if isinstance(t, np.ndarray):
        return t
    if hasattr(t, "detach"):  # torch
        t = t.detach()
        if t.dtype.__str__() == "torch.bfloat16":
            t = t.float()
        return t.cpu().numpy()
    return np.asarray(t)


def params_from_state_dict(
    sd: Mapping[str, object], cfg: ModelConfig, dtype=jnp.bfloat16,
    weight_dtype: str = "bf16",
) -> Params:
    """HF llama state dict (name -> tensor) -> model.py param pytree.

    ``weight_dtype="int8"`` quantizes each projection as it is mapped
    (weight_quant.quantize_weight on the host tensor), so the
    full-precision [in, out] device copy of a quantized leaf never
    exists — the largest device-resident transient is one layer's
    quantization scratch, not the whole bf16 model."""
    if weight_dtype not in ("bf16", "int8"):
        raise ValueError(f"weight_dtype must be bf16|int8: {weight_dtype!r}")

    def get(name: str) -> np.ndarray:
        for key in (name, f"model.{name}"):
            if key in sd:
                return _to_np(sd[key])
        raise KeyError(f"checkpoint missing tensor {name!r}")

    def linear(name: str, quant: bool = False):
        w = get(name).T  # [out,in] -> [in,out]
        if quant and weight_dtype == "int8":
            return quantize_weight(jnp.asarray(w, jnp.float32))
        return jnp.asarray(w, dtype)

    layers = []
    for i in range(cfg.num_hidden_layers):
        p = f"layers.{i}"
        layer = {
            "input_layernorm": jnp.asarray(
                get(f"{p}.input_layernorm.weight"), dtype
            ),
            "post_attention_layernorm": jnp.asarray(
                get(f"{p}.post_attention_layernorm.weight"), dtype
            ),
            "q_proj": linear(f"{p}.self_attn.q_proj.weight", quant=True),
            "k_proj": linear(f"{p}.self_attn.k_proj.weight", quant=True),
            "v_proj": linear(f"{p}.self_attn.v_proj.weight", quant=True),
            "o_proj": linear(f"{p}.self_attn.o_proj.weight", quant=True),
        }
        if cfg.num_local_experts > 0:
            # Mixtral naming: block_sparse_moe.gate is the router,
            # experts.{e}.w1/w3/w2 are gate/up/down; stack experts on a
            # leading axis (moe.py's [E, ...] layout, sharded over ep)
            E = cfg.num_local_experts
            m = f"{p}.block_sparse_moe"
            layer["moe"] = {
                "router": linear(f"{m}.gate.weight"),
                "gate_proj": jnp.stack(
                    [linear(f"{m}.experts.{e}.w1.weight") for e in range(E)]
                ),
                "up_proj": jnp.stack(
                    [linear(f"{m}.experts.{e}.w3.weight") for e in range(E)]
                ),
                "down_proj": jnp.stack(
                    [linear(f"{m}.experts.{e}.w2.weight") for e in range(E)]
                ),
            }
        else:
            layer["gate_proj"] = linear(
                f"{p}.mlp.gate_proj.weight", quant=True
            )
            layer["up_proj"] = linear(f"{p}.mlp.up_proj.weight", quant=True)
            layer["down_proj"] = linear(
                f"{p}.mlp.down_proj.weight", quant=True
            )
        if cfg.qkv_bias:  # Qwen2 family
            layer["q_bias"] = jnp.asarray(
                get(f"{p}.self_attn.q_proj.bias"), dtype
            )
            layer["k_bias"] = jnp.asarray(
                get(f"{p}.self_attn.k_proj.bias"), dtype
            )
            layer["v_bias"] = jnp.asarray(
                get(f"{p}.self_attn.v_proj.bias"), dtype
            )
        layers.append(layer)
    params: Params = {
        "embed_tokens": jnp.asarray(get("embed_tokens.weight"), dtype),
        "layers": layers,
        "norm": jnp.asarray(get("norm.weight"), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = linear("lm_head.weight")
    return params


def load_pretrained(
    model_dir: str, dtype=jnp.bfloat16, weight_dtype: str = "bf16"
) -> tuple[Params, ModelConfig]:
    """Load (params, config) from an HF snapshot directory."""
    root = pathlib.Path(model_dir)
    with open(root / "config.json", "r", encoding="utf-8") as f:
        cfg = ModelConfig.from_hf_dict(json.load(f))

    from safetensors import safe_open

    sd: dict[str, np.ndarray] = {}
    shards = sorted(root.glob("*.safetensors"))
    if not shards:
        raise FileNotFoundError(f"no *.safetensors under {model_dir}")
    for shard in shards:
        with safe_open(str(shard), framework="np") as f:
            for name in f.keys():
                sd[name] = f.get_tensor(name)
    return params_from_state_dict(sd, cfg, dtype, weight_dtype), cfg
