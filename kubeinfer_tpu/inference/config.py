"""Model configuration for the native inference runtime.

Field names follow the HuggingFace llama config vocabulary so checkpoints
map 1:1 (weights.py); presets cover the model families the reference's
samples reference (facebook/opt-style tiny demo models up through
llama-70B-class shapes for sizing math).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


def _hf_head_dim_override(d: dict) -> int:
    """Explicit head width from a HF config dict, 0 when derivable.

    GemmaConfig defaults head_dim to 256 REGARDLESS of
    hidden_size/num_heads, so a gemma config.json that omits the key
    still means 256 — deriving it would build a wrong-geometry model
    whose q reshape fails against the checkpoint's 256-wide heads.
    """
    derived = d["hidden_size"] // d["num_attention_heads"]
    default = 256 if d.get("model_type") == "gemma" else derived
    hd = d.get("head_dim", default)
    return hd if hd != derived else 0


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32  # < heads => grouped-query attention
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    # Qwen2-family attention: biases on q/k/v projections (o stays
    # bias-free — the Qwen2 scheme; published llama checkpoints never
    # ship attention biases, so the hypothetical llama attention_bias
    # o-projection bias is deliberately unsupported)
    qkv_bias: bool = False
    # Mixtral-family sparse MLP: >0 replaces every dense MLP with a
    # top-k routed mixture of SwiGLU experts (moe.py)
    num_local_experts: int = 0
    num_experts_per_tok: int = 2
    # Gemma-family deltas from the llama recipe: tanh-approx GeGLU
    # instead of SwiGLU ("gelu_pytorch_tanh"), embeddings scaled by
    # sqrt(hidden_size) on the way in, and RMSNorm weights stored as an
    # OFFSET from 1 (x_norm * (1 + w), zero-init) rather than a gain
    hidden_act: str = "silu"
    scale_embeddings: bool = False
    rmsnorm_offset: bool = False
    # Explicit head width for families where it is NOT
    # hidden_size/num_heads (gemma-7b: 16 heads x 256 on a 3072 hidden —
    # the q/o projections are then [H, heads*head_dim] rectangles, which
    # the decoder already handles generically). 0 = derive.
    head_dim_override: int = 0

    @property
    def head_dim(self) -> int:
        if self.head_dim_override:
            return self.head_dim_override
        return self.hidden_size // self.num_attention_heads

    def __post_init__(self) -> None:
        if not self.head_dim_override and (
            self.hidden_size % self.num_attention_heads
        ):
            raise ValueError("hidden_size must divide by num_attention_heads")
        # refuse-at-config-time (same convention as the attention_bias
        # check in from_hf_dict): an unknown activation would otherwise
        # only raise mid-jit-trace inside the first forward
        if self.hidden_act not in ("silu", "gelu_pytorch_tanh"):
            raise ValueError(
                f"unsupported hidden_act {self.hidden_act!r} "
                "(silu and gelu_pytorch_tanh are implemented)"
            )
        if self.num_attention_heads % self.num_key_value_heads:
            raise ValueError(
                "num_attention_heads must divide by num_key_value_heads"
            )

    @classmethod
    def from_hf_dict(cls, d: dict[str, Any]) -> "ModelConfig":
        """Build from a HuggingFace config.json dict (llama family)."""
        # llama-style attention_bias=true also puts a bias on o_proj,
        # which this runtime does not model; loading such a checkpoint
        # with that bias silently dropped would corrupt every layer's
        # attention output, so refuse at config time instead.
        if d.get("attention_bias", False) and d.get("model_type") != "qwen2":
            raise ValueError(
                "attention_bias=true (o_proj bias) is not supported; "
                "only the Qwen2 q/k/v-bias scheme is implemented"
            )
        return cls(
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_hidden_layers=d["num_hidden_layers"],
            num_attention_heads=d["num_attention_heads"],
            num_key_value_heads=d.get(
                "num_key_value_heads", d["num_attention_heads"]
            ),
            rms_norm_eps=d.get("rms_norm_eps", 1e-5),
            rope_theta=d.get("rope_theta", 10000.0),
            max_position_embeddings=d.get("max_position_embeddings", 4096),
            tie_word_embeddings=d.get("tie_word_embeddings", False)
            or d.get("model_type") == "gemma",
            qkv_bias=d.get("model_type") == "qwen2",
            num_local_experts=d.get("num_local_experts", 0),
            num_experts_per_tok=d.get("num_experts_per_tok", 2),
            hidden_act=(
                "gelu_pytorch_tanh"
                if d.get("model_type") == "gemma"
                else d.get("hidden_act", "silu")
            ),
            scale_embeddings=d.get("model_type") == "gemma",
            rmsnorm_offset=d.get("model_type") == "gemma",
            head_dim_override=_hf_head_dim_override(d),
        )


PRESETS: dict[str, ModelConfig] = {
    # CI-sized model: small enough for the 1-core test box, GQA on
    "tiny": ModelConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=512,
    ),
    # benchmark-sized model (~280M params): big enough that decode is
    # HBM-bound like production models, small enough to init on-chip in
    # seconds
    "bench-280m": ModelConfig(
        vocab_size=32000,
        hidden_size=1024,
        intermediate_size=4096,
        num_hidden_layers=16,
        num_attention_heads=16,
        num_key_value_heads=8,
        max_position_embeddings=4096,
    ),
    # serving-scale benchmark model (~1.7B params, llama-family shape):
    # big enough that HBM pressure, bucketing, and flash attention bite
    # (r4 verdict item 3 — every published serving number was 280M),
    # small enough to random-init on a 16GB v5e chip with headroom for
    # KV caches (bf16 weights ~3.5GB)
    "bench-1p7b": ModelConfig(
        vocab_size=32000,
        hidden_size=2048,
        intermediate_size=8192,
        num_hidden_layers=24,
        num_attention_heads=16,
        num_key_value_heads=8,
        max_position_embeddings=4096,
    ),
    "qwen2-7b": ModelConfig(
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_hidden_layers=28,
        num_attention_heads=28,
        num_key_value_heads=4,
        rms_norm_eps=1e-6,
        rope_theta=1000000.0,
        max_position_embeddings=32768,
        qkv_bias=True,
    ),
    "tiny-gemma": ModelConfig(  # demo/e2e-sized gemma-family config
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=1,
        rms_norm_eps=1e-6,
        max_position_embeddings=512,
        tie_word_embeddings=True,
        hidden_act="gelu_pytorch_tanh",
        scale_embeddings=True,
        rmsnorm_offset=True,
    ),
    "gemma-2b": ModelConfig(
        vocab_size=256000,
        hidden_size=2048,
        intermediate_size=16384,
        num_hidden_layers=18,
        num_attention_heads=8,
        num_key_value_heads=1,  # multi-query attention
        rms_norm_eps=1e-6,
        max_position_embeddings=8192,
        tie_word_embeddings=True,
        hidden_act="gelu_pytorch_tanh",
        scale_embeddings=True,
        rmsnorm_offset=True,
    ),
    "gemma-7b": ModelConfig(
        vocab_size=256000,
        hidden_size=3072,
        intermediate_size=24576,
        num_hidden_layers=28,
        num_attention_heads=16,
        num_key_value_heads=16,
        head_dim_override=256,  # 16 x 256 = 4096-wide q/o on 3072 hidden
        rms_norm_eps=1e-6,
        max_position_embeddings=8192,
        tie_word_embeddings=True,
        hidden_act="gelu_pytorch_tanh",
        scale_embeddings=True,
        rmsnorm_offset=True,
    ),
    "mixtral-8x7b": ModelConfig(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=8,
        rms_norm_eps=1e-5,
        rope_theta=1000000.0,
        max_position_embeddings=32768,
        num_local_experts=8,
        num_experts_per_tok=2,
    ),
    "llama-3-8b": ModelConfig(
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=8,
        rope_theta=500000.0,
        max_position_embeddings=8192,
    ),
    "llama-3-70b": ModelConfig(
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_hidden_layers=80,
        num_attention_heads=64,
        num_key_value_heads=8,
        rope_theta=500000.0,
        max_position_embeddings=8192,
    ),
}
