"""Sharding for the native runtime: TP param specs + SP forward.

Tensor parallel (the reference's ``--tensor-parallel-size`` is a
pass-through flag to external vLLM, vllm.go:57-61; here TP is real):
attention heads and ffn columns shard over the ``tp`` mesh axis. With
column-parallel (q/k/v/gate/up) then row-parallel (o/down) weights, the
only collectives GSPMD must insert are the two per-block psums of the
standard Megatron layout — we annotate the params and let the partitioner
do exactly that (scaling-book recipe: annotate, don't hand-schedule).

Sequence parallel: ``forward_sequence_parallel`` runs the whole decoder
under ``shard_map`` with the sequence axis sharded over ``sp``, swapping
the dense attention for ring attention (ring_attention.py). Weights are
replicated across ``sp``; activations never materialize the full
sequence on one device — this is the long-context path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from kubeinfer_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.model import Params, forward
from kubeinfer_tpu.inference.ring_attention import ring_attention


def make_inference_mesh(
    tp: int = 1, sp: int = 1, dp: int | None = None
) -> Mesh:
    """(dp, tp, sp) mesh over the available devices (dp fills the rest)."""
    import numpy as np

    devices = jax.devices()
    if dp is None:
        dp = len(devices) // (tp * sp)
    n = dp * tp * sp
    if n > len(devices) or n < 1:
        raise ValueError(
            f"mesh dp={dp} tp={tp} sp={sp} needs {n} devices, have "
            f"{len(devices)}"
        )
    return Mesh(
        np.asarray(devices[:n]).reshape(dp, tp, sp),
        axis_names=("dp", "tp", "sp"),
    )


def make_axis_mesh(axis_name: str, n: int) -> Mesh:
    """1-D mesh over the first ``n`` devices (shared by the pp/ep
    constructors — one place for device-count checks and, later, any
    ICI-locality device ordering)."""
    import numpy as np

    devices = jax.devices()
    if n > len(devices):
        raise ValueError(
            f"{axis_name}={n} needs {n} devices, have {len(devices)}"
        )
    return Mesh(np.asarray(devices[:n]).reshape(n), axis_names=(axis_name,))


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpec tree matching init_params' layout (Megatron TP)."""
    layer = {
        "input_layernorm": P(),
        "post_attention_layernorm": P(),
        "q_proj": P(None, "tp"),  # column parallel: heads shard
        "k_proj": P(None, "tp"),
        "v_proj": P(None, "tp"),
        "o_proj": P("tp", None),  # row parallel: psum after
    }
    if cfg.num_local_experts > 0:
        # Mixtral family under TP: every expert's ffn shards exactly like
        # the dense mlp (column-parallel gate/up, row-parallel down) with
        # the expert-stacked leading axis replicated; expert parallelism
        # over an ``ep`` axis is the separate moe.moe_block_ep path.
        layer["moe"] = {
            "router": P(),
            "gate_proj": P(None, None, "tp"),
            "up_proj": P(None, None, "tp"),
            "down_proj": P(None, "tp", None),
        }
    else:
        layer["gate_proj"] = P(None, "tp")
        layer["up_proj"] = P(None, "tp")
        layer["down_proj"] = P("tp", None)
    if cfg.qkv_bias:  # biases follow their projection's output sharding
        layer["q_bias"] = P("tp")
        layer["k_bias"] = P("tp")
        layer["v_bias"] = P("tp")
    return {
        "embed_tokens": P(None, None),  # replicated (small vs the ffn)
        "layers": [layer] * cfg.num_hidden_layers,
        "norm": P(),
        "lm_head": P(None, "tp"),  # vocab-sharded logits
    }


def shard_params(params: Params, mesh: Mesh, cfg: ModelConfig) -> Params:
    """Place a param pytree onto the mesh per param_specs."""
    specs = param_specs(cfg)
    if "lm_head" not in params:
        specs = dict(specs)
        specs.pop("lm_head")
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def forward_tensor_parallel(
    params: Params, tokens: jax.Array, cfg: ModelConfig, mesh: Mesh
) -> jax.Array:
    """Jit the standard forward with TP-sharded params; GSPMD inserts the
    Megatron psums. ``params`` should already be placed (shard_params) —
    then this is zero-copy; unplaced params are placed on trace."""

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def fwd(p, t, cfg: ModelConfig):
        from kubeinfer_tpu.inference.model import attention

        # attn_fn pinned to the dense einsum path: GSPMD partitions
        # einsums across the mesh, but the default forward's causal
        # flash kernel is a Pallas custom call that GSPMD cannot
        # partition — under a sharded jit it would replicate (or fail
        # to lower) instead of sharding over heads.
        out, _ = forward(p, t, cfg, attn_fn=attention)
        return jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P("dp", None, None))
        )

    tokens = jax.device_put(
        tokens, NamedSharding(mesh, P("dp", None))
    )
    return fwd(shard_params(params, mesh, cfg), tokens, cfg)


def forward_sequence_parallel(
    params: Params, tokens: jax.Array, cfg: ModelConfig, mesh: Mesh
) -> jax.Array:
    """Causal LM forward with the SEQUENCE axis sharded over ``sp``.

    The full decoder body runs per-shard under shard_map (pointwise over
    T except attention, which is the ring). RoPE positions are global:
    each shard computes them from its axis index. T must divide by the
    sp axis size.
    """
    B, T = tokens.shape
    sp = mesh.shape["sp"]
    if T % sp:
        raise ValueError(f"sequence length {T} must divide by sp={sp}")
    T_loc = T // sp

    def body(p, t_local):
        r = jax.lax.axis_index("sp")
        positions = (
            r * T_loc + jnp.arange(T_loc, dtype=jnp.int32)[None, :]
        )
        positions = jnp.broadcast_to(positions, t_local.shape)

        def ring_fn(q, k, v, mask):  # model's mask is local-only: ignore;
            # causality comes from global positions inside the ring
            del mask
            return ring_attention(q, k, v, axis_name="sp")

        # the local mask arg is unused by ring_fn but must have the
        # local shape for the (ignored) broadcast in attention()'s twin
        local_mask = jnp.ones((t_local.shape[0], T_loc, T_loc), bool)
        out, _ = forward(
            p, t_local, cfg, positions=positions, attn_mask=local_mask,
            attn_fn=ring_fn,
        )
        return out

    shard_fwd = jax.jit(
        shard_map(
            functools.partial(body),
            mesh=mesh,
            in_specs=(param_specs_replicated(cfg, params), P(None, "sp")),
            out_specs=P(None, "sp", None),
        )
    )
    return shard_fwd(params, tokens)


def param_specs_replicated(cfg: ModelConfig, params: Params) -> Params:
    """All-replicated spec tree (shard_map in_specs for the SP path)."""
    specs = jax.tree.map(lambda _: P(), params)
    return specs
