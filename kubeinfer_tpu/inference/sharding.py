"""Sharding for the native runtime: TP param specs + SP forward +
the continuous batcher's device layout.

Tensor parallel (the reference's ``--tensor-parallel-size`` is a
pass-through flag to external vLLM, vllm.go:57-61; here TP is real):
attention heads and ffn columns shard over the ``tp`` mesh axis. With
column-parallel (q/k/v/gate/up) then row-parallel (o/down) weights, the
only collectives GSPMD must insert are the two per-block psums of the
standard Megatron layout — we annotate the params and let the partitioner
do exactly that (scaling-book recipe: annotate, don't hand-schedule).

:class:`EngineLayout` extends the same recipe to the serving engine's
paged state: params per :func:`param_specs`, the shared KV block pool
``[num_blocks, block_size, n_kv, D]`` sharded along ``n_kv`` (each
device holds its own heads' slice of EVERY block — block indices stay
logical and host bookkeeping never sees the layout), everything else
replicated. The engine's jits (admit, chunk, decode window) take the
placed arrays and GSPMD propagates — one extra compiled executable per
layout, no trace changes.

Sequence parallel: ``forward_sequence_parallel`` runs the whole decoder
under ``shard_map`` with the sequence axis sharded over ``sp``, swapping
the dense attention for ring attention (ring_attention.py). Weights are
replicated across ``sp``; activations never materialize the full
sequence on one device — this is the long-context path.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from kubeinfer_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.model import Params, forward
from kubeinfer_tpu.inference.ring_attention import ring_attention


def order_devices_ici(devices) -> list:
    """Devices reordered along a boustrophedon walk of the chip grid so
    consecutive ranks are ICI neighbors (the ordering make_axis_mesh's
    docstring deferred).

    ``jax.devices()`` enumerates TPU chips in row-major coordinate
    order, so the wrap from the end of one row to the start of the next
    puts consecutive mesh ranks on chips a full row apart — every
    collective then pays a multi-hop detour on exactly the axis that is
    supposed to be latency-critical. The snake walk flips direction on
    alternate rows (and alternate planes, for 3D slices), keeping every
    consecutive pair one ICI hop apart; cores on the same chip sort
    adjacent, which is tighter still. Devices without chip coords
    (CPU/virtual meshes, the 8-device test mesh) keep their enumeration
    order — on those platforms there is no topology to respect and the
    stable order keeps layouts reproducible.
    """
    coords = [getattr(d, "coords", None) for d in devices]
    if any(c is None for c in coords):
        return list(devices)
    sizes = [max(c[i] for c in coords) + 1 for i in range(len(coords[0]))]

    def snake_rank(c) -> int:
        # walk dims slowest-to-fastest (TPU coords are (x, y, z): z is
        # the slowest axis); a dim entered at an odd index reverses the
        # next-faster dim, which is what makes row ends adjacent
        rank, flip = 0, False
        for i in reversed(range(len(sizes))):
            v = (sizes[i] - 1 - c[i]) if flip else c[i]
            rank = rank * sizes[i] + v
            flip = (v % 2) == 1
        return rank

    return sorted(
        devices,
        key=lambda d: (snake_rank(d.coords),
                       getattr(d, "core_on_chip", 0)),
    )


def mesh_device_array(devices, dp: int, tp: int, sp: int):
    """ICI-ordered ``(dp, tp, sp)`` device array with ``tp`` ranks
    adjacent on the physical chain.

    A plain ``reshape(dp, tp, sp)`` makes ``sp`` the fastest-varying
    axis; filling ``(dp, sp, tp)`` and transposing instead puts
    consecutive ``tp`` ranks on consecutive chain positions — the tp
    axis carries the per-layer Megatron psums (two per block, every
    step), while sp/dp collectives are per-request-scale, so tp gets
    the single-hop neighbors. When sp == 1 the transpose is the
    identity and the array matches the historical layout exactly.
    Factored from make_inference_mesh so topology tests can drive it
    with fake devices.
    """
    import numpy as np

    ordered = order_devices_ici(devices)[: dp * tp * sp]
    return np.asarray(ordered).reshape(dp, sp, tp).transpose(0, 2, 1)


def make_inference_mesh(
    tp: int = 1, sp: int = 1, dp: int | None = None
) -> Mesh:
    """(dp, tp, sp) mesh over the available devices (dp fills the rest),
    ICI-ordered so adjacent tp ranks sit on adjacent devices
    (order_devices_ici / mesh_device_array)."""
    devices = jax.devices()
    if dp is None:
        dp = len(devices) // (tp * sp)
    n = dp * tp * sp
    if n > len(devices) or n < 1:
        raise ValueError(
            f"mesh dp={dp} tp={tp} sp={sp} needs {n} devices, have "
            f"{len(devices)}"
        )
    return Mesh(
        mesh_device_array(devices, dp, tp, sp),
        axis_names=("dp", "tp", "sp"),
    )


def make_axis_mesh(axis_name: str, n: int) -> Mesh:
    """1-D mesh over the first ``n`` devices in ICI order (shared by the
    pp/ep constructors — one place for device-count checks and the
    locality ordering)."""
    import numpy as np

    devices = jax.devices()
    if n > len(devices):
        raise ValueError(
            f"{axis_name}={n} needs {n} devices, have {len(devices)}"
        )
    return Mesh(
        np.asarray(order_devices_ici(devices)[:n]).reshape(n),
        axis_names=(axis_name,),
    )


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpec tree matching init_params' layout (Megatron TP)."""
    layer = {
        "input_layernorm": P(),
        "post_attention_layernorm": P(),
        "q_proj": P(None, "tp"),  # column parallel: heads shard
        "k_proj": P(None, "tp"),
        "v_proj": P(None, "tp"),
        "o_proj": P("tp", None),  # row parallel: psum after
    }
    if cfg.num_local_experts > 0:
        # Mixtral family under TP: every expert's ffn shards exactly like
        # the dense mlp (column-parallel gate/up, row-parallel down) with
        # the expert-stacked leading axis replicated; expert parallelism
        # over an ``ep`` axis is the separate moe.moe_block_ep path.
        layer["moe"] = {
            "router": P(),
            "gate_proj": P(None, None, "tp"),
            "up_proj": P(None, None, "tp"),
            "down_proj": P(None, "tp", None),
        }
    else:
        layer["gate_proj"] = P(None, "tp")
        layer["up_proj"] = P(None, "tp")
        layer["down_proj"] = P("tp", None)
    if cfg.qkv_bias:  # biases follow their projection's output sharding
        layer["q_bias"] = P("tp")
        layer["k_bias"] = P("tp")
        layer["v_bias"] = P("tp")
    return {
        "embed_tokens": P(None, None),  # replicated (small vs the ffn)
        "layers": [layer] * cfg.num_hidden_layers,
        "norm": P(),
        "lm_head": P(None, "tp"),  # vocab-sharded logits
    }


def expand_quant_specs(specs: Params, params: Params) -> Params:
    """Grow a param_specs tree to match weight-quantized leaves: where
    ``params`` carries a quant dict, the weight's spec applies to the
    int8 codes and the f32 scale plane shards along the weight's OUT
    axis (per-column storage, so there is no tile/tp divisibility
    coupling). Placement only — no new programs, same as the rest of
    the TP layout. Uses tree.map's prefix rule: ``specs`` is a prefix
    of ``params``, so a P leaf meets the whole quant subtree."""

    def one(spec, leaf):
        if isinstance(leaf, dict) and "qw" in leaf:
            out_axis = spec[-1] if len(spec) else None
            return {"qw": spec, "scale": P(out_axis)}
        return spec

    return jax.tree.map(one, specs, params)


def shard_params(params: Params, mesh: Mesh, cfg: ModelConfig) -> Params:
    """Place a param pytree onto the mesh per param_specs."""
    specs = param_specs(cfg)
    if "lm_head" not in params:
        specs = dict(specs)
        specs.pop("lm_head")
    specs = expand_quant_specs(specs, params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


@dataclasses.dataclass(frozen=True)
class EngineLayout:
    """Device layout of the continuous batcher: mesh + placements for
    params, the paged KV pool, and the rest of the slot state.

    ``tp == 1`` is the degenerate single-device layout: no mesh exists,
    ``shard_params``/``shard_state`` return their inputs untouched, and
    the engine is byte-for-byte the pre-sharding engine — same arrays,
    same traces, same compile cache. Under ``tp > 1`` the layout only
    PLACES arrays; it never rewrites the engine's programs. Params
    follow :func:`param_specs` (Megatron column/row parallel), the
    per-layer pool ``[num_blocks, block_size, n_kv, D]`` shards along
    ``n_kv`` (dim 2), and every other SlotState leaf — block tables,
    sampling knobs, PRNG keys — replicates. Because the ``num_blocks``
    axis is whole on every device, the host's i32 block tables resolve
    per-device KV shards unchanged: a table entry names the same
    logical block everywhere, each device just gathers/scatters its own
    heads' slice of it. That is the whole reason BlockPool/RadixCache
    never learn about the layout.

    Token parity with tp=1 is by dominance, not bit-exactness of the
    logits: GSPMD's psum reduces partial products in a different order
    than the unsharded contraction, so logits can differ in the last
    ulps — but the sampling noise is position-folded (identical across
    layouts) and argmax/gumbel-pick decisions ride logit GAPS, which
    the parity suite pins greedy and sampled across admits, windows,
    and preemption cycles.
    """

    tp: int = 1
    mesh: Mesh | None = None

    def __post_init__(self) -> None:
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if (self.mesh is None) != (self.tp == 1):
            raise ValueError(
                "EngineLayout carries a mesh exactly when tp > 1 "
                f"(tp={self.tp}, mesh={'set' if self.mesh else 'None'})"
            )

    @classmethod
    def build(cls, tp: int = 1) -> "EngineLayout":
        """The CLI/bench constructor: tp=1 stays meshless (zero
        behavior change), tp>1 builds the ICI-ordered serving mesh."""
        if tp <= 1:
            return cls()
        return cls(tp=tp, mesh=make_inference_mesh(tp=tp, sp=1, dp=1))

    @property
    def sharded(self) -> bool:
        return self.mesh is not None

    @property
    def mesh_devices(self) -> int:
        """Device count under the layout (1 when unsharded) — what the
        kubeinfer_mesh_devices gauge reports."""
        return 1 if self.mesh is None else self.mesh.size

    def check_model(self, cfg: ModelConfig) -> None:
        """Divisibility the layout needs: every device must own whole
        heads. n_kv % tp == 0 keeps the pool shards real (a device with
        zero KV heads would still pay every collective); GQA ratios
        where n_kv == tp (one KV head per device) are the floor."""
        if not self.sharded:
            return
        if cfg.num_attention_heads % self.tp:
            raise ValueError(
                f"tp={self.tp} must divide num_attention_heads="
                f"{cfg.num_attention_heads}"
            )
        if cfg.num_key_value_heads % self.tp:
            raise ValueError(
                f"tp={self.tp} must divide num_key_value_heads="
                f"{cfg.num_key_value_heads} (KV pool shards along n_kv)"
            )

    def shard_params(self, params: Params, cfg: ModelConfig) -> Params:
        """Place params per param_specs; identity when unsharded."""
        if not self.sharded:
            return params
        return shard_params(params, self.mesh, cfg)

    def pool_sharding(self) -> NamedSharding:
        """[num_blocks, block_size, n_kv, D]: heads shard, blocks stay
        whole per device so logical table indices resolve everywhere."""
        return NamedSharding(self.mesh, P(None, None, "tp", None))

    def scale_sharding(self) -> NamedSharding:
        """[num_blocks, n_kv] int8 dequant scales: shard along n_kv
        exactly like the pool — each device holds its own heads'
        scales for EVERY block, so the kernel's scale prefetch never
        crosses devices."""
        return NamedSharding(self.mesh, P(None, "tp"))

    def tail_sharding(self) -> NamedSharding:
        """[n_slots, 2, block_size, n_kv, D] bf16 tail pairs: n_kv
        shards with the pool (dim 3); slots and the 2-slot tail axis
        stay whole per device."""
        return NamedSharding(self.mesh, P(None, None, None, "tp", None))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_state(self, state):
        """Place a stepper.SlotState; identity when unsharded. The
        placement is the jit contract: decode_window/_admit_slot donate
        this pytree, and jax compiles one executable per distinct input
        sharding — which is exactly the one-shape-per-(bucket, layout)
        discipline the profiler pins."""
        if not self.sharded:
            return state
        pool = self.pool_sharding()
        scale = self.scale_sharding()
        tail = self.tail_sharding()
        rep = self.replicated()
        kv_names = ("caches_k", "caches_v", "scales_k", "scales_v",
                    "tails_k", "tails_v")
        placed = {
            f.name: jax.device_put(getattr(state, f.name), rep)
            for f in dataclasses.fields(state)
            if f.name not in kv_names
        }
        return dataclasses.replace(
            state,
            caches_k=[jax.device_put(c, pool) for c in state.caches_k],
            caches_v=[jax.device_put(c, pool) for c in state.caches_v],
            scales_k=[jax.device_put(s, scale) for s in state.scales_k],
            scales_v=[jax.device_put(s, scale) for s in state.scales_v],
            tails_k=[jax.device_put(t, tail) for t in state.tails_k],
            tails_v=[jax.device_put(t, tail) for t in state.tails_v],
            **placed,
        )


def forward_tensor_parallel(
    params: Params, tokens: jax.Array, cfg: ModelConfig, mesh: Mesh
) -> jax.Array:
    """Jit the standard forward with TP-sharded params; GSPMD inserts the
    Megatron psums. ``params`` should already be placed (shard_params) —
    then this is zero-copy; unplaced params are placed on trace."""

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def fwd(p, t, cfg: ModelConfig):
        from kubeinfer_tpu.inference.model import attention

        # attn_fn pinned to the dense einsum path: GSPMD partitions
        # einsums across the mesh, but the default forward's causal
        # flash kernel is a Pallas custom call that GSPMD cannot
        # partition — under a sharded jit it would replicate (or fail
        # to lower) instead of sharding over heads.
        out, _ = forward(p, t, cfg, attn_fn=attention)
        return jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P("dp", None, None))
        )

    tokens = jax.device_put(
        tokens, NamedSharding(mesh, P("dp", None))
    )
    return fwd(shard_params(params, mesh, cfg), tokens, cfg)


def forward_sequence_parallel(
    params: Params, tokens: jax.Array, cfg: ModelConfig, mesh: Mesh
) -> jax.Array:
    """Causal LM forward with the SEQUENCE axis sharded over ``sp``.

    The full decoder body runs per-shard under shard_map (pointwise over
    T except attention, which is the ring). RoPE positions are global:
    each shard computes them from its axis index. T must divide by the
    sp axis size.
    """
    B, T = tokens.shape
    sp = mesh.shape["sp"]
    if T % sp:
        raise ValueError(f"sequence length {T} must divide by sp={sp}")
    T_loc = T // sp

    def body(p, t_local):
        r = jax.lax.axis_index("sp")
        positions = (
            r * T_loc + jnp.arange(T_loc, dtype=jnp.int32)[None, :]
        )
        positions = jnp.broadcast_to(positions, t_local.shape)

        def ring_fn(q, k, v, mask):  # model's mask is local-only: ignore;
            # causality comes from global positions inside the ring
            del mask
            return ring_attention(q, k, v, axis_name="sp")

        # the local mask arg is unused by ring_fn but must have the
        # local shape for the (ignored) broadcast in attention()'s twin
        local_mask = jnp.ones((t_local.shape[0], T_loc, T_loc), bool)
        out, _ = forward(
            p, t_local, cfg, positions=positions, attn_mask=local_mask,
            attn_fn=ring_fn,
        )
        return out

    shard_fwd = jax.jit(
        shard_map(
            functools.partial(body),
            mesh=mesh,
            in_specs=(param_specs_replicated(cfg, params), P(None, "sp")),
            out_specs=P(None, "sp", None),
        )
    )
    return shard_fwd(params, tokens)


def param_specs_replicated(cfg: ModelConfig, params: Params) -> Params:
    """All-replicated spec tree (shard_map in_specs for the SP path)."""
    specs = jax.tree.map(lambda _: P(), params)
    return specs
