"""Decoder-only transformer (llama family) in pure JAX.

TPU-first choices:

- **bf16 params, f32 accumulations where it matters** (RMSNorm stats and
  attention softmax run in f32; matmuls feed the MXU in bf16 by default).
- **Static shapes everywhere**: the forward takes [B, T] tokens plus an
  explicit position offset so the same compiled function serves prefill
  (T = padded prompt) and decode (T = 1) with a KV cache.
- **No module framework**: params are a plain pytree of jnp arrays with
  HF-compatible naming (weights.py maps safetensors 1:1), so sharding is
  a tree_map of PartitionSpecs (sharding.py) and checkpoints need no
  object graph.

Numerical parity with ``transformers`` LlamaForCausalLM is pinned by
tests/test_inference_model.py (same weights → logits within bf16/f32
tolerance).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.weight_quant import quantize_layer, wq_dot

Params = dict[str, Any]


# --- initialization --------------------------------------------------------


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype=jnp.float32,
    weight_dtype: str = "bf16",
) -> Params:
    """Random init (normal, 0.02 std — HF default) with HF tree layout.

    ``weight_dtype="int8"`` quantizes each layer's projection leaves as
    it is built (weight_quant.quantize_layer), mirroring the load-time
    path in weights.params_from_state_dict — the full-precision layer
    never outlives the loop iteration. "bf16" (the dtype axis name, not
    a cast — ``dtype`` still controls precision) leaves the tree
    byte-identical to the pre-quantization layout."""
    if weight_dtype not in ("bf16", "int8"):
        raise ValueError(f"weight_dtype must be bf16|int8: {weight_dtype!r}")
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense(k, shape):
        return (0.02 * jax.random.normal(k, shape, jnp.float32)).astype(dtype)

    H, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    kv_dim = cfg.num_key_value_heads * cfg.head_dim
    q_dim = cfg.num_attention_heads * cfg.head_dim
    # gemma stores norm weights as a zero-init offset from gain 1
    norm_init = jnp.zeros if cfg.rmsnorm_offset else jnp.ones
    layers = []
    for i in range(cfg.num_hidden_layers):
        ks = jax.random.split(jax.random.fold_in(k_layers, i), 7)
        layer = {
            "input_layernorm": norm_init((H,), dtype),
            "post_attention_layernorm": norm_init((H,), dtype),
            # weights stored [in, out] (transposed vs torch Linear) so
            # the forward is x @ W with no per-call transpose
            # q/o are [H, heads*head_dim] RECTANGLES when head_dim is
            # overridden (gemma-7b); square for every derived-head family
            "q_proj": dense(ks[0], (H, q_dim)),
            "k_proj": dense(ks[1], (H, kv_dim)),
            "v_proj": dense(ks[2], (H, kv_dim)),
            "o_proj": dense(ks[3], (q_dim, H)),
        }
        if cfg.num_local_experts > 0:  # Mixtral family: routed MLP
            from kubeinfer_tpu.inference.moe import init_moe_params

            layer["moe"] = init_moe_params(
                jax.random.fold_in(ks[4], 1), H, F,
                cfg.num_local_experts, dtype=dtype,
            )
        else:
            layer["gate_proj"] = dense(ks[4], (H, F))
            layer["up_proj"] = dense(ks[5], (H, F))
            layer["down_proj"] = dense(ks[6], (F, H))
        if cfg.qkv_bias:  # Qwen2 family
            layer["q_bias"] = jnp.zeros((q_dim,), dtype)
            layer["k_bias"] = jnp.zeros((kv_dim,), dtype)
            layer["v_bias"] = jnp.zeros((kv_dim,), dtype)
        if weight_dtype == "int8":
            layer = quantize_layer(layer)
        layers.append(layer)
    params: Params = {
        "embed_tokens": dense(k_embed, (V, H)),
        "layers": layers,
        "norm": norm_init((H,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense(k_head, (H, V))
    return params


def layer_param_template(cfg: ModelConfig) -> dict:
    """Structure-only pytree of ONE decoder layer (None leaves).

    The single source of truth for which keys a layer carries per config
    (dense vs moe mlp, qkv biases); spec builders that cannot afford to
    materialize real params (pipeline.py's stage specs — a mixtral-8x7b
    init is tens of GB) tree.map over this instead of hardcoding key
    lists, which silently breaks when a family adds keys (r2 review
    finding: pp crashed on moe/bias layers).
    """
    layer: dict = {
        "input_layernorm": None,
        "post_attention_layernorm": None,
        "q_proj": None,
        "k_proj": None,
        "v_proj": None,
        "o_proj": None,
    }
    if cfg.num_local_experts > 0:
        layer["moe"] = {
            "router": None,
            "gate_proj": None,
            "up_proj": None,
            "down_proj": None,
        }
    else:
        layer["gate_proj"] = None
        layer["up_proj"] = None
        layer["down_proj"] = None
    if cfg.qkv_bias:
        layer["q_bias"] = None
        layer["k_bias"] = None
        layer["v_bias"] = None
    return layer


# --- building blocks -------------------------------------------------------


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, offset: bool = False
) -> jax.Array:
    """RMSNorm with f32 statistics regardless of activation dtype.

    ``offset`` selects the Gemma convention: the stored weight is a
    zero-init delta and the gain is (1 + w) — folding it into the weight
    at load time would silently corrupt checkpoints saved back out, so
    the convention is applied at compute time.
    """
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    w = weight.astype(jnp.float32)
    if offset:
        w = 1.0 + w
    return ((xf * scale) * w).astype(x.dtype)


def _mlp_act(cfg: ModelConfig):
    """The gated-MLP activation for this family: llama/qwen2/mixtral use
    SwiGLU (silu); gemma uses the tanh-approximate GeGLU
    ("gelu_pytorch_tanh" — exactly jax.nn.gelu(approximate=True))."""
    if cfg.hidden_act == "silu":
        return jax.nn.silu
    if cfg.hidden_act == "gelu_pytorch_tanh":
        return lambda x: jax.nn.gelu(x, approximate=True)
    raise ValueError(f"unsupported hidden_act {cfg.hidden_act!r}")


def rope_tables(
    positions: jax.Array, head_dim: int, theta: float
) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) tables for rotary embeddings at given positions [B, T]."""
    inv_freq = 1.0 / (
        theta
        ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B,T,D/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate [B, T, heads, head_dim] by position tables [B, T, head_dim/2].

    HF llama convention: the head dim is split into halves (x1 = first
    half, x2 = second half), not interleaved pairs.
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def attention(
    q: jax.Array,  # [B, T, n_heads, D]
    k: jax.Array,  # [B, S, n_kv, D]
    v: jax.Array,  # [B, S, n_kv, D]
    mask: jax.Array,  # bool[B, T, S] True = attend
) -> jax.Array:
    """GQA scaled-dot-product attention, f32 softmax, [B, T, n_heads, D]."""
    B, T, n_heads, D = q.shape
    n_kv = k.shape[2]
    group = n_heads // n_kv
    # fold heads into kv groups: [B, T, n_kv, group, D]
    qg = q.reshape(B, T, n_kv, group, D)
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(D))
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(B, T, n_heads, D)


def decoder_layer(
    layer: Params,
    x: jax.Array,  # [B, T, H]
    cos: jax.Array,
    sin: jax.Array,
    mask: jax.Array,
    cfg: ModelConfig,
    kv_cache: tuple[jax.Array, jax.Array] | None = None,
    cache_offset: jax.Array | int = 0,
    attn_fn=attention,
    tp_axis: str | None = None,
    tp_size: int = 1,
    block_tables: jax.Array | None = None,  # i32[B, max_blocks] paged write
    wq_gspmd: bool = False,
) -> tuple[jax.Array, tuple[jax.Array, jax.Array] | None]:
    """One pre-norm block; returns (x, updated kv cache or None).

    Projection matmuls route through weight_quant.wq_dot so a layer
    whose leaves are quantized dicts rides the fused dequant-matmul;
    plain leaves take the literal ``x @ w`` (identical trace to the
    pre-quantization engine). ``wq_gspmd`` pins the dense dequant route
    under GSPMD sharding — the same custom-call constraint as the
    attention kernels.

    ``block_tables`` switches the cache write to the paged layout: the
    cache operands are then the POOL tensors [num_blocks, block_size,
    n_kv, D] shared across rows, and row b's token at logical position
    ``cache_offset[b]`` lands in block ``block_tables[b, off // bs]``
    at slot ``off % bs``. Decode-only (T == 1 with per-row offsets) —
    prefill into the pool goes through the engine's gather/scatter
    admit step, not through here. The paired ``attn_fn`` must read the
    pool through the same tables (batching wires
    decode_attention_blocks_auto).

    ``tp_axis``/``tp_size`` run the block in MANUAL tensor parallelism
    (inside a shard_map with Megatron-sharded weights,
    sharding.param_specs): projections arrive column-sharded so this
    device computes heads/tp_size attention heads and F/tp_size mlp
    lanes, and the two row-parallel contractions (o_proj, down_proj /
    moe down) psum over ``tp_axis``. The GSPMD path (jit + sharded
    params) needs none of this — the compiler inserts the same psums —
    but shard_map bodies (the sequence-parallel ring) see local shards
    and must say the collectives out loud.
    """
    B, T, H = x.shape
    D = cfg.head_dim
    n_q = cfg.num_attention_heads // tp_size
    n_kv = cfg.num_key_value_heads // tp_size
    h = rms_norm(
        x, layer["input_layernorm"], cfg.rms_norm_eps,
        offset=cfg.rmsnorm_offset,
    )
    q = wq_dot(h, layer["q_proj"], gspmd=wq_gspmd)
    k = wq_dot(h, layer["k_proj"], gspmd=wq_gspmd)
    v = wq_dot(h, layer["v_proj"], gspmd=wq_gspmd)
    if cfg.qkv_bias:  # Qwen2 family; o_proj stays bias-free
        q = q + layer["q_bias"]
        k = k + layer["k_bias"]
        v = v + layer["v_bias"]
    q = q.reshape(B, T, n_q, D)
    k = k.reshape(B, T, n_kv, D)
    v = v.reshape(B, T, n_kv, D)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if kv_cache is not None:
        ck, cv = kv_cache
        if block_tables is not None:
            if getattr(cache_offset, "ndim", 0) != 1:
                raise ValueError(
                    "block_tables requires per-row cache_offset "
                    "(prefill writes go through the engine's paged "
                    "admit, not decoder_layer)"
                )
            if isinstance(ck, tuple):
                # quantized pool: the cache entry is (int8 pages,
                # scales, bf16 tails). Fresh K/V lands in the per-slot
                # TAIL, never the pool — quantize-on-commit happens at
                # the window boundary (stepper._commit_full_tails), so
                # a partial block never round-trips through int8. Tail
                # slot rel = pos//bs - offset//bs is 0 or 1: the window
                # writes at most T <= k + 1 < block_size positions, so
                # one boundary crossing max. Inactive rows scribble
                # into their OWN tail slots — harmless, (re)admit
                # rewrites them.
                kq, ks, ktail = ck
                vq, vs, vtail = cv
                bs = kq.shape[1]
                rows = jnp.arange(block_tables.shape[0])
                if T == 1:
                    # rel is identically 0: the tail was pinned to
                    # offset // bs at window start
                    ktail = ktail.at[rows, 0, cache_offset % bs].set(
                        k[:, 0])
                    vtail = vtail.at[rows, 0, cache_offset % bs].set(
                        v[:, 0])
                else:
                    pos = cache_offset[:, None] + jnp.arange(T)
                    rel = pos // bs - (cache_offset // bs)[:, None]
                    ktail = ktail.at[
                        rows[:, None], rel, pos % bs].set(k)
                    vtail = vtail.at[
                        rows[:, None], rel, pos % bs].set(v)
                # repack and fall through: the quantized attn_fn
                # unpacks the triple, and the epilogue below is
                # dtype-agnostic
                ck = (kq, ks, ktail)
                cv = (vq, vs, vtail)
            else:
                # paged decode write: one batched scatter into the
                # pool. Rows of a retired slot carry an all-null
                # table, so their write lands in the sacrificial block
                # 0 — duplicate indices there make block 0's content
                # nondeterministic, which is fine because nothing ever
                # attends to it.
                bs = ck.shape[1]
                rows = jnp.arange(block_tables.shape[0])
                if T == 1:
                    blk = block_tables[rows, cache_offset // bs]
                    ck = ck.at[blk, cache_offset % bs].set(k[:, 0])
                    cv = cv.at[blk, cache_offset % bs].set(v[:, 0])
                else:
                    # speculative verify window: row b writes its T
                    # tokens at contiguous logical positions
                    # cache_offset[b] + t. Within a live row the
                    # (block, slot) pairs are distinct; cross-row
                    # collisions happen only on the null block 0
                    # above, so scatter order never matters for
                    # anything attended to.
                    pos = cache_offset[:, None] + jnp.arange(T)
                    blk = block_tables[rows[:, None], pos // bs]
                    ck = ck.at[blk, pos % bs].set(k)
                    cv = cv.at[blk, pos % bs].set(v)
        elif getattr(cache_offset, "ndim", 0) == 1:
            # per-row offsets (continuous-batching / ragged decode:
            # rows at different sequence positions in one dispatch)
            if T == 1:
                # decode writes one token per row: a batched scatter
                # lowers to a single fused scatter instead of the
                # vmapped DUS's per-row gather/update chain — same
                # values, so the vmap branch's exactness tests cover it
                rows = jnp.arange(ck.shape[0])
                ck = ck.at[rows, cache_offset].set(k[:, 0])
                cv = cv.at[rows, cache_offset].set(v[:, 0])
            else:

                def row_update(cache, new):
                    return jax.vmap(
                        lambda c, n, o: jax.lax.dynamic_update_slice(
                            c, n, (o, 0, 0)
                        )
                    )(cache, new, cache_offset)

                ck = row_update(ck, k)
                cv = row_update(cv, v)
        else:
            ck = jax.lax.dynamic_update_slice(ck, k, (0, cache_offset, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, cache_offset, 0, 0))
        k, v = ck, cv
        kv_cache = (ck, cv)

    attn = attn_fn(q, k, v, mask)
    attn_out = wq_dot(
        attn.reshape(B, T, n_q * D), layer["o_proj"], gspmd=wq_gspmd
    )
    if tp_axis is not None:
        # row-parallel epilogue: each device contracted its own heads
        attn_out = jax.lax.psum(attn_out, tp_axis)
    x = x + attn_out

    h = rms_norm(
        x, layer["post_attention_layernorm"], cfg.rms_norm_eps,
        offset=cfg.rmsnorm_offset,
    )
    if "moe" in layer:  # Mixtral family (static: pytree structure)
        from kubeinfer_tpu.inference.moe import moe_block

        m = moe_block(layer["moe"], h, top_k=cfg.num_experts_per_tok)
        if tp_axis is not None:
            # experts shard like the dense mlp (param_specs): each
            # device holds every expert's F/tp lanes; the router sees
            # replicated h, so gating is identical across devices and
            # one psum after the expert-weighted sum completes the
            # row-parallel down contraction
            m = jax.lax.psum(m, tp_axis)
        x = x + m
    else:
        gate = _mlp_act(cfg)(wq_dot(h, layer["gate_proj"], gspmd=wq_gspmd))
        mlp = wq_dot(
            gate * wq_dot(h, layer["up_proj"], gspmd=wq_gspmd),
            layer["down_proj"], gspmd=wq_gspmd,
        )
        if tp_axis is not None:
            mlp = jax.lax.psum(mlp, tp_axis)
        x = x + mlp
    return x, kv_cache


# --- full forward ----------------------------------------------------------


def causal_mask(T: int, dtype=bool) -> jax.Array:
    return jnp.tril(jnp.ones((T, T), dtype))


def forward(
    params: Params,
    tokens: jax.Array,  # i32[B, T]
    cfg: ModelConfig,
    positions: jax.Array | None = None,  # i32[B, T]; default arange
    attn_mask: jax.Array | None = None,  # bool[B, T, S]
    kv_caches: list[tuple[jax.Array, jax.Array]] | None = None,
    cache_offset: jax.Array | int = 0,
    attn_fn=None,
    tp_axis: str | None = None,
    tp_size: int = 1,
    return_hidden: bool = False,
    block_tables: jax.Array | None = None,  # i32[B, max_blocks] paged write
    wq_gspmd: bool = False,
) -> tuple[jax.Array, list | None]:
    """Logits [B, T, V] (+ updated KV caches when provided).

    ``return_hidden=True`` returns the post-norm hidden states [B, T, H]
    instead of logits — prefill consumes logits at ONE position per row,
    so chunked_prefill selects the hidden row first and pays the
    full-vocab head matmul once per prompt instead of once per chunk
    token (~20% of prefill FLOPs on a 32k-vocab model, plus the [C, V]
    f32 materialization per chunk).

    ``tp_axis``/``tp_size``: manual tensor parallelism for shard_map
    bodies (see decoder_layer). The returned logits are then
    vocab-sharded [B, T, V/tp] when the model has a separate ``lm_head``
    (column-parallel per sharding.param_specs) and full-width when
    embeddings are tied (embed_tokens is replicated) — the caller's
    out_specs must match.

    ``attn_fn=None`` (the default) means auto: the plain causal no-cache
    path derives its mask in-kernel on TPU (causal_attention_auto);
    every other path gets the dense ``attention``. Pass a callable to
    pin a specific implementation.

    Without caches: plain causal self-attention over T (prefill/training).
    With caches: keys/values are written at ``cache_offset`` and attention
    runs over the full cache length (decode); ``attn_mask`` must then mask
    cache positions ≥ the true length.
    """
    B, T = tokens.shape
    if positions is None:
        base = jnp.arange(T, dtype=jnp.int32)[None, :]
        if getattr(cache_offset, "ndim", 0) == 1:
            positions = base + cache_offset[:, None]  # per-row offsets
        else:
            positions = jnp.broadcast_to(base + cache_offset, (B, T))
    if attn_mask is None:
        if kv_caches is not None:
            raise ValueError("decode with kv_caches requires attn_mask")
        attn_mask = jnp.broadcast_to(causal_mask(T)[None], (B, T, T))
        if attn_fn is None:
            # default causal forward (training / full-sequence prefill):
            # derive the mask in-kernel on TPU instead of shipping the
            # [B, T, T] tensor; the dense mask above survives only as
            # the fallback operand (DCE'd when the kernel path runs).
            # Callers that must stay on the dense einsum (e.g. GSPMD-
            # sharded jits, where a Pallas custom call cannot partition)
            # pass attn_fn=attention explicitly. Lazy import:
            # flash_attention imports this module.
            from kubeinfer_tpu.inference.flash_attention import (
                causal_attention_auto,
            )

            attn_fn = causal_attention_auto
    if attn_fn is None:
        attn_fn = attention

    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    x = params["embed_tokens"][tokens]
    if cfg.scale_embeddings:
        # Gemma scales embeddings into the residual stream; the HF
        # reference casts the sqrt(H) normalizer to the activation dtype
        # BEFORE multiplying — mirrored for checkpoint-level parity
        x = x * jnp.asarray(
            float(cfg.hidden_size) ** 0.5, x.dtype
        )
    new_caches = [] if kv_caches is not None else None
    for i, layer in enumerate(params["layers"]):
        cache = kv_caches[i] if kv_caches is not None else None
        x, cache = decoder_layer(
            layer, x, cos, sin, attn_mask, cfg,
            kv_cache=cache, cache_offset=cache_offset, attn_fn=attn_fn,
            tp_axis=tp_axis, tp_size=tp_size, block_tables=block_tables,
            wq_gspmd=wq_gspmd,
        )
        if new_caches is not None:
            new_caches.append(cache)
    x = rms_norm(
        x, params["norm"], cfg.rms_norm_eps, offset=cfg.rmsnorm_offset
    )
    if return_hidden:
        return x, new_caches
    logits = (x @ lm_head_matrix(params, cfg)).astype(jnp.float32)
    return logits, new_caches


def lm_head_matrix(params: Params, cfg: ModelConfig) -> jax.Array:
    """The [H, V] output projection (tied or separate) — one home for
    the tie_word_embeddings branch so late head application
    (chunked_prefill) cannot drift from forward's."""
    return (
        params["embed_tokens"].T
        if cfg.tie_word_embeddings
        else params["lm_head"]
    )


@partial(jax.jit, static_argnames=("cfg",))
def forward_jit(params: Params, tokens: jax.Array, cfg: ModelConfig):
    """Jitted no-cache forward (training/prefill compile target)."""
    return forward(params, tokens, cfg)[0]
