"""Training step for the native model (fine-tuning / the dryrun's full
multi-parallel step).

Plain causal-LM loss with the standard sharded-training layout: params
carry their TP PartitionSpecs (sharding.py), the batch shards over
``dp``, and XLA's partitioner inserts the gradient psums — no hand-rolled
collectives (scaling-book recipe). ``jax.checkpoint`` on the per-layer
body trades FLOPs for memory exactly where long sequences need it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.model import Params, attention, forward
from kubeinfer_tpu.inference.sharding import forward_sequence_parallel


def _nll_mean(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross entropy — the ONE copy of the loss math
    every training flavor (dense, sharded, sequence-parallel) shares,
    so they cannot silently diverge."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def _sgd(params: Params, grads: Params, lr: float) -> Params:
    """Shared SGD update (params keep their dtype and placement)."""
    return jax.tree.map(
        lambda p, g: (p - lr * g.astype(p.dtype)).astype(p.dtype),
        params, grads,
    )


def causal_lm_loss(
    params: Params, tokens: jax.Array, cfg: ModelConfig, attn_fn=None
) -> jax.Array:
    """Mean next-token cross entropy over [B, T] (targets = shift-left).

    Uses the DEFAULT forward attention binding (causal_attention_auto):
    on TPU-aligned shapes that is the flash kernel pair, now
    differentiable through its recompute-based custom_vjp
    (flash_attention.py), so a long-context train step never
    materializes the [T, T] score tensor the r3 pin forced. The GSPMD-
    sharded path (sharded_train_step) still pins the dense einsum —
    Pallas calls cannot partition under GSPMD.
    """
    logits, _ = forward(params, tokens[:, :-1], cfg, attn_fn=attn_fn)
    return _nll_mean(logits, tokens[:, 1:])


@functools.partial(
    jax.jit, static_argnames=("cfg", "attn_fn"), donate_argnums=(0,)
)
def train_step(
    params: Params, tokens: jax.Array, cfg: ModelConfig, lr: float = 1e-3,
    attn_fn=None,
) -> tuple[Params, jax.Array]:
    """One SGD step; params are donated (updated in place on device)."""
    loss, grads = jax.value_and_grad(causal_lm_loss)(
        params, tokens, cfg, attn_fn
    )
    return _sgd(params, grads, lr), loss


def sharded_train_step(mesh: Mesh, cfg: ModelConfig):
    """Jitted train step for TP-sharded params + dp-sharded batch.

    Returns ``step(params, tokens) -> (params, loss)``; place params with
    sharding.shard_params and tokens with P("dp", None) first — GSPMD
    propagates those input shardings through grads and the update, so
    updated params keep their TP placement (the donate keeps them
    in-place on device across steps). Forward psums come from the
    Megatron layout; gradient reductions over dp are inserted by the
    partitioner.
    """

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(params: Params, tokens: jax.Array):
        # dense attention pinned: a Pallas custom call cannot partition
        # under this GSPMD-sharded jit (the single-device train_step
        # default is the differentiable flash path)
        new_params, loss = train_step(params, tokens, cfg, attn_fn=attention)
        return new_params, jax.lax.with_sharding_constraint(
            loss, NamedSharding(mesh, P())
        )

    return step


def sp_causal_lm_loss(
    params: Params, tokens: jax.Array, cfg: ModelConfig, mesh: Mesh
) -> jax.Array:
    """Causal-LM loss with the SEQUENCE axis sharded over the mesh's
    ``sp`` axis — long-context training without any device ever holding
    the full sequence (or anything [T, T]-sized). The ring-attention
    forward differentiates end to end: ppermute transposes to ppermute
    under AD and the online-softmax fold is plain jnp, so no custom
    backward is needed (measured grad deltas vs the dense loss are
    ~1e-8; the parity test guards at 5e-6 absolute to absorb
    reduction-order noise across mesh shapes). tokens is [B, T+1] with
    T divisible by the sp axis size.
    """
    logits = forward_sequence_parallel(params, tokens[:, :-1], cfg, mesh)
    return _nll_mean(logits, tokens[:, 1:])


def sp_train_step(mesh: Mesh, cfg: ModelConfig, lr: float = 1e-3):
    """Jitted SGD step over the sequence-parallel loss.

    Returns ``step(params, tokens) -> (params, loss)``. Complements
    sharded_train_step (tensor/data parallel): this one scales the
    SEQUENCE dimension over ICI — the two compose at the mesh level the
    same way the serving stack's SP x TP route does.
    """

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(params: Params, tokens: jax.Array):
        loss, grads = jax.value_and_grad(sp_causal_lm_loss)(
            params, tokens, cfg, mesh
        )
        return _sgd(params, grads, lr), loss

    return step
