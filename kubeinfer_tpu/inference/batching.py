"""Continuous batching: requests join/leave a shared decode batch.

The reference delegates serving entirely to vLLM, whose headline
scheduler feature is continuous batching; this is the TPU-native
equivalent, built from static shapes:

- A fixed pool of B decode **slots**. KV lives in a shared PAGED pool:
  per-layer [num_blocks, block_size, n_kv, D] tensors plus a static
  i32[B, max_blocks] block table per slot (vLLM's PagedAttention
  layout, Kwon et al. 2023). All device state lives in one
  ``SlotState`` pytree that never changes shape; every
  allocation/refcount/free decision is host-side (kv_blocks.py),
  between device steps.
- ``stepper.decode_window`` advances EVERY active slot K tokens in ONE
  jitted call — compiled once per horizon bucket (K ∈ {1, 2, 4, 8}),
  so the per-dispatch floor is paid once per K tokens. Each fused step's
  K/V land via one batched scatter through the block tables; attention
  reads the pool through the same tables
  (flash_attention.decode_attention_blocks_auto). The scheduler picks K
  per pass (``_pick_horizon``) and overlaps its own bookkeeping with
  the in-flight window (``_plan_admissions``), syncing tokens only at
  the window boundary.
- New requests **prefill into a free slot** (compiled once per SUFFIX
  bucket) while other slots keep decoding. A host-side radix cache
  (kv_blocks.RadixCache, SGLang's RadixAttention idea) matches the
  longest full-block prompt prefix already in the pool: matched blocks
  join the slot's table by refcount bump and prefill starts at the
  matched offset, so a warm system prompt pays only its novel suffix.
  The partial tail block is never shared — it is recomputed into a
  fresh block (copy-on-write by construction).

The scheduler loop itself (admit → step → emit/retire) is plain Python
in the serving thread: decisions are O(slots) host work between device
steps, exactly the split the task brief prescribes (control flow on
host, math under jit).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import itertools
import logging
import queue
import threading
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.engine import _bucket, record_seen
from kubeinfer_tpu.inference.kv_blocks import (
    BlockPool,
    RadixCache,
    dequantize_blocks,
    prefix_fingerprints,
    quantize_blocks,
)
from kubeinfer_tpu.analysis.racecheck import guard, make_lock
from kubeinfer_tpu.inference.model import Params, forward
from kubeinfer_tpu.observability import tracing
from kubeinfer_tpu.observability.flightrecorder import FlightRecorder
from kubeinfer_tpu.observability.slo import SLOMonitor, SLOObjective
from kubeinfer_tpu.observability.stepprof import StepProfiler
from kubeinfer_tpu.inference.sharding import EngineLayout
from kubeinfer_tpu.inference.weight_quant import (
    params_weight_dtype,
    quantize_params,
)
from kubeinfer_tpu.inference.stepper import (
    DraftState,
    SlotState,
    WINDOW_BUCKETS,
    decode_window,
    init_draft_state,
    init_slot_state,
    sample_rows,
    verify_window,
)

log = logging.getLogger(__name__)

# spans are recorded retroactively from the request timeline below, so
# the scheduler never holds a live span across passes (docs/OBSERVABILITY.md)
_TRACER = tracing.get_tracer("engine")

# per-token instant events on the decode span are capped so a single
# long completion cannot dominate the span ring's memory
_MAX_TOKEN_EVENTS = 128

# pool block width (tokens). 128 keeps each block lane-aligned so the
# block-table Pallas kernel's tiles are MXU-shaped
# (flash_attention.decode_blocks_available); engines whose cache_len is
# smaller clamp down and take the gather+dense fallback.
DEFAULT_BLOCK_SIZE = 128

# --- device state ----------------------------------------------------------
# SlotState and the fused decode window live in stepper.py (ROADMAP
# item 3's unification: one stepper serves the per-request engine, the
# sequence-parallel engine, and this batcher); the admit/prefill-chunk
# dispatches below stay here — they are paged-pool plumbing the other
# engines never touch.


@functools.partial(
    jax.jit, static_argnames=("cfg", "wq_gspmd"), donate_argnums=(1,)
)
def _admit_slot(
    params: Params,
    state: SlotState,
    suffix: jax.Array,  # i32[1, T_bucket] prompt tokens from ``start`` on
    suffix_len: jax.Array,  # i32[] live tokens in ``suffix``
    start: jax.Array,  # i32[] matched-prefix length (0 = cold admit)
    prompt_len: jax.Array,  # i32[] full prompt length (= start + suffix_len)
    cfg: ModelConfig,
    slot: jax.Array,  # i32[] — traced, or admission compiles per slot
    table_row: jax.Array,  # i32[max_blocks] this slot's block table
    own_mask: jax.Array,  # bool[max_blocks] True = freshly allocated block
    temperature: jax.Array,  # f32[]
    top_k: jax.Array,  # i32[]
    top_p: jax.Array,  # f32[]
    rep_penalty: jax.Array,  # f32[]
    key_data: jax.Array,  # u32[2] per-request PRNG key data
    seen_row: jax.Array,  # bool[1, V] host-computed full-prompt id set
    wq_gspmd: bool = False,  # static: dense dequant route under GSPMD
) -> SlotState:
    """Prefill one request's novel suffix into the pool blocks of
    ``table_row`` (compiled per SUFFIX bucket — a warm admit of a long
    prompt compiles and runs the short-suffix trace).

    Shape of the trick: gather the row's logical cache view through the
    table (shared prefix blocks arrive with their KV already computed),
    run the dense prefill over the suffix window at ``cache_offset=
    start`` with RoPE positions ``start + arange(T)``, then scatter the
    updated view back — but ONLY into blocks this admit owns
    (``own_mask``): shared blocks are never rewritten (copy-on-write),
    and the null padding past the row's last block is left alone so
    duplicate scatter indices all carry the block's current value
    (deterministic by construction). Masked positions of the gathered
    view contribute exactly 0 to attention, so a cold admit here is
    bit-identical to the pre-paging dense prefill."""
    T = suffix.shape[1]
    nb, bs, n_kv, D = state.caches_k[0].shape
    M = table_row.shape[0]
    S = M * bs  # logical per-row width == engine cache_len
    q_pos = start + jnp.arange(T)
    cache_pos = jnp.arange(S)
    # causal over logical positions, limited to the real prompt: key
    # slots past prompt_len (pad tail and decode room) are masked; the
    # shared-prefix slots < start are always visible
    mask = (
        (cache_pos[None, None, :] <= q_pos[None, :, None])
        & (cache_pos[None, None, :] < prompt_len)
    )
    quantized = state.caches_k[0].dtype == jnp.int8
    if quantized:
        # quantized pool: the gathered view dequantizes committed
        # blocks (shared-prefix KV arrives approximate — that IS the
        # int8 contract); the suffix window recomputes in bf16, and
        # requantizing a block whose values came from dequantization
        # is exact (the amax element always quantizes to ±127, so the
        # recovered scale round-trips)
        dt = state.tails_k[0].dtype
        caches = [
            (
                dequantize_blocks(
                    ck[table_row], sk[table_row], dt
                ).reshape(1, S, n_kv, D),
                dequantize_blocks(
                    cv[table_row], sv[table_row], dt
                ).reshape(1, S, n_kv, D),
            )
            for ck, sk, cv, sv in zip(
                state.caches_k, state.scales_k,
                state.caches_v, state.scales_v,
            )
        ]
    else:
        caches = [
            (
                ck[table_row].reshape(1, S, n_kv, D),
                cv[table_row].reshape(1, S, n_kv, D),
            )
            for ck, cv in zip(state.caches_k, state.caches_v)
        ]
    logits, caches = forward(
        params, suffix, cfg, positions=q_pos[None, :], attn_mask=mask,
        kv_caches=caches, cache_offset=start, wq_gspmd=wq_gspmd,
    )

    last = jnp.clip(suffix_len - 1, 0, T - 1)
    first = sample_rows(
        logits[:, last], temperature[None], top_k[None], top_p[None],
        rep_penalty[None], seen_row, key_data[None], prompt_len[None],
    )[0]
    seen_row = record_seen(seen_row, first[None], rep_penalty[None])

    own = own_mask[:, None, None, None]

    def put(pool, view):
        new_blocks = view.reshape(M, bs, n_kv, D)
        return pool.at[table_row].set(
            jnp.where(own, new_blocks, pool[table_row])
        )

    if quantized:
        # quantize-on-commit: only owned FULL blocks (< prompt_len //
        # bs) enter the pool; the partial tail block stays bf16 in the
        # slot's tail pair until a decode window fills it
        # (stepper._commit_full_tails) — a partial block never
        # round-trips through int8
        tb = prompt_len // bs
        own_q = own_mask & (jnp.arange(M) < tb)

        def putq(pool, scales, view):
            blocks = view.reshape(M, bs, n_kv, D)
            qv, sv = quantize_blocks(blocks)
            pool = pool.at[table_row].set(
                jnp.where(own_q[:, None, None, None], qv,
                          pool[table_row])
            )
            scales = scales.at[table_row].set(
                jnp.where(own_q[:, None], sv, scales[table_row])
            )
            return pool, scales

        def tail_pair(tails, view):
            blocks = view.reshape(M, bs, n_kv, D)
            # slot 0 = the current partial block tb (clipped gather:
            # tb == M only for prefill-only full rows, which never
            # decode); slot 1 = zeroed spill room
            t0 = blocks[jnp.clip(tb, 0, M - 1)]
            return tails.at[slot].set(
                jnp.stack([t0, jnp.zeros_like(t0)])
            )

        qk = [putq(b, s, c[0]) for b, s, c in zip(
            state.caches_k, state.scales_k, caches)]
        qv_ = [putq(b, s, c[1]) for b, s, c in zip(
            state.caches_v, state.scales_v, caches)]
        kv_fields = dict(
            caches_k=[p for p, _ in qk],
            scales_k=[s for _, s in qk],
            caches_v=[p for p, _ in qv_],
            scales_v=[s for _, s in qv_],
            tails_k=[tail_pair(t, c[0]) for t, c in zip(
                state.tails_k, caches)],
            tails_v=[tail_pair(t, c[1]) for t, c in zip(
                state.tails_v, caches)],
        )
    else:
        kv_fields = dict(
            caches_k=[
                put(b, c[0]) for b, c in zip(state.caches_k, caches)
            ],
            caches_v=[
                put(b, c[1]) for b, c in zip(state.caches_v, caches)
            ],
        )

    return dataclasses.replace(
        state,
        **kv_fields,
        tables=state.tables.at[slot].set(table_row),
        last_token=state.last_token.at[slot].set(first),
        offset=state.offset.at[slot].set(prompt_len),
        active=state.active.at[slot].set(True),
        temperature=state.temperature.at[slot].set(temperature),
        top_k=state.top_k.at[slot].set(top_k),
        top_p=state.top_p.at[slot].set(top_p),
        rep_penalty=state.rep_penalty.at[slot].set(rep_penalty),
        seen=jax.lax.dynamic_update_slice(
            state.seen, seen_row, (slot, 0)
        ),
        rng=state.rng.at[slot].set(key_data),
    )


@functools.partial(
    jax.jit, static_argnames=("cfg", "wq_gspmd"), donate_argnums=(1,)
)
def _prefill_chunk(
    params: Params,
    state: SlotState,
    window: jax.Array,  # i32[1, C] prompt tokens [pos, pos + C)
    pos: jax.Array,  # i32[] chunk start position in the logical row
    cfg: ModelConfig,
    table_row: jax.Array,  # i32[max_blocks] this slot's block table
    own_mask: jax.Array,  # bool[max_blocks] True = freshly allocated block
    wq_gspmd: bool = False,  # static: dense dequant route under GSPMD
) -> SlotState:
    """Commit ONE fixed-size prefill chunk's KV into the pool — no
    sampling, no slot-state installation (``_admit_slot`` finishes the
    tail and flips the slot live in one dispatch, so the row is never
    half-visible to the decode batch: its table stays all-null and
    ``active`` stays False until the final chunk).

    Same gather/scatter shape as ``_admit_slot``: the row's logical view
    through ``table_row`` (earlier chunks' KV arrives committed), dense
    forward over the window at ``cache_offset=pos``, own-masked write
    back (shared radix-prefix blocks are never rewritten). The window is
    always entirely inside the prompt, so the plain causal mask over
    logical positions is exactly ``_admit_slot``'s prompt-limited mask
    restricted to these queries — chunked and whole-suffix prefill
    commit bit-identical KV. ``return_hidden=True`` skips the lm-head
    matmul: intermediate chunks sample nothing, so the vocab projection
    is paid once per prompt (in the final ``_admit_slot``), not once per
    chunk. Compiled once per chunk width C (a fixed multiple of
    block_size), never per prompt length."""
    T = window.shape[1]
    nb, bs, n_kv, D = state.caches_k[0].shape
    M = table_row.shape[0]
    S = M * bs
    q_pos = pos + jnp.arange(T)
    cache_pos = jnp.arange(S)
    mask = cache_pos[None, None, :] <= q_pos[None, :, None]
    quantized = state.caches_k[0].dtype == jnp.int8
    if quantized:
        dt = state.tails_k[0].dtype
        caches = [
            (
                dequantize_blocks(
                    ck[table_row], sk[table_row], dt
                ).reshape(1, S, n_kv, D),
                dequantize_blocks(
                    cv[table_row], sv[table_row], dt
                ).reshape(1, S, n_kv, D),
            )
            for ck, sk, cv, sv in zip(
                state.caches_k, state.scales_k,
                state.caches_v, state.scales_v,
            )
        ]
    else:
        caches = [
            (
                ck[table_row].reshape(1, S, n_kv, D),
                cv[table_row].reshape(1, S, n_kv, D),
            )
            for ck, cv in zip(state.caches_k, state.caches_v)
        ]
    _, caches = forward(
        params, window, cfg, positions=q_pos[None, :], attn_mask=mask,
        kv_caches=caches, cache_offset=pos, return_hidden=True,
        wq_gspmd=wq_gspmd,
    )

    own = own_mask[:, None, None, None]

    def put(pool, view):
        new_blocks = view.reshape(M, bs, n_kv, D)
        return pool.at[table_row].set(
            jnp.where(own, new_blocks, pool[table_row])
        )

    if quantized:
        # intermediate chunks are block-aligned and entirely inside the
        # prompt, so every owned block the window covered is FULL —
        # quantize all owned blocks (blocks past the chunk hold junk
        # that later chunks and the finalizing _admit_slot rewrite;
        # already-committed earlier-chunk blocks requantize exactly,
        # see _admit_slot)
        def putq(pool, scales, view):
            blocks = view.reshape(M, bs, n_kv, D)
            qv, sv = quantize_blocks(blocks)
            pool = pool.at[table_row].set(
                jnp.where(own, qv, pool[table_row])
            )
            scales = scales.at[table_row].set(
                jnp.where(own_mask[:, None], sv, scales[table_row])
            )
            return pool, scales

        qk = [putq(b, s, c[0]) for b, s, c in zip(
            state.caches_k, state.scales_k, caches)]
        qv_ = [putq(b, s, c[1]) for b, s, c in zip(
            state.caches_v, state.scales_v, caches)]
        return dataclasses.replace(
            state,
            caches_k=[p for p, _ in qk],
            scales_k=[s for _, s in qk],
            caches_v=[p for p, _ in qv_],
            scales_v=[s for _, s in qv_],
        )

    return dataclasses.replace(
        state,
        caches_k=[put(b, c[0]) for b, c in zip(state.caches_k, caches)],
        caches_v=[put(b, c[1]) for b, c in zip(state.caches_v, caches)],
    )


@functools.partial(jax.jit, static_argnames=("dcfg",), donate_argnums=(1,))
def _admit_draft(
    dparams: Params,
    dstate: DraftState,
    window: jax.Array,  # i32[1, T_bucket] FULL effective prompt, padded
    prompt_len: jax.Array,  # i32[] live tokens in ``window``
    dcfg: ModelConfig,
    slot: jax.Array,  # i32[]
) -> DraftState:
    """Prefill the DRAFT model over one slot's effective prompt and
    install the row (compiled per full-prompt bucket — the draft has no
    radix reuse, so unlike ``_admit_slot`` the whole prompt recomputes;
    the draft is small enough that this never dominates an admit).

    The forward runs against throwaway 1-row caches and the result is
    scattered into the slot's stripe of the dense draft cache. Padded
    tail positions (>= prompt_len) carry junk KV, which is safe by the
    DraftState invariant: verify_window's repair forward rewrites
    positions offset-1 and offset before any read, and the propose scan
    writes each deeper position before attending it — junk is never
    upstream of a kept token. ``prev`` is the prompt's last token
    (position prompt_len - 1): the target's ``last_token`` after admit
    is the freshly sampled token at position prompt_len, one past it.

    A 0-layer (bigram) draft — embed/norm/lm_head only, the degenerate
    end of the draft spectrum, cf. prompt-lookup/n-gram drafting — has
    no KV to prefill: its logits depend only on the previous token, so
    installing the row is just setting ``prev``."""
    T = window.shape[1]
    if dcfg.num_hidden_layers == 0:
        return dataclasses.replace(
            dstate,
            prev=dstate.prev.at[slot].set(window[0, prompt_len - 1]),
        )
    n_kv = dstate.caches_k[0].shape[2]
    D = dstate.caches_k[0].shape[3]
    caches = [
        (
            jnp.zeros((1, T, n_kv, D), dstate.caches_k[0].dtype),
            jnp.zeros((1, T, n_kv, D), dstate.caches_v[0].dtype),
        )
        for _ in range(dcfg.num_hidden_layers)
    ]
    pos = jnp.arange(T)
    mask = (pos[None, None, :] <= pos[None, :, None])
    _, caches = forward(
        dparams, window, dcfg, attn_mask=mask,
        kv_caches=caches, cache_offset=0, return_hidden=True,
    )

    def put(pool, view):
        return jax.lax.dynamic_update_slice(
            pool, view, (slot, 0, 0, 0)
        )

    return dataclasses.replace(
        dstate,
        caches_k=[put(b, c[0]) for b, c in zip(dstate.caches_k, caches)],
        caches_v=[put(b, c[1]) for b, c in zip(dstate.caches_v, caches)],
        prev=dstate.prev.at[slot].set(window[0, prompt_len - 1]),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _import_blocks(
    state: SlotState,
    table_row: jax.Array,  # i32[max_blocks] freshly allocated block ids
    own_mask: jax.Array,  # bool[max_blocks] True = real imported page
    pages_k: jax.Array,  # [L, max_blocks, bs, n_kv, D], zero-padded
    pages_v: jax.Array,
    scales_k: jax.Array,  # f32[L, max_blocks, n_kv]; all-ones for bf16
    scales_v: jax.Array,
) -> SlotState:
    """Scatter fetched KV pages into the pool (disaggregated prefill:
    a prefill replica computed them, wire.py carried them, the host
    staged them — kubeinfer_tpu/disagg/). Same own-mask discipline as
    ``_admit_slot``'s put: padding entries point at the null block with
    ``own=False``, so every duplicate scatter index carries the block's
    current value (deterministic by construction), and the pages tensor
    is always padded to ``max_blocks`` — ONE compiled shape per engine
    config, never one per prefix length. No slot state is touched: the
    import only materializes pool blocks; the request that wants them
    admits through the ordinary warm path afterwards, which is what
    makes a remote prefix token-identical to a radix hit."""
    own = own_mask[:, None, None, None]

    def put(pool, pages):
        return pool.at[table_row].set(
            jnp.where(own, pages, pool[table_row])
        )

    def put_s(scales, pages):
        return scales.at[table_row].set(
            jnp.where(own_mask[:, None], pages, scales[table_row])
        )

    # quantized pools also land the per-block scales (the exporter
    # captured committed int8 pages, so no requantization happens on
    # either side of the wire); the bf16 pytree has no scale leaves and
    # the operands are simply unused
    scale_fields = {}
    if state.scales_k:  # lint: allow[jit-traced-branch] branches on pytree STRUCTURE (empty list under bf16), not a traced value — both trace shapes are legal and cached separately
        scale_fields = dict(
            scales_k=[
                put_s(s, scales_k[i])
                for i, s in enumerate(state.scales_k)
            ],
            scales_v=[
                put_s(s, scales_v[i])
                for i, s in enumerate(state.scales_v)
            ],
        )
    return dataclasses.replace(
        state,
        caches_k=[
            put(b, pages_k[i]) for i, b in enumerate(state.caches_k)
        ],
        caches_v=[
            put(b, pages_v[i]) for i, b in enumerate(state.caches_v)
        ],
        **scale_fields,
    )


# --- host-side scheduler ---------------------------------------------------


class EngineDrainingError(RuntimeError):
    """submit() refused because the engine is draining. Its own type
    (not ValueError) so the server can answer 503 — the request is
    valid, THIS replica just won't take it — and the router can treat
    the refusal as 'mark draining, route elsewhere' rather than a
    client error to relay."""


class EngineOverloadedError(RuntimeError):
    """submit() shed because the waiting-work depth reached
    ``queue_depth_limit`` (ROADMAP item 5's graceful load-shedding:
    refuse at the door instead of queue collapse). Distinct from
    EngineDrainingError because the remedy differs — a drained replica
    never recovers for new work, an overloaded one does, so the server
    answers 503 WITH Retry-After and the router treats it as transient
    pressure, not evacuation."""

    def __init__(self, msg: str, retry_after_s: float = 1.0) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s


@dataclass(frozen=True)
class PreemptionPolicy:
    """SLO-aware preemption knobs (vLLM preempts by full recompute; the
    radix trie makes park-and-readmit nearly free here, so the policy
    can afford to fire on queue-wait pressure alone).

    A waiter triggers preemption only when ALL of: its wait exceeds
    ``threshold_s``, the engine-private queue_wait SLO burn rate has
    reached ``burn_limit`` (burn 1.0 = spending error budget exactly at
    the sustainable rate), at least ``cooldown_steps`` decode steps ran
    since the last preemption, and some victim has decoded at least
    ``min_progress`` tokens since its own (re)admission. The last two
    are the anti-livelock levers: every park is preceded by guaranteed
    forward progress, so an oversubscribed engine round-robins rather
    than thrashes."""

    threshold_s: float = 0.5
    objective: float = 0.9  # good fraction target for the private SLO
    burn_limit: float = 1.0
    cooldown_steps: int = 4
    min_progress: int = 2

    @classmethod
    def parse(cls, spec: str) -> "PreemptionPolicy":
        """``THRESHOLD_S[:BURN_LIMIT]`` — the --preemption-slo CLI
        syntax, e.g. ``0.5`` or ``0.5:2.0``."""
        parts = spec.split(":")
        if len(parts) > 2:
            raise ValueError(
                f"preemption spec {spec!r} is not THRESHOLD_S[:BURN_LIMIT]"
            )
        kw: dict = {"threshold_s": float(parts[0])}
        if len(parts) == 2:
            kw["burn_limit"] = float(parts[1])
        return cls(**kw)


# process-wide request-id stream: every flight-recorder lifecycle emit
# carries ``req=<rid>`` (the canonical detail key the protocol spec in
# analysis/protocol.py requires), so a /debug/flightrecorder dump keys
# each request's chain unambiguously even across engine restarts
_REQ_IDS = itertools.count()


@dataclass
class _Request:
    prompt: list[int]
    max_new: int
    eos_id: int
    rid: int = field(default_factory=lambda: next(_REQ_IDS))
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    rep_penalty: float = 1.0
    seed: int = 0
    out_tokens: list[int] = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    cancelled: threading.Event = field(default_factory=threading.Event)
    # set instead of a normal completion when the engine shut down
    # mid-flight — truncated output must not look like success
    failed: str = ""
    # request timeline, tracing-clock seconds: the scheduler writes
    # these at the admit/first-token/retire transitions and the server
    # reads them AFTER done is set (the Event is the happens-before
    # edge), deriving the queue-wait/TTFT/TPOT histograms without a
    # second timing source. trace_parent anchors the retroactive
    # engine spans to the caller's trace (or a fresh one).
    trace_parent: "tracing.SpanContext | None" = None
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first: float = 0.0
    t_done: float = 0.0
    token_times: list[float] = field(default_factory=list)
    # preemption bookkeeping: t_parked restarts the request's place in
    # the longest-pending-first admission order (a just-parked victim
    # goes to the back of the line — the anti-livelock invariant);
    # tokens_at_admit anchors the min_progress victim guard to the
    # CURRENT residency, not lifetime output
    t_parked: float = 0.0
    preemptions: int = 0
    tokens_at_admit: int = 0
    # True once any token_times entry was interpolated from a fused
    # window bracket rather than observed per step — the decode span
    # carries it as ``kubeinfer.interpolated`` so trace readers don't
    # mistake the evenly spaced events for per-step measurements
    # (docs/OBSERVABILITY.md)
    interpolated: bool = False
    # per-request speculative accounting (verify-window path): accepted
    # draft tokens and windows that rolled at least one draft back —
    # carried onto the engine.decode span at retirement
    spec_accepted: int = 0
    spec_rollbacks: int = 0
    # disaggregated prefill (disagg/): export_kv asks the scheduler to
    # capture this request's committed full-block pages at finalize
    # time — the ONLY thread where reading _state is safe (jit donation
    # deletes the buffers HTTP threads would race). kv_export is the
    # captured dict (pages_k/pages_v/fingerprints/block_size), read by
    # the server after done is set (the Event is the happens-before
    # edge, same contract as the timeline fields above).
    export_kv: bool = False
    kv_export: dict | None = None
    # live-session migration (drain): set INSTEAD of a normal
    # completion when the engine handed this session off — carries the
    # generation-so-far plus how many committed blocks were streamed to
    # the export cache, so the router can re-route with a resume body.
    # Read by the server after done is set (same happens-before
    # contract as kv_export above). A migrated request is neither
    # finished nor failed: its out_tokens are a PREFIX of the final
    # answer, which the resuming replica completes token-identically.
    migrated: dict | None = None

    @property
    def pending_since(self) -> float:
        return self.t_parked or self.t_submit

    def cancel(self) -> None:
        """Abandon the request: the scheduler drops it before admission
        or retires its slot at the next step, instead of decoding tokens
        nobody will read."""
        self.cancelled.set()


@dataclass
class _PrefillTask:
    """One in-progress chunked prefill: the slot is reserved (its
    ``_slot_req`` entry set, blocks held) but the row stays inactive —
    the decode batch keeps stepping other slots between chunks.
    ``tokens`` is the EFFECTIVE prompt (original prompt + any tokens
    generated before a preemption), frozen at plan time; ``pos`` is the
    next logical position to prefill (starts at the radix-matched
    offset, advances one chunk per scheduler pass)."""

    req: _Request
    slot: int
    table_row: np.ndarray  # i32[max_blocks]
    own_mask: np.ndarray  # bool[max_blocks]
    reuse: int  # radix-matched full blocks
    total: int  # blocks held by the slot (prompt + decode horizon)
    pos: int
    tokens: list[int]
    resumed: bool
    # the plan reserved verify slack (spec_k extra positions), so the
    # finalize also prefills the slot's draft-cache row
    spec_ok: bool = False


@dataclass
class _ImportTask:
    """One staged KV import (disaggregated prefill): an HTTP thread
    fetched and verified the pages (disagg/client.py), the scheduler
    thread scatters them — it is the only ``_state`` writer, so the
    handoff is a queue + Event rather than a lock around device state.
    ``tokens`` covers exactly the imported full blocks (n * block_size
    tokens); ``pages_k``/``pages_v`` are ``[L, n, bs, n_kv, D]``."""

    tokens: list[int]
    pages_k: np.ndarray
    pages_v: np.ndarray
    # int8 wire (kubeinfer-kvwire/2): per-block-per-head dequant scales
    # [L, n, n_kv] f32; None on the bf16 wire
    scales_k: np.ndarray | None = None
    scales_v: np.ndarray | None = None
    # chunked import (kubeinfer-kvwire/3, live migration): the pages
    # cover blocks [start_block, start_block + n) and ``tokens`` the
    # whole prefix through the chunk's end — the scatter stacks on a
    # radix-matched [0, start_block) prefix, so a chunk can never land
    # on the wrong base
    start_block: int = 0
    done: threading.Event = field(default_factory=threading.Event)
    imported: int = 0
    reason: str | None = None


class ContinuousEngine:
    """Slot-scheduled generation: submit() from any thread; a single
    scheduler thread admits requests into free slots and steps the
    shared decode batch.

    Cold-compile stall (ADVICE r5): when ``_place`` forms a draft
    group, ``speculative.start_group`` runs ON the scheduler thread,
    and the first group with a new ``(B, prompt bucket, cache_len)``
    shape pays the full jit compile there — potentially tens of
    seconds on which EVERY in-flight slot request also stalls (no
    decode steps run while the scheduler is inside the compile). The
    same applies to the first prefill of each prompt bucket on the
    slot path. Deployments that care should call ``prewarm_spec()``
    (and/or issue a throwaway generate per bucket) before serving
    traffic; the per-shape compile caches are process-global, so one
    warmup covers all subsequent groups of that shape.
    """

    def __init__(self, params: Params, cfg: ModelConfig,
                 n_slots: int = 8, cache_len: int = 1024,
                 speculative=None, block_size: int | None = None,
                 num_blocks: int | None = None,
                 prefill_chunk_blocks: int = 0,
                 preemption: PreemptionPolicy | None = None,
                 max_window: int = 8,
                 layout: EngineLayout | None = None,
                 spec_draft: tuple[Params, ModelConfig] | None = None,
                 spec_k: int = 4,
                 kv_dtype: str = "bf16",
                 weight_dtype: str = "bf16",
                 queue_depth_limit: int = 0,
                 migration_chunk_blocks: int = 4,
                 flight_capacity: int = 512,
                 replica_name: str | None = None) -> None:
        # device layout (sharding.EngineLayout): tp=1 (the default) is
        # meshless and every placement below is the identity — the
        # engine is byte-for-byte the single-device engine. Under tp>1
        # the layout places params (Megatron specs) and the slot state
        # (pool along n_kv, rest replicated); the jits themselves are
        # unchanged and GSPMD partitions from the input shardings.
        self.layout = layout if layout is not None else EngineLayout()
        self.layout.check_model(cfg)
        self._sharded = self.layout.sharded
        # weight precision axis (ISSUE 20), kv_dtype's load-time
        # mirror: "int8" accepts either pre-quantized params (the
        # load-time path — weights.params_from_state_dict /
        # model.init_params, where the bf16 copy never reached the
        # device) or plain params to quantize here; "bf16" with a
        # quantized tree is a hard error rather than a silent
        # dequantize, because the caller's capacity math would be wrong
        if weight_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"weight_dtype must be 'bf16' or 'int8', got "
                f"{weight_dtype!r}"
            )
        held = params_weight_dtype(params)
        if weight_dtype == "int8" and held == "bf16":
            params = quantize_params(params)
        elif weight_dtype == "bf16" and held == "int8":
            raise ValueError(
                "weight_dtype='bf16' but params are weight-quantized "
                "(dequantize_params first, or pass weight_dtype='int8')"
            )
        self.weight_dtype = weight_dtype
        self.params = self.layout.shard_params(params, cfg)
        # static param footprint for the kubeinfer_model_param_bytes
        # gauge: int8 pages + f32 scale planes under weight quant,
        # global across the mesh (shape metadata only — no host sync)
        self.model_param_bytes = int(sum(
            x.nbytes for x in jax.tree.leaves(self.params)
        ))
        self.cfg = cfg
        self.n_slots = n_slots
        self.cache_len = cache_len
        # paged KV: block width defaults to the kernel-aligned size,
        # clamped for small test caches (which then take the
        # gather+dense fallback path)
        self.block_size = block_size if block_size is not None else min(
            DEFAULT_BLOCK_SIZE, cache_len
        )
        if cache_len % self.block_size:
            raise ValueError(
                f"cache_len {cache_len} must be a multiple of block_size "
                f"{self.block_size}"
            )
        self.max_blocks = cache_len // self.block_size
        if num_blocks is None:
            # 2x slot capacity (+ the reserved null block): the surplus
            # is what the radix cache retains between requests — with
            # exactly slot capacity every admit would evict the prefix
            # it hopes to reuse
            num_blocks = 1 + 2 * n_slots * self.max_blocks
        if num_blocks < 1 + n_slots * self.max_blocks:
            # below this floor a full-length request could find the pool
            # permanently short even after evicting the whole trie (its
            # blocks pinned by other slots) — the holdover would starve
            raise ValueError(
                f"num_blocks {num_blocks} < 1 + n_slots * max_blocks "
                f"({1 + n_slots * self.max_blocks}): a request could "
                "never admit"
            )
        self._pool = BlockPool(num_blocks, self.block_size)
        self._radix = RadixCache(self._pool)
        # chunked prefill: intermediate chunks are exactly this many
        # tokens (k full blocks — ONE compiled shape), the tail rides
        # the existing _admit_slot bucket traces. 0 disables, restoring
        # the single-dispatch admit.
        if prefill_chunk_blocks < 0:
            raise ValueError(
                f"prefill_chunk_blocks must be >= 0, got "
                f"{prefill_chunk_blocks}"
            )
        self.chunk_tokens = prefill_chunk_blocks * self.block_size
        # fused decode windows: horizons are drawn from the static
        # bucket set clipped to max_window (one compiled shape per
        # bucket — stepper.WINDOW_BUCKETS). max_window=1 restores the
        # one-dispatch-per-token loop exactly.
        if max_window < 1:
            raise ValueError(f"max_window must be >= 1, got {max_window}")
        self.max_window = max_window
        self._window_buckets = tuple(
            b for b in WINDOW_BUCKETS if b <= max_window
        )
        self.windows_total = 0  # telemetry: fused decode dispatches
        # admissions PLANNED while a decode window is in flight
        # (host-side radix match + block alloc only — no device work):
        # (req, slot, kv_plan, effective tokens), admitted at the next
        # window boundary by _admit_pending. Mutated under _lock; swept
        # by _fail_inflight like every other handoff field.
        self._staged: list[tuple[_Request, int, tuple, list[int]]] = []
        # SLO-aware preemption: the engine owns a PRIVATE monitor (the
        # server's SLOMonitor aggregates every route; feeding the
        # scheduler from it would double-count queue_wait and couple
        # admission policy to scrape configuration). Observations land
        # at admit time plus a live head-wait probe in _maybe_preempt,
        # so a wedged engine with no admits still sees its burn rise.
        self.preemption = preemption
        self._slo: SLOMonitor | None = None
        if preemption is not None:
            self._slo = SLOMonitor(
                objectives=(SLOObjective(
                    "queue_wait", preemption.threshold_s,
                    preemption.objective,
                ),),
                windows=(30.0, 300.0),
                name="batching.SLOMonitor._lock",
            )
        # chunked prefills in flight (at most one chunk dispatched per
        # scheduler pass, FIFO) and preempted requests awaiting readmit
        self._prefills: list[_PrefillTask] = []
        self._parked: list[_Request] = []
        # staged KV imports (disaggregated prefill, disagg/): appended
        # by HTTP threads under _lock, serviced one per scheduler pass
        # by _step_import, swept by _fail_inflight like every other
        # handoff field
        self._imports: list[_ImportTask] = []
        self.imports_total = 0  # telemetry: serviced KV imports
        self.imported_blocks_total = 0  # telemetry: blocks scattered in
        # live-session migration (drain): while _draining, submit()
        # refuses, pending populations complete as migrated, and live
        # slots stream their committed blocks out through
        # migration_sink one chunk per scheduler pass (decode keeps
        # running between chunks), then park-and-migrate the tail.
        # _draining is read locklessly on hot paths (same torn-read
        # tolerance as stats_summary — a racing submit lands in the
        # queue and the next drain sweep migrates it).
        if migration_chunk_blocks < 1:
            raise ValueError(
                f"migration_chunk_blocks must be >= 1, got "
                f"{migration_chunk_blocks}"
            )
        self.migration_chunk_blocks = migration_chunk_blocks
        self._draining = False
        self._drained = threading.Event()
        # injectable export hook, set by the serving layer: called on
        # the scheduler thread OFF _lock with one chunk dict
        # (start_block, pages, fingerprints slice, scales for int8) —
        # the server encodes wire v3 and parks it in its KVExportCache
        self.migration_sink = None
        # per-slot count of committed blocks already streamed out
        self._migrate_cursor: dict[int, int] = {}
        self.migrated_total = 0  # telemetry: sessions handed off
        self.migration_chunks_total = 0  # telemetry: chunks streamed
        self.migration_blocks_total = 0  # telemetry: blocks streamed
        # cooldown ticks on decode steps; start past the gate so the
        # first pressure spike can preempt immediately
        self._steps_since_preempt = 1 << 30
        self.preempted_total = 0  # telemetry: rows parked
        self.resumed_total = 0  # telemetry: parked rows readmitted
        self.chunks_total = 0  # telemetry: intermediate chunk dispatches
        # step-level observability (docs/OBSERVABILITY.md): one record
        # per device dispatch, plus the scheduler-decision flight ring.
        # The kv_stats callback reads the pool's own locked counters and
        # runs OUTSIDE the profiler lock, so no cycle joins the
        # engine -> radix -> pool order.
        self.profiler = StepProfiler(
            n_slots=n_slots,
            kv_stats=lambda: (self._pool.used_blocks,
                              self._pool.free_blocks),
            name="batching.StepProfiler._lock",
        )
        if flight_capacity < 1:
            raise ValueError(
                f"flight_capacity must be >= 1, got {flight_capacity}"
            )
        self.flight = FlightRecorder(
            capacity=flight_capacity,
            name="batching.FlightRecorder._lock",
        )
        # fleet identity on this engine's spans (engine.queue_wait /
        # prefill / decode): every in-process replica records into the
        # module-global RECORDER, so without a replica attr a merged
        # fleet trace cannot say WHICH engine served a hop. None (the
        # default) adds no attr — single-engine traces stay unchanged.
        self.replica_name = replica_name
        # host copy of each slot's owned block ids (shared + fresh), in
        # table order — what retire returns to the pool
        self._slot_blocks: list[list[int]] = [[] for _ in range(n_slots)]
        # Optional SpeculativeEngine: draft-eligible requests decode
        # through an INCREMENTAL draft group (speculative.start_group /
        # step_group) that interleaves with busy slots one round at a
        # time — r4 verdict item 5: the old route only engaged when the
        # batcher was fully idle, so spec_served stayed flat exactly
        # when throughput mattered. One live group at a time; greedy
        # requests keep token-identity, sampled requests keep the exact
        # target distribution with PER-ROW warp knobs (speculative.py);
        # repetition-penalty requests stay on slots (the penalty
        # reshapes p from state the verifier window cannot see).
        self.speculative = speculative
        self.spec_served = 0  # telemetry: requests served via the draft
        self.spec_accepted = 0  # telemetry: accepted draft tokens, all groups
        # (member requests, live group handle) — at most one in flight
        self._spec_group: tuple[list[_Request], object] | None = None
        # arrival-order heads popped from the queue but not yet
        # placeable (no free slot / not group-joinable); served before
        # the queue. A deque (oldest first) rather than a single slot:
        # preemption interleaves parked readmits with fresh arrivals,
        # so two unplaced requests can be in hand at once.
        self._holdover: "collections.deque[_Request]" = collections.deque()
        # Speculative VERIFY path (distinct from the draft-GROUP path
        # above — this one rides the paged batch itself): a draft model
        # proposes spec_k tokens per live row and ONE fused
        # stepper.verify_window dispatch scores/accepts them. When set,
        # it supersedes the group route entirely (_place gates on it):
        # the verify window serves every slot request, warm or resumed,
        # with or without repetition penalty, and composes with
        # preemption and tensor parallelism — everything the
        # solo-dense group path cannot.
        self.spec_draft = spec_draft
        self.spec_k = spec_k
        self._dparams: Params | None = None
        self._dcfg: ModelConfig | None = None
        self._dstate: DraftState | None = None
        # per-slot: the admit plan reserved verify slack and the draft
        # row was prefilled. Verify dispatches only when ALL live
        # decoding rows are spec-capable (one fused window covers every
        # slot); a single tight-on-cache row degrades the pass to
        # decode_window, never to wrong output.
        self._slot_spec_ok = [False] * n_slots
        # monotonic verify-path counters (scheduler_stats -> /metrics
        # delta): proposed draft tokens, host-accepted draft tokens,
        # windows that rolled at least one draft back
        self.spec_draft_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_rollbacks = 0
        if spec_draft is not None:
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            dparams, dcfg = spec_draft
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    "draft/target vocabulary mismatch: "
                    f"{dcfg.vocab_size} vs {cfg.vocab_size}"
                )
            if spec_k + 1 > cache_len:
                raise ValueError(
                    f"spec_k {spec_k} leaves no room in cache_len "
                    f"{cache_len}"
                )
            # draft params/state replicate under tp: the draft is tiny,
            # and replication keeps it free of head-divisibility
            # constraints the target's Megatron specs impose
            if self._sharded:
                rep = self.layout.replicated()
                dparams = jax.tree.map(
                    lambda x: jax.device_put(x, rep), dparams
                )
            self._dparams, self._dcfg = dparams, dcfg
            dstate = init_draft_state(
                dcfg, n_slots, cache_len, params["norm"].dtype
            )
            if self._sharded:
                rep = self.layout.replicated()
                dstate = jax.tree.map(
                    lambda x: jax.device_put(x, rep), dstate
                )
            self._dstate = dstate
        # paged-pool precision axis (ISSUE 15): int8 pages + per-block
        # scales double the effective pool capacity; the stepper, the
        # attention routers, and the wire all branch statically on the
        # pool dtype, so the bf16 engine's traces stay byte-identical
        if kv_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"kv_dtype must be 'bf16' or 'int8', got {kv_dtype!r}"
            )
        self.kv_dtype = kv_dtype
        # host telemetry: logical KV blocks quantize-committed into the
        # pool (admit full blocks + decode/verify tail commits; imports
        # arrive pre-quantized and are not re-counted). Monotonic —
        # the server deltas it into a Prometheus counter.
        self.quant_blocks_total = 0
        self._state = self.layout.shard_state(init_slot_state(
            cfg, n_slots, cache_len, params["norm"].dtype,
            num_blocks, self.block_size, kv_dtype=kv_dtype,
        ))
        # static pool footprint for the kubeinfer_kv_pool_bytes gauge:
        # pages + scales + tails, global across the mesh (shape
        # metadata only — no host sync)
        st = self._state
        self.kv_pool_bytes = int(sum(
            x.nbytes for x in (
                *st.caches_k, *st.caches_v, *st.scales_k,
                *st.scales_v, *st.tails_k, *st.tails_v,
            )
        ))
        # load-shedding door (ROADMAP item 5): 0 = unbounded (the
        # pre-shedding behavior); > 0 sheds submits once waiting work
        # (queue + holdover + parked) reaches the limit
        self.queue_depth_limit = int(queue_depth_limit)
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._slot_req: list[_Request | None] = [None] * n_slots
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # guards _slot_req and request result mutation between the
        # scheduler loop and stop()'s cleanup (the join below can time
        # out behind a long jit compile, leaving both threads live)
        self._lock = make_lock("batching.ContinuousEngine._lock")
        guard(self)

    # -- public API -------------------------------------------------------

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Can this request ride a slot? (callers fall back to the
        per-request engine when not — e.g. contexts beyond slot width)"""
        return (
            prompt_len > 0
            and prompt_len + max_new_tokens <= self.cache_len
            and _bucket(prompt_len) <= self.cache_len
        )

    def submit(self, prompt: list[int], max_new_tokens: int = 32,
               eos_id: int = -1, temperature: float = 0.0,
               seed: int = 0, top_k: int = 0,
               top_p: float = 1.0,
               repetition_penalty: float = 1.0,
               export_kv: bool = False,
               resume_tokens: list[int] | None = None) -> _Request:
        """``resume_tokens`` is the migration resume path: tokens a
        SOURCE replica already generated for this request. They
        pre-populate ``out_tokens``, so admission takes the readmit
        route (effective prompt = prompt + resume_tokens, remaining
        budget = max_new - len(resume_tokens)) and — by the
        position-folded key schedule that makes park/readmit exact —
        every later sample draws the identical noise an uninterrupted
        run would have at that position. ``max_new_tokens`` stays the
        ORIGINAL total budget, exactly as a parked request keeps its
        own; the returned out_tokens therefore contains resume_tokens
        as a prefix of the full answer."""
        if self._draining:
            # lockless read, same torn-read tolerance as stats_summary:
            # a submit racing the flag flip lands in the queue and the
            # next _step_drain sweep migrates it — refused here only as
            # a fast path so the router marks this replica early
            raise EngineDrainingError("engine is draining")
        if not prompt:
            raise ValueError("empty prompt")
        if not self.fits(len(prompt), max_new_tokens):
            # includes the bucket check: admission pads the prompt to a
            # bucket, and a bucket wider than the cache cannot prefill —
            # accepting it here would return a silent empty completion
            raise ValueError(
                f"request (prompt {len(prompt)} + new {max_new_tokens}, "
                f"prefill bucket {_bucket(len(prompt))}) exceeds slot "
                f"capacity ({self.cache_len})"
            )
        rt = [int(t) for t in (resume_tokens or [])]
        if rt:
            if len(rt) >= max_new_tokens:
                # a fully (or over-) generated resume has nothing left
                # to decode; admitting it would sample past the budget
                raise ValueError(
                    f"resume_tokens ({len(rt)}) must leave budget "
                    f"(max_new {max_new_tokens})"
                )
            if _bucket(len(prompt) + len(rt)) > self.cache_len:
                # the readmit's effective prompt pads to a bucket just
                # like a cold admit; same silent-empty-completion guard
                # as fits() applies to the widened prompt
                raise ValueError(
                    f"resume bucket {_bucket(len(prompt) + len(rt))} "
                    f"exceeds slot capacity ({self.cache_len})"
                )
        if self.queue_depth_limit:
            # same lockless depth read as stats_summary (torn by at
            # most 1); >= so limit=1 means "shed whenever anything is
            # already waiting"
            depth = (self._queue.qsize() + len(self._holdover)
                     + len(self._parked))
            if depth >= self.queue_depth_limit:
                # ledger the refusal as submit -> backpressure -> fail
                # (the SPEC's queued self-loop, then the terminal) so
                # flight post-mortems see WHY the request never reached
                # a slot, then refuse with a retry hint instead of
                # joining a queue already past the replica's drain rate
                req = _Request(prompt, max_new_tokens, eos_id,
                               temperature=temperature, top_k=top_k,
                               top_p=top_p, rep_penalty=repetition_penalty,
                               seed=seed)
                req.t_submit = tracing.now()
                req.failed = "shed"
                self._note("submit", req=req.rid,
                           prompt_tokens=len(prompt),
                           max_new=max_new_tokens)
                self._note("backpressure", req=req.rid,
                           reason="queue_depth_limit", depth=depth,
                           limit=self.queue_depth_limit)
                self._note("fail", req=req.rid, reason="shed")
                req.done.set()
                raise EngineOverloadedError(
                    f"queue depth {depth} >= queue_depth_limit "
                    f"{self.queue_depth_limit}"
                )
        req = _Request(prompt, max_new_tokens, eos_id,
                       temperature=temperature, top_k=top_k, top_p=top_p,
                       rep_penalty=repetition_penalty, seed=seed,
                       export_kv=export_kv)
        if rt:
            # the admit path detects a resume by out_tokens being
            # non-empty (exactly how a parked readmit looks); no
            # token_times for these — they were timed on the source
            req.out_tokens = rt
        # capture the submitter's trace context here (scheduler runs on
        # its own thread, where the thread-local stack is empty); no
        # inbound context still gets a per-request trace anchor
        ctx = tracing.current_context()
        req.trace_parent = ctx if ctx is not None else \
            tracing.new_root_context()
        req.t_submit = tracing.now()
        # note BEFORE the queue publish: once the request is visible the
        # scheduler thread can admit it, and an admit event with a lower
        # ring seq than its own submit would be an illegal transition to
        # the protocol oracle (and a lie to any post-mortem reader)
        self._note("submit", req=req.rid, prompt_tokens=len(prompt),
                   max_new=max_new_tokens)
        self._queue.put(req)
        return req

    def serve(self, prompt: list[int], max_new_tokens: int = 32,
              eos_id: int = -1, temperature: float = 0.0,
              seed: int = 0, top_k: int = 0, top_p: float = 1.0,
              repetition_penalty: float = 1.0,
              timeout: float = 300.0,
              export_kv: bool = False,
              resume_tokens: list[int] | None = None) -> _Request:
        """submit() + wait, returning the completed request object so
        callers (the HTTP server's latency-breakdown histograms) can
        read the timeline fields alongside the tokens. A request that
        completes by MIGRATION (this replica drained mid-generation)
        returns normally with ``req.migrated`` set — the caller decides
        whether to re-route with the partial out_tokens."""
        req = self.submit(prompt, max_new_tokens, eos_id,
                          temperature=temperature, seed=seed,
                          top_k=top_k, top_p=top_p,
                          repetition_penalty=repetition_penalty,
                          export_kv=export_kv,
                          resume_tokens=resume_tokens)
        if not req.done.wait(timeout):
            req.cancel()  # free the slot; tokens would go unread
            raise TimeoutError("generation timed out")
        if req.failed:
            raise RuntimeError(req.failed)
        return req

    def generate(self, prompt: list[int], max_new_tokens: int = 32,
                 eos_id: int = -1, temperature: float = 0.0,
                 seed: int = 0, top_k: int = 0, top_p: float = 1.0,
                 repetition_penalty: float = 1.0,
                 timeout: float = 300.0) -> list[int]:
        return self.serve(
            prompt, max_new_tokens, eos_id, temperature=temperature,
            seed=seed, top_k=top_k, top_p=top_p,
            repetition_penalty=repetition_penalty, timeout=timeout,
        ).out_tokens

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> None:
        """Flip the engine into drain mode: submit() starts refusing,
        and the scheduler loop replaces admission/preemption with
        ``_step_drain`` — pending populations complete as migrated
        immediately, live slots stream their committed KV out through
        ``migration_sink`` one chunk per pass (decode keeps running
        between chunks — the stream chases the decode head), and each
        caught-up slot parks-for-migrate. Idempotent; ``undrain()``
        reverses it (the rebalance caller drains, hands sessions off,
        then rejoins the fleet)."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self._drained.clear()
            # note under the lock: the scheduler observes _draining via
            # this same lock, so the drain_start event's ring seq is
            # guaranteed to precede every migrate* emit of the window —
            # the protocol oracle's drain guard depends on that order
            self._note("drain_start")

    def undrain(self) -> None:
        """Resume admissions after a drain (rebalance / cancelled
        scale-down). Sessions already migrated are gone — a bounced-back
        request re-enters through submit(resume_tokens=...) and lands
        warm on the blocks ``_migrate_slot`` parked in the trie."""
        with self._lock:
            if not self._draining:
                return
            self._draining = False
            self._drained.clear()
            # under the lock for the same seq-order guarantee as
            # drain_start: no migrate* emit may land after this event
            self._note("drain_end")

    def wait_drained(self, timeout_s: float = 30.0) -> bool:
        """Block until every live session has reached a terminal state
        (done, failed, cancelled, or migrated) and the arrival queue is
        empty. Only meaningful while draining."""
        return self._drained.wait(timeout_s)

    def kv_cache_stats(self) -> dict:
        """Point-in-time paged-KV accounting for /metrics: pool
        occupancy plus the radix cache's monotonic hit/miss/eviction
        counters (the server turns the latter into Prometheus counters
        by delta at scrape time). Callable from any thread — the pool
        and trie take their own locks."""
        stats = self._radix.stats()
        stats["blocks_in_use"] = self._pool.used_blocks
        stats["blocks_free"] = self._pool.free_blocks
        stats["pool_bytes"] = self.kv_pool_bytes
        stats["quant_blocks"] = self.quant_blocks_total
        return stats

    def cache_summary(self) -> dict:
        """Capped radix-summary advertisement (fingerprints + version)
        for the fleet router — served at the inference server's
        ``/cache/summary`` and embedded in stats_summary for the
        heartbeat path. Callable from any thread; the trie takes its
        own lock."""
        return self._radix.summary()

    def import_prefix(self, tokens: list[int], pages_k: np.ndarray,
                      pages_v: np.ndarray,
                      timeout_s: float = 10.0,
                      scales_k: np.ndarray | None = None,
                      scales_v: np.ndarray | None = None,
                      kv_dtype: str = "bf16",
                      start_block: int = 0) -> tuple[int, str | None]:
        """Land a remotely prefilled prefix in the local pool + radix
        cache (disaggregated prefill, disagg/). Callable from any
        thread: the scatter is staged for the scheduler thread — the
        only ``_state`` writer — and this call waits for it. Returns
        ``(blocks_imported, reason)``; reason is None on success, else
        a low-cardinality fallback label. Never raises: every failure
        here just means the request prefills locally (token-identical
        by the determinism contract).

        ``tokens`` must cover exactly the imported full blocks and
        ``pages_k``/``pages_v`` be ``[L, n, block_size, n_kv, D]`` in
        the cache dtype — the caller (disagg.client) has already
        verified the fingerprint chain, so a shape mismatch here means
        a mis-configured fleet, not corruption.

        ``start_block`` supports CHUNKED imports (wire v3, live-session
        migration): the pages cover blocks ``[start_block, start_block
        + n)`` of ``tokens``, and the first ``start_block`` blocks must
        already be in the radix cache (landed by the previous chunks) —
        a chunk whose base prefix was evicted between chunks fails with
        ``missing_prefix`` rather than caching a chain with a hole."""
        if start_block < 0:
            return 0, "shape_mismatch"
        if kv_dtype != self.kv_dtype:
            # cross-dtype pages are structurally unusable (an int8 page
            # without its scales, or bf16 pages a quantized pool would
            # have to requantize blind) — reject before staging so the
            # caller counts a low-cardinality fallback and prefills
            # locally
            return 0, "kv_dtype_mismatch"
        if pages_k.ndim != 5 or pages_k.shape != pages_v.shape:
            return 0, "shape_mismatch"
        n = int(pages_k.shape[1])
        if n == 0 or start_block + n > self.max_blocks or \
                len(tokens) != (start_block + n) * self.block_size:
            return 0, "shape_mismatch"
        if kv_dtype == "int8":
            want_s = (pages_k.shape[0], n, pages_k.shape[3])
            if (
                scales_k is None or scales_v is None
                or tuple(scales_k.shape) != want_s
                or tuple(scales_v.shape) != want_s
            ):
                return 0, "shape_mismatch"
        if self._stop.is_set() or self._thread is None:
            return 0, "stopped"
        task = _ImportTask(list(tokens), pages_k, pages_v,
                           scales_k=scales_k, scales_v=scales_v,
                           start_block=start_block)
        with self._lock:
            self._imports.append(task)
        self._note("import_staged", blocks=n)
        if not task.done.wait(timeout_s):
            # the scheduler may still service the task later — that
            # only warms the trie; the caller stops waiting and
            # prefills locally
            return 0, "timeout"
        return task.imported, task.reason

    def _step_import(self) -> None:
        """Service at most ONE staged KV import per scheduler pass —
        the same pass quantum as chunked prefill, so a burst of imports
        never starves the decode batch. Runs on the scheduler thread
        only (the sole ``_state`` writer); alloc → scatter → trie
        insert → drop our alloc hold, leaving the imported blocks at
        trie-only refcount exactly like a parked prefix: LRU-evictable,
        never pool-pinning. Spans already cached keep the existing
        trie nodes and our duplicate fresh blocks free right back —
        dedup by construction (freed blocks hold junk pages, harmless:
        every owned block is fully rewritten before any read)."""
        with self._lock:
            task = self._imports.pop(0) if self._imports else None
        if task is None:
            return
        n = int(task.pages_k.shape[1])
        L = len(self._state.caches_k)
        _nb, bs, n_kv, D = self._state.caches_k[0].shape
        want = (L, n, bs, n_kv, D)
        cache_dt = np.dtype(self._state.caches_k[0].dtype)
        if (
            np.dtype(task.pages_k.dtype) != cache_dt
            or np.dtype(task.pages_v.dtype) != cache_dt
        ):
            # distinct from shape_mismatch: a dtype disagreement means
            # the fleet mixes kv_dtype configurations, which the wire's
            # version negotiation should have caught upstream
            task.reason = "kv_dtype_mismatch"
            self._note("import_reject", blocks=n, reason=task.reason)
            task.done.set()
            return
        if (
            tuple(task.pages_k.shape) != want
            or tuple(task.pages_v.shape) != want
        ):
            task.reason = "shape_mismatch"
            self._note("import_reject", blocks=n, reason=task.reason)
            task.done.set()
            return
        # trie/pool mutations take _lock (HTTP threads walk the trie in
        # cache_summary); the jit scatter between them stays OFF-lock —
        # only this thread allocs, so the two sections can't interleave
        start = task.start_block
        with self._lock:
            shared: list[int] = []
            if start:
                # chunked import (wire v3): this chunk stacks on the
                # blocks the previous chunks inserted. The trie walk
                # refs its matches (ours until the final insert/unref
                # below); fewer matches than start_block means the base
                # was evicted between chunks — reject rather than cache
                # a chain with a hole, the importer restarts the prefix
                matched = self._radix.match(
                    task.tokens[: start * self.block_size]
                )
                if len(matched) < start:
                    if matched:
                        self._pool.unref(matched)
                    task.reason = "missing_prefix"
                    self._note("import_reject", blocks=n,
                               reason=task.reason)
                    task.done.set()
                    return
                shared = matched[:start]
                if len(matched) > start:
                    self._pool.unref(matched[start:])
            if not self._radix.ensure_free(n):
                if shared:
                    self._pool.unref(shared)
                task.reason = "backpressure"
                self._note("import_reject", blocks=n, reason=task.reason)
                task.done.set()
                return
            fresh = self._pool.alloc(n)
        table_row = np.zeros(self.max_blocks, np.int32)
        table_row[:n] = fresh
        own_mask = np.zeros(self.max_blocks, bool)
        own_mask[:n] = True
        pk = np.zeros((L, self.max_blocks, bs, n_kv, D), cache_dt)
        pk[:, :n] = task.pages_k
        pv = np.zeros((L, self.max_blocks, bs, n_kv, D), cache_dt)
        pv[:, :n] = task.pages_v
        # all-ones padding keeps null-block scales at their init value;
        # the bf16 pytree carries no scale leaves and jit drops these
        sk = np.ones((L, self.max_blocks, n_kv), np.float32)
        sv = np.ones((L, self.max_blocks, n_kv), np.float32)
        if task.scales_k is not None:
            sk[:, :n] = task.scales_k
            sv[:, :n] = task.scales_v
        # lint: allow[lock-discipline] scheduler thread is the only _state writer; see _loop
        self._state = _import_blocks(
            self._state, jnp.asarray(table_row), jnp.asarray(own_mask),
            jnp.asarray(pk), jnp.asarray(pv),
            jnp.asarray(sk), jnp.asarray(sv),
        )
        with self._lock:
            # the insert covers the WHOLE chain so far (shared base +
            # this chunk); the trie takes its own reference per block
            # and both our holds return here, leaving the chain at
            # trie-only refcount — LRU-evictable like any parked prefix
            created = self._radix.insert(task.tokens, shared + fresh)
            self._pool.unref(shared + fresh)
        self.imports_total += 1
        self.imported_blocks_total += n
        task.imported = n
        self._note("import", blocks=n, created_nodes=created,
                   start_block=start)
        task.done.set()

    def scheduler_stats(self) -> dict:
        """Preemption/chunking accounting for /metrics: monotonic
        preempt/resume/chunk counters (the server converts them by
        delta at scrape time) plus the instantaneous chunk-queue and
        parked-row depths. Lockless reads, same torn-read tolerance as
        stats_summary — a scrape must never stall behind an admit
        compile."""
        return {
            "preempted": self.preempted_total,
            "resumed": self.resumed_total,
            "chunks": self.chunks_total,
            "chunk_queue": len(self._prefills),
            "parked": len(self._parked),
            # fused decode dispatches (each covers 1..max_window steps)
            "windows": self.windows_total,
            # verify-window accounting (speculative decode on the paged
            # batch): proposed / host-accepted draft tokens and windows
            # that rolled at least one draft back
            "spec_draft_tokens": self.spec_draft_tokens,
            "spec_accepted_tokens": self.spec_accepted_tokens,
            "spec_rollbacks": self.spec_rollbacks,
            # disaggregated prefill: serviced imports / blocks landed
            "kv_imports": self.imports_total,
            "kv_imported_blocks": self.imported_blocks_total,
            # live-session migration: sessions handed off, chunks and
            # blocks streamed out (drain/evacuate/rebalance paths)
            "migrated": self.migrated_total,
            "migration_chunks": self.migration_chunks_total,
            "migration_blocks": self.migration_blocks_total,
        }

    def _span_ids(self, req: "_Request") -> dict:
        """Fleet-join attrs carried by every engine span: the request
        id under the flight ring's literal ``req`` key (so spans and
        flight decisions correlate by the same id), plus the replica
        name when this engine has one — fleetview groups a merged
        trace's hops per replica by that attr. No replica_name = no
        attr, so single-engine traces are unchanged."""
        ids = {"req": req.rid}
        if self.replica_name is not None:
            ids["replica"] = self.replica_name
        return ids

    def _note(self, kind: str, **detail) -> None:
        """Flight-recorder entry with queue depth + pool occupancy
        observed NOW. Callable from any thread: qsize and the pool
        counters each take their own locks; the holdover is not folded
        in (reading it here would need the engine lock from submit()'s
        HTTP threads — queue_depth is a decision-time signal, not an
        accounting invariant)."""
        self.flight.note(
            kind,
            queue_depth=self._queue.qsize(),
            kv_in_use=self._pool.used_blocks,
            kv_free=self._pool.free_blocks,
            **detail,
        )

    def slo_burn(self) -> float:
        """Worst burn rate across every objective and window — the
        scalar the reconciler's evacuation pass thresholds on (a
        replica persistently burning error budget gets drained before
        it starts failing requests outright). 0.0 without an SLO
        monitor or without traffic; callable from any thread."""
        if self._slo is None:
            return 0.0
        rates = self._slo.burn_rates()
        worst = 0.0
        for per_window in rates.values():
            for rate in per_window.values():
                worst = max(worst, float(rate))
        return worst

    def stats_summary(self, window_s: float = 60.0) -> dict:
        """One-dict replica serving summary for the node agent's
        NodeState heartbeat (and /debug callers): occupancy, queue
        depth, goodput, free blocks, prefix hit rate. Everything here
        is advertised to the control-plane store, where ROADMAP item 4's
        prefix-cache-aware router and the reconciler's cost tensor can
        finally see per-replica load. Plain JSON-serializable scalars
        only — NodeState.to_dict embeds it verbatim."""
        prof = self.profiler.summary(window_s=window_s)
        kv = self.kv_cache_stats()
        # lockless holdover/parked peeks: the engine lock is held across
        # admit jit compiles (potentially tens of seconds) and a
        # heartbeat must never stall behind one; a torn read here only
        # skews queue_depth by 1 for one sample. Parked rows count as
        # waiting — they hold no slot and need a readmit to progress.
        waiting = len(self._holdover) + len(self._parked)
        lookups = kv["hits"] + kv["misses"]
        return {
            "n_slots": self.n_slots,
            "block_size": self.block_size,
            # device layout, advertised so the fleet router / capacity
            # dashboards can tell a tp=4 replica's pool shard from a
            # single-device pool of the same logical block count
            "tp_degree": self.layout.tp,
            "mesh_devices": self.layout.mesh_devices,
            "queue_depth": self._queue.qsize() + waiting,
            "batch_occupancy": round(prof["batch_occupancy"], 6),
            "goodput_tokens_per_sec": round(
                prof["goodput_tokens_per_sec"], 6
            ),
            "padding_waste_frac": round(prof["padding_waste_frac"], 6),
            "kv_blocks_free": kv["blocks_free"],
            "kv_blocks_in_use": kv["blocks_in_use"],
            "kv_dtype": self.kv_dtype,
            "kv_pool_bytes": kv["pool_bytes"],
            # weight precision axis + resident param footprint: the
            # capacity twin of the kv fields above, so fleet dashboards
            # and the router can tell an int8-weights replica (≈2x
            # model headroom) from a bf16 one on the same heartbeat
            "weight_dtype": self.weight_dtype,
            "model_param_bytes": self.model_param_bytes,
            "prefix_hit_rate": round(
                kv["hits"] / lookups if lookups else 0.0, 6
            ),
            "prefix_cached_tokens": kv["cached_tokens"],
            # drain awareness for the router (skip for new work) and
            # the reconciler's evacuation trigger — both ride the same
            # heartbeat this dict feeds
            "draining": bool(self._draining),
            "slo_burn": round(self.slo_burn(), 6),
            # the router's prefix-affinity signal, already capped at
            # kv_blocks.SUMMARY_FINGERPRINT_BUDGET so a big trie cannot
            # bloat the store write this dict rides in (the node agent
            # re-clamps defensively — the callback is injectable)
            "cache_summary": self._radix.summary(),
        }

    def prewarm_spec(self, group_sizes: tuple[int, ...] = (1,),
                     prompt_len: int = 8, max_new_tokens: int = 8,
                     sampled: bool = False) -> int:
        """Compile the draft-group path for the given group sizes BEFORE
        traffic arrives (class docstring: the first group of a new shape
        otherwise compiles on the scheduler thread, stalling every
        in-flight slot request behind it). Runs ``start_group`` plus one
        ``step_group`` round per size on dummy prompts and discards the
        results; the jit caches are process-global, so one warm covers
        all later groups of that ``(B, bucket, cache_len)`` shape.
        ``sampled=True`` warms the sampled trace instead of the greedy
        one (the greedy/sampled split is a static trace flag — they
        compile separately). Call before serving; returns the number of
        shapes warmed. No-op without a speculative engine."""
        if self.speculative is None:
            return 0
        warmed = 0
        for b in group_sizes:
            b = int(b)
            if b < 1 or not self.speculative.fits(prompt_len, max_new_tokens):
                continue
            g = self.speculative.start_group(
                [[1] * prompt_len] * b,
                max_new_tokens=max_new_tokens,
                temperatures=0.7 if sampled else 0.0,
            )
            self.speculative.step_group(g)
            warmed += 1
        return warmed

    def start(self) -> "ContinuousEngine":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="continuous-batcher"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        # release every waiter AS FAILURES: queued requests never
        # admitted and in-slot requests mid-decode would otherwise block
        # their callers for the full generate() timeout — and a
        # truncated token list must not read as a normal completion
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.failed = "engine stopped before the request was served"
            self._note("fail", req=req.rid, reason="engine stopped")
            req.done.set()
        # the join above can expire behind a long jit compile, leaving
        # the scheduler live — and the scheduler may PUBLISH a slot or
        # group after this sweep ran (admission was mid-compile during
        # the snapshot). The loop's epilogue runs the same sweep from
        # the scheduler thread when it observes _stop, so whichever
        # side sees the published state last releases the waiters.
        self._fail_inflight()

    def _fail_inflight(self) -> None:
        """Fail over every published in-flight request (slots, live
        group, holdover, parked rows, chunked prefills) — shared by
        stop() and the scheduler loop's epilogue; all handoff fields
        are swapped under the lock."""
        failed = 0
        with self._lock:
            held = list(self._holdover)
            self._holdover.clear()
            parked, self._parked = self._parked, []
            # staged admissions hold pool references but no slot yet:
            # release the planned blocks and fail the requests (they
            # were popped from the pending order, so nothing else will
            # serve them)
            staged, self._staged = self._staged, []
            # staged KV imports hold no pool references yet (alloc
            # happens in _step_import); releasing their waiters is the
            # whole cleanup
            imports, self._imports = self._imports, []
            # chunked-prefill tasks' requests are already published in
            # _slot_req (the slot is reserved at plan time), so the
            # slot sweep below releases them; only the task list needs
            # clearing so a mid-compile chunk cannot be re-dispatched
            self._prefills.clear()
            group, self._spec_group = self._spec_group, None
            for slot, req in enumerate(self._slot_req):
                if req is not None:
                    self._slot_req[slot] = None
                    req.failed = "engine stopped mid-generation"
                    self._note("fail", req=req.rid,
                               reason="stopped mid-generation")
                    req.done.set()
                    failed += 1
        for holdover in held:
            holdover.failed = "engine stopped before the request was served"
            # lint: allow[protocol-order] consecutive sweeps fail DISTINCT request populations (slots, holdover, parked, staged, group); each chain sees exactly one fail
            self._note("fail", req=holdover.rid, reason="stopped unserved")
            holdover.done.set()
            failed += 1
        for req in parked:
            # parked requests carry partial output: fail, never return
            # a truncated token list as a normal completion
            req.failed = "engine stopped mid-generation"
            # lint: allow[protocol-order] distinct population from the holdover sweep above
            self._note("fail", req=req.rid, reason="stopped while parked")
            req.done.set()
            failed += 1
        for req, _slot, kv_plan, _tokens in staged:
            table_row, _own, _reuse, total, _spec = kv_plan
            self._pool.unref([int(b) for b in table_row[:total]])
            req.failed = "engine stopped before the request was served"
            # lint: allow[protocol-order] distinct population from the parked sweep above
            self._note("fail", req=req.rid, reason="stopped while staged")
            req.done.set()
            failed += 1
        for task in imports:
            task.reason = "stopped"
            task.done.set()
        if group is not None:
            for req in group[0]:
                req.failed = "engine stopped mid-generation"
                # lint: allow[protocol-order] distinct population from the staged sweep above
                self._note("fail", req=req.rid,
                           reason="stopped mid spec-group")
                req.done.set()
                failed += 1
        if failed:
            # auto-dump the flight recorder: the post-mortem needs the
            # scheduler's last decisions in the log stream even if the
            # process dies before anyone curls /debug/flightrecorder.
            # Guarded on failed>0 so the stop()+epilogue double
            # invocation dumps at most once (the second sweep finds
            # nothing published).
            self._note("fail_inflight", failed=failed)
            log.warning(
                "engine stopped with %d in-flight request(s); "
                "flight recorder dump:\n%s", failed, self.flight.render(),
            )

    # -- scheduler loop ---------------------------------------------------

    def _plan_kv(self, tokens: list[int], max_new: int, rid: int = -1):
        """Host-side paged-admit plan: radix match → capacity clamp →
        evict/alloc. ``tokens`` is the EFFECTIVE prompt — the original
        prompt for a fresh admit, prompt + generated-so-far for a
        parked readmit (whose park inserted those full blocks into the
        trie, so the match below recovers them with zero recompute) —
        and ``max_new`` the REMAINING budget, so the block horizon is
        identical across preemptions. Returns ``(table_row, own_mask,
        reuse, total, spec_ok)`` — the static-shape operands
        ``_admit_slot`` needs plus whether verify slack was reserved —
        or None when the pool cannot supply the fresh blocks
        (admission backpressure; unreachable with the __init__ sizing
        floor but kept for custom pools). On success the slot holds one
        reference per block in ``table_row[:total]``.

        Verify slack: a verify window scatters KV up to position
        ``offset + spec_k``, past the plain decode horizon, so a
        spec-capable slot holds ceil((p + max_new + spec_k) / bs)
        blocks. The slack is best-effort — under pool pressure the plan
        falls back to the plain horizon with ``spec_ok=False`` and the
        slot simply decodes through decode_window (degraded throughput,
        never degraded correctness)."""
        p = len(tokens)
        bs = self.block_size
        matched = self._radix.match(tokens)  # +1 ref each, ours now
        # full blocks only, and never the whole prompt: the last token
        # must be recomputed so the admit has logits to sample from
        reuse = min(len(matched), (p - 1) // bs)
        # the suffix pads to a bucket and the prefill window must fit
        # the logical cache: shrinking reuse widens the recompute
        # window, terminating by submit()'s guarantee that the cold
        # bucket fits. Buckets stay canonical (engine._bucket) so warm
        # admits share the cold traces' compile cache.
        while reuse > 0 and reuse * bs + _bucket(p - reuse * bs) > \
                self.cache_len:
            reuse -= 1
        if reuse < len(matched):
            self._pool.unref(matched[reuse:])
        shared = matched[:reuse]
        plain = -(-(p + max_new) // bs)  # ceil; fits() bounds it
        spec_ok = (
            self.spec_draft is not None
            and p + max_new + self.spec_k <= self.cache_len
        )
        total = -(-(p + max_new + self.spec_k) // bs) if spec_ok else plain
        ev_before = self._radix.stats()["evictions"]
        if not self._radix.ensure_free(total - reuse):
            # drop the verify slack first: a spec-capable plan must
            # never fail an admission the plain plan could serve
            if spec_ok and total > plain and \
                    self._radix.ensure_free(plain - reuse):
                total, spec_ok = plain, False
            else:
                if shared:
                    self._pool.unref(shared)
                # the fail-fast precheck (kv_blocks.ensure_free) means
                # this fires WITHOUT stripping the trie when the
                # shortfall is structural; the detail says which case
                # the post-mortem is looking at (free+evictable < need
                # = pinned by live rows)
                self._note("backpressure", req=rid, prompt_tokens=p,
                           need_blocks=total - reuse,
                           free_blocks=self._pool.free_blocks,
                           evictable_blocks=self._radix.evictable_blocks(),
                           reason="pool pinned beyond eviction reach")
                return None
        evicted = self._radix.stats()["evictions"] - ev_before
        if evicted:
            self._note("evict", nodes=evicted, need_blocks=total - reuse)
        fresh = self._pool.alloc(total - reuse)
        self._radix.note_result(reuse)
        table_row = np.zeros(self.max_blocks, np.int32)
        table_row[:reuse] = shared
        table_row[reuse:total] = fresh
        own_mask = np.zeros(self.max_blocks, bool)
        own_mask[reuse:total] = True
        return table_row, own_mask, reuse, total, spec_ok

    def _admit(self, slot: int, req: _Request, kv_plan,
               tokens: list[int]) -> None:
        """Reserve ``slot`` for ``req`` and start its prefill. With
        chunking enabled and a long novel suffix, only a task is queued
        — ``_step_prefill`` dispatches one chunk per scheduler pass so
        decode steps interleave; otherwise (short suffix, chunking off)
        the whole suffix goes through ``_finalize_admit`` in one
        dispatch, exactly the pre-chunking admit."""
        table_row, own_mask, reuse, total, spec_ok = kv_plan
        resumed = bool(req.out_tokens)
        if not resumed:
            # first admission only: a readmit is not a queue exit (the
            # request's TTFT clock kept running while parked — it
            # already has tokens)
            req.t_admit = tracing.now()
            _TRACER.record_span(
                "engine.queue_wait", start=req.t_submit, end=req.t_admit,
                parent=req.trace_parent, slot=slot,
                **self._span_ids(req),
            )
            if self._slo is not None:
                self._slo.observe(
                    "queue_wait", req.t_admit - req.t_submit,
                    t=req.t_admit,
                )
        self._slot_req[slot] = req
        self._slot_blocks[slot] = [int(b) for b in table_row[:total]]
        # the flag flips TRUE only when _finalize_admit also committed
        # the draft row; until then the slot is mid-prefill (inactive)
        # and never counted by the verify gate anyway
        self._slot_spec_ok[slot] = False
        req.tokens_at_admit = len(req.out_tokens)
        task = _PrefillTask(
            req=req, slot=slot, table_row=table_row, own_mask=own_mask,
            reuse=reuse, total=total, pos=reuse * self.block_size,
            tokens=tokens, resumed=resumed, spec_ok=spec_ok,
        )
        if self._next_chunk_len(task) is not None:
            self._prefills.append(task)
            return
        self._finalize_admit(task)

    def _next_chunk_len(self, task: _PrefillTask) -> int | None:
        """Chunk width for ``task``'s next dispatch, or None when the
        remaining suffix should finalize through ``_admit_slot``. A
        chunk is taken only while the POST-chunk tail still pads to a
        canonical bucket that fits the logical row — otherwise the
        final suffix is simply taken larger (still a canonical bucket,
        so the compile-shape set stays {C} ∪ prefill buckets)."""
        C = self.chunk_tokens
        if not C:
            return None
        rem = len(task.tokens) - task.pos
        if rem <= C:
            return None
        if task.pos + C + _bucket(rem - C) > self.cache_len:
            return None
        return C

    def _step_prefill(self) -> None:
        """Advance the oldest chunked prefill by AT MOST one dispatch —
        the scheduler's pass quantum, so a long cold prompt never
        blocks the decode batch for more than one chunk's latency
        (Sarathi-SC's stall-free schedule, PAPERS.md)."""
        with self._lock:
            task = self._prefills[0] if self._prefills else None
        if task is None:
            return
        if task.req.cancelled.is_set():
            with self._lock:
                if self._prefills and self._prefills[0] is task:
                    self._prefills.pop(0)
                    self._abort_prefill(task)
            return
        C = self._next_chunk_len(task)
        if C is None:
            with self._lock:
                if not self._prefills or self._prefills[0] is not task:
                    return  # stop() cleared the queue mid-pass
                self._prefills.pop(0)
                # lint: allow[blocking-under-lock] the tail-bucket admit compile (tens of seconds cold) deliberately spans _lock: slot tables and the prefill queue must swap atomically vs stop(); deployments prewarm (see class docstring)
                self._finalize_admit(task)
            return
        window = np.asarray(
            task.tokens[task.pos:task.pos + C], np.int32
        )[None]
        t0 = tracing.now()
        # device work outside the lock (first chunk of a width pays its
        # compile; stop() must still be able to fail the slots)
        # lint: allow[lock-discipline] scheduler thread is the only _state writer; see _loop
        self._state = _prefill_chunk(
            self.params, self._state, jnp.asarray(window),
            jnp.int32(task.pos), self.cfg,
            jnp.asarray(task.table_row), jnp.asarray(task.own_mask),
            wq_gspmd=self._sharded,
        )
        task.pos += C
        self.chunks_total += 1
        t1 = tracing.now()
        with self._lock:
            live_rows = sum(1 for r in self._slot_req if r is not None)
        # every chunk token is live prompt work — no bucket padding by
        # construction (intermediate chunks are exactly C tokens)
        self.profiler.record(
            "chunk", bucket=C, live_rows=live_rows,
            live_tokens=C, padded_tokens=0, start=t0, end=t1,
        )
        self._note("chunk", req=task.req.rid, slot=task.slot,
                   pos=task.pos, prompt_tokens=len(task.tokens))

    def _abort_prefill(self, task: _PrefillTask) -> None:
        """Drop a cancelled mid-chunk prefill (caller holds the lock).
        The row was never activated — its table is still all-null and
        ``active`` False — so releasing the block holds is the whole
        cleanup; no device state to touch."""
        slot, req = task.slot, task.req
        self._slot_req[slot] = None
        self._slot_spec_ok[slot] = False
        blocks, self._slot_blocks[slot] = self._slot_blocks[slot], []
        if blocks:
            self._pool.unref(blocks)
        req.t_done = tracing.now()
        self._note("retire", req=req.rid, slot=slot,
                   tokens=len(req.out_tokens),
                   freed_blocks=len(blocks), cancelled=True)
        req.done.set()

    def _finalize_admit(self, task: _PrefillTask) -> None:
        """Prefill the remaining suffix, sample the next token, and
        flip the slot live — one ``_admit_slot`` dispatch (caller holds
        the lock). For a resumed request the suffix counter equals the
        uninterrupted run's decode counter at the same position
        (_admit_slot folds prompt_len == original prompt + generated;
        stepper.decode_body folds offset + 1), so preempted and uninterrupted
        runs draw identical sampling noise — the token-identity
        invariant the preemption tests pin."""
        req, slot, tokens = task.req, task.slot, task.tokens
        reuse, total = task.reuse, task.total
        p = len(tokens)
        start = task.pos
        suffix_len = p - start
        t0 = tracing.now()
        T = _bucket(suffix_len)  # _next_chunk_len kept start + T fitting
        padded = np.zeros((1, T), np.int32)
        padded[0, :suffix_len] = tokens[start:]
        # full effective-prompt id set computed host-side: the jit only
        # sees the suffix, but repetition penalty must cover reused and
        # pre-preemption tokens too
        seen_row = np.zeros((1, self.cfg.vocab_size), bool)
        seen_row[0, np.asarray(tokens, np.int64)] = True
        # explicit impl: stepper.sample_rows wraps with threefry2x32 and
        # SlotState.rng is u32[B, 2]; deriving from the default-impl
        # PRNGKey would break under jax_default_prng_impl=rbg (u32[4])
        key_data = jax.random.key_data(
            jax.random.key(req.seed, impl="threefry2x32")
        ).astype(jnp.uint32)
        self._state = _admit_slot(
            self.params, self._state, jnp.asarray(padded),
            jnp.int32(suffix_len), jnp.int32(start), jnp.int32(p),
            self.cfg, jnp.int32(slot),
            jnp.asarray(task.table_row), jnp.asarray(task.own_mask),
            jnp.float32(req.temperature), jnp.int32(req.top_k),
            jnp.float32(req.top_p), jnp.float32(req.rep_penalty), key_data,
            jnp.asarray(seen_row), wq_gspmd=self._sharded,
        )
        if self.spec_draft is not None and task.spec_ok:
            # draft-row prefill rides the same boundary: the draft has
            # no radix reuse (and no chunking — it is small enough not
            # to need either), so the FULL effective prompt recomputes
            # in one dispatch, compiled per full-prompt bucket. The
            # bucket fits by the same guards that admitted the target
            # (submit's fits() for fresh prompts, _pick_victim's bucket
            # check for readmits).
            Td = _bucket(p)
            dwin = np.zeros((1, Td), np.int32)
            dwin[0, :p] = tokens
            self._dstate = _admit_draft(
                self._dparams, self._dstate, jnp.asarray(dwin),
                jnp.int32(p), self._dcfg, jnp.int32(slot),
            )
            self._slot_spec_ok[slot] = True
        # cache the effective prompt's FULL blocks for later admits —
        # including this one's fresh blocks (their KV is committed by
        # the scatter above; the partial tail block stays private)
        full = p // self.block_size
        if self.kv_dtype == "int8":
            # every owned full block was quantize-committed by the
            # scatter above (chunked prefills requantize the same
            # blocks — one logical commit, counted once here)
            self.quant_blocks_total += max(0, full - reuse)
        if full:
            self._radix.insert(
                tokens, [int(b) for b in task.table_row[:full]]
            )
        if req.export_kv and full:
            # disaggregated prefill export (disagg/): capture the
            # committed full-block pages HERE — the scheduler thread is
            # the only safe _state reader (jit donation deletes buffers
            # under any racing HTTP-thread read), and right after the
            # insert above the trie holds exactly these blocks. The
            # fingerprints ride out of the trie walk
            # (match_with_fingerprints) so the wire's content addresses
            # are the very chain the router and importers recompute.
            idx = jnp.asarray(
                np.asarray(task.table_row[:full], np.int32)
            )
            pages_k = np.stack([
                # lint: allow[host-sync] export capture: the prefilled pages must reach host memory before the request completes (one gather per layer, prefill-only requests never decode)
                np.asarray(ck[idx]) for ck in self._state.caches_k
            ])
            pages_v = np.stack([
                # lint: allow[host-sync] export capture (same boundary as pages_k above)
                np.asarray(cv[idx]) for cv in self._state.caches_v
            ])
            pairs = self._radix.match_with_fingerprints(
                tokens[:full * self.block_size]
            )
            # the walk refs its matches for us; the slot already holds
            # these blocks, so the extra hold is returned immediately
            self._pool.unref([b for b, _ in pairs])
            req.kv_export = {
                "pages_k": pages_k,
                "pages_v": pages_v,
                "fingerprints": [fp for _, fp in pairs],
                "block_size": self.block_size,
                "kv_dtype": self.kv_dtype,
            }
            if self.kv_dtype == "int8":
                # committed pages are int8 — the scales travel with
                # them so the importer lands bit-identical blocks (the
                # partial tail block is NOT in table_row[:full] and
                # never leaves the engine in bf16)
                req.kv_export["scales_k"] = np.stack([
                    # lint: allow[host-sync] export capture (same boundary as pages_k above)
                    np.asarray(sk[idx]) for sk in self._state.scales_k
                ])
                req.kv_export["scales_v"] = np.stack([
                    # lint: allow[host-sync] export capture (same boundary as pages_k above)
                    np.asarray(sv[idx]) for sv in self._state.scales_v
                ])
        # the prefill already produced the next generated token —
        # except in prefill-only mode (max_new == 0, the disagg export
        # role), where the sampled token is discarded: the request's
        # contract is "KV cached, nothing generated", and the decode
        # replica resamples token #1 itself from the identical
        # distribution (committed-blocks rule: it recomputes the last
        # prompt position)
        # lint: allow[host-sync] admission boundary: the first token must reach the request result now
        first = int(self._state.last_token[slot])
        now = tracing.now()
        if req.max_new > 0:
            req.out_tokens.append(first)
            req.token_times.append(now)
        # a preemption readmit keeps the stamp from its original admit,
        # but a server-level resume (migration hand-off) never had one
        # in THIS engine — without the stamp here the server's TTFT
        # breakdown degrades to whole-request duration and the
        # import-vs-reprefill comparison measures the decode tail
        if not req.t_first:
            req.t_first = now
        # one profiler record per prefill dispatch, bracketing the
        # _admit_slot call + its host sync above. The dispatch's one
        # live token is the sampled token; the padding waste is the
        # bucket tail (T - suffix_len) the static shapes force us to
        # compute.
        live_rows = sum(1 for r in self._slot_req if r is not None)
        self.profiler.record(
            "prefill", bucket=T, live_rows=live_rows,
            live_tokens=suffix_len, padded_tokens=T - suffix_len,
            start=t0, end=now,
        )
        if task.resumed:
            self.resumed_total += 1
            self._note("resume", req=req.rid, slot=slot, suffix_bucket=T,
                       reuse_blocks=reuse, total_blocks=total,
                       preemptions=req.preemptions)
        else:
            self._note("admit", req=req.rid, slot=slot, suffix_bucket=T,
                       reuse_blocks=reuse, total_blocks=total)
        # span start: a FRESH admission's prefill phase begins at
        # t_admit — exactly where engine.queue_wait ends (the serving
        # breakdown is contiguous by construction, and with chunking
        # the intermediate chunk dispatches belong inside the prefill
        # phase). A readmit never exited a queue, so its span brackets
        # just the finalize dispatch.
        sp = _TRACER.start_span(
            "engine.prefill", parent=req.trace_parent,
            start=t0 if task.resumed else req.t_admit,
            slot=slot, prompt_tokens=p, bucket=T,
            reused_tokens=reuse * self.block_size, prefix_hit=reuse > 0,
            **self._span_ids(req),
        )
        sp.event("first-token", ts=now)
        _TRACER.finish(sp, end=now)
        self._maybe_retire(slot)

    def _maybe_retire(self, slot: int) -> None:
        req = self._slot_req[slot]
        if req is None:
            return
        finished = (
            req.cancelled.is_set()
            or len(req.out_tokens) >= req.max_new
            or (
                req.eos_id >= 0 and req.out_tokens
                and req.out_tokens[-1] == req.eos_id
            )
        )
        if finished:
            self._slot_req[slot] = None
            self._slot_spec_ok[slot] = False
            # a drain may have been streaming this slot; the slot id is
            # about to be reusable, and a stale cursor would make a
            # later drain stream the wrong blocks
            self._migrate_cursor.pop(slot, None)
            blocks, self._slot_blocks[slot] = self._slot_blocks[slot], []
            if blocks:
                # drop the slot's hold; blocks also cached in the trie
                # keep the trie's reference and stay reusable
                self._pool.unref(blocks)
            self._state = dataclasses.replace(
                self._state,
                active=self._state.active.at[slot].set(False),
                # the row's table goes all-null BEFORE its next decode
                # scatter: freed blocks may be re-issued to another
                # slot, and a stale table would keep writing into them
                tables=self._state.tables.at[slot].set(0),
            )
            req.t_done = tracing.now()
            self._note("retire", req=req.rid, slot=slot,
                       tokens=len(req.out_tokens),
                       freed_blocks=len(blocks),
                       cancelled=req.cancelled.is_set())
            sp = _TRACER.start_span(
                "engine.decode", parent=req.trace_parent,
                start=req.t_first or req.t_done, slot=slot,
                tokens=len(req.out_tokens),
                cancelled=req.cancelled.is_set(),
                **self._span_ids(req),
                # stamped whenever any token event below carries an
                # interpolated timestamp (fused windows observe one
                # bracket per K tokens, not one clock read per token) —
                # trace readers must not treat the events as per-step
                # measurements (docs/OBSERVABILITY.md, TPOT row)
                **({"kubeinfer.interpolated": True}
                   if req.interpolated else {}),
                **({"kubeinfer.spec_accepted": req.spec_accepted,
                    "kubeinfer.spec_rollbacks": req.spec_rollbacks}
                   if self.spec_draft is not None else {}),
            )
            for i, ts in enumerate(req.token_times[:_MAX_TOKEN_EVENTS]):
                sp.event("token", ts=ts, i=i)
            _TRACER.finish(sp, end=req.t_done)
            req.done.set()

    # -- preemptive scheduling --------------------------------------------

    def _park_slot(self, slot: int) -> None:
        """Preempt a decoding row: bump its committed full blocks into
        the radix trie (the trie's own +1 reference), release every
        slot hold, and free the slot. The readmit later radix-matches
        those exact blocks, so an unevicted park costs only the partial
        tail block's recompute — vLLM preempts by recomputing the WHOLE
        sequence; the trie is what makes parking nearly free here.
        Parked blocks sit at trie-only refcount 1, i.e. they stay LRU-
        evictable: a parked row can never pin the pool (eviction only
        degrades its resume toward a colder admit, never correctness).
        Caller holds the engine lock; lock order engine→radix→pool is
        preserved through the insert/unref below."""
        req = self._slot_req[slot]
        if req is None:
            return
        toks = req.prompt + req.out_tokens
        blocks, self._slot_blocks[slot] = self._slot_blocks[slot], []
        # the LAST generated token's KV is not committed yet (the next
        # decode step would have written it at the row's offset), so
        # only blocks fully inside [0, len-1) may enter the trie — a
        # block-aligned park would otherwise cache a block whose final
        # position is junk, poisoning every later content-addressed
        # match of it (the readmit itself recomputes the tail, but a
        # LONGER continuation would reuse the poisoned block verbatim)
        committed = toks[:-1]
        full = len(committed) // self.block_size
        if full:
            self._radix.insert(committed, blocks[:full])
        self._slot_req[slot] = None
        self._slot_spec_ok[slot] = False
        self._migrate_cursor.pop(slot, None)  # slot id becomes reusable
        if blocks:
            self._pool.unref(blocks)
        self._state = dataclasses.replace(
            self._state,
            active=self._state.active.at[slot].set(False),
            # all-null BEFORE the next decode scatter: freed blocks may
            # be re-issued to another slot, and a stale table would
            # keep writing into them
            tables=self._state.tables.at[slot].set(0),
        )
        req.t_parked = tracing.now()
        req.preemptions += 1
        self.preempted_total += 1
        self._parked.append(req)
        self._note("preempt", req=req.rid, slot=slot,
                   tokens=len(req.out_tokens),
                   cached_blocks=full, parked=len(self._parked))

    # -- live-session migration (drain) -----------------------------------

    def _mark_migrated(self, req: "_Request", streamed: int) -> None:
        """Complete ``req`` as MIGRATED: the terminal state a drained
        session reaches instead of done/failed. The waiter wakes with
        ``req.migrated`` set and out_tokens a PREFIX of the final
        answer — the serving layer re-routes with those tokens as the
        resume prefix, and token identity across the hop is the same
        position-folded-key invariant park/readmit pins. Scheduler
        thread only; safe with or without the lock (mutates only the
        request and monotonic counters)."""
        req.migrated = {
            "tokens": list(req.out_tokens),
            "blocks": int(streamed),
            "block_size": self.block_size,
            "kv_dtype": self.kv_dtype,
        }
        req.t_done = tracing.now()
        self.migrated_total += 1
        self._note("migrate", req=req.rid, tokens=len(req.out_tokens),
                   blocks=streamed)
        req.done.set()

    def _migrate_slot(self, slot: int, req: "_Request",
                      streamed: int) -> None:
        """Park-for-migrate: release ``slot`` exactly like ``_park_slot``
        — committed full blocks go into the radix trie — but the
        request completes as migrated instead of joining ``_parked``.
        The trie insert is load-bearing for the fallback story: if the
        router bounces the session back here (target died, or this was
        a rebalance and we undrain), the resume admit radix-matches
        these exact blocks and costs only the tail recompute."""
        with self._lock:
            if self._slot_req[slot] is not req:
                return  # retired or cancelled since the snapshot
            toks = req.prompt + req.out_tokens
            blocks, self._slot_blocks[slot] = self._slot_blocks[slot], []
            # same committed-blocks rule as _park_slot: the last
            # token's KV is uncommitted, so only blocks fully inside
            # [0, len-1) may be cached (or streamed — the export cursor
            # obeys the identical bound)
            committed = toks[:-1]
            full = len(committed) // self.block_size
            if full:
                self._radix.insert(committed, blocks[:full])
            self._slot_req[slot] = None
            self._slot_spec_ok[slot] = False
            self._migrate_cursor.pop(slot, None)
            if blocks:
                self._pool.unref(blocks)
            self._state = dataclasses.replace(
                self._state,
                active=self._state.active.at[slot].set(False),
                tables=self._state.tables.at[slot].set(0),
            )
        self._mark_migrated(req, streamed)

    def _step_drain(self) -> None:
        """One drain pass (scheduler thread only): sweep every queued
        population to a terminal state, then advance at most ONE live
        slot — stream one chunk of its committed blocks through
        ``migration_sink``, or park-and-migrate it once the stream has
        caught up with the decode head. One chunk per pass is the same
        quantum as chunked prefill and KV import: the decode windows
        between chunks keep emitting tokens — that interleave is what
        'migrate while decoding' means, and the catch-up always
        terminates because a pass streams chunk_blocks * block_size
        token positions while decode advances at most one window."""
        # never-admitted work first: it holds no KV, so 'migrating' it
        # is just handing the request (plus any resume prefix) back to
        # the router for placement elsewhere
        with self._lock:
            pending: list[_Request] = list(self._holdover)
            self._holdover.clear()
            pending.extend(self._parked)
            self._parked.clear()
            while True:
                try:
                    pending.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            staged, self._staged = self._staged, []
            for req, _slot, kv_plan, _tokens in staged:
                # release the plan's block holds (same bookkeeping as
                # the cancelled-staged path in _admit_pending)
                table_row, _own, _reuse, total, _spec = kv_plan
                self._pool.unref([int(b) for b in table_row[:total]])
                pending.append(req)
        for req in pending:
            if req.cancelled.is_set():
                req.t_done = tracing.now()
                req.done.set()
                continue
            self._mark_migrated(req, streamed=0)
        sink = self.migration_sink
        stream = None  # (slot, req, toks, cursor, n, blocks)
        final = None  # (slot, req, cursor)
        with self._lock:
            # mid-prefill rows keep prefilling (they become decoding
            # rows in a pass or two); cancelled rows retire at the next
            # window boundary — neither is a migration candidate yet
            prefilling = {t.slot for t in self._prefills}
            for slot, req in enumerate(self._slot_req):
                if req is None or slot in prefilling \
                        or req.cancelled.is_set():
                    continue
                toks = req.prompt + req.out_tokens
                committed = (len(toks) - 1) // self.block_size
                cursor = self._migrate_cursor.get(slot, 0)
                if sink is not None and cursor < committed:
                    n = min(self.migration_chunk_blocks,
                            committed - cursor)
                    stream = (slot, req, toks, cursor, n, list(
                        self._slot_blocks[slot][cursor:cursor + n]
                    ))
                else:
                    # caught up (or no sink is wired — then nothing
                    # streams and the target resumes by re-prefill,
                    # warm off the trie insert if it lands back here)
                    final = (slot, req, cursor)
                break
        if stream is not None:
            slot, req, toks, cursor, n, blocks = stream
            bs = self.block_size
            # page capture off the lock: only this thread writes
            # _state, so the gather cannot race a donation; same
            # boundary as _finalize_admit's export capture
            idx = jnp.asarray(np.asarray(blocks, np.int32))
            pages_k = np.stack([
                np.asarray(ck[idx]) for ck in self._state.caches_k
            ])
            pages_v = np.stack([
                np.asarray(cv[idx]) for cv in self._state.caches_v
            ])
            # fingerprints recomputed from the tokens, not read from
            # the trie: the streamed blocks are slot-held (not yet
            # inserted), and the chain from token 0 is exactly what the
            # importer recomputes to verify the slice
            fps = prefix_fingerprints(toks[:(cursor + n) * bs], bs)
            chunk = {
                "start_block": cursor,
                "pages_k": pages_k,
                "pages_v": pages_v,
                "fingerprints": fps[cursor:cursor + n],
                "block_size": bs,
                "kv_dtype": self.kv_dtype,
            }
            if self.kv_dtype == "int8":
                # committed blocks are already quantized (window-
                # boundary commit), so the scales travel with the chunk
                chunk["scales_k"] = np.stack([
                        np.asarray(sk[idx]) for sk in self._state.scales_k
                ])
                chunk["scales_v"] = np.stack([
                        np.asarray(sv[idx]) for sv in self._state.scales_v
                ])
            try:
                sink(chunk)
            except Exception:  # noqa: BLE001 — sink is injected code
                # a broken sink must not wedge the drain: hand the
                # session off with what was already streamed; the
                # target re-prefills the rest from the last verified
                # chunk (or from scratch), token-identical either way
                self._note("migrate_sink_error", req=req.rid, slot=slot,
                           start_block=cursor)
                self._migrate_slot(slot, req, cursor)
                return
            with self._lock:
                self._migrate_cursor[slot] = cursor + n
                self.migration_chunks_total += 1
                self.migration_blocks_total += n
            self._note("migrate_chunk", req=req.rid, slot=slot,
                       start_block=cursor, blocks=n)
            return
        if final is not None:
            slot, req, cursor = final
            self._migrate_slot(slot, req, cursor)
            return
        # nothing to advance: drained once every population is empty
        # (spec groups and prefills finish through their own steppers)
        with self._lock:
            live = (
                any(r is not None for r in self._slot_req)
                or self._spec_group is not None
                or bool(self._holdover) or bool(self._parked)
                or bool(self._prefills) or bool(self._staged)
            )
        if not live and self._queue.empty():
            self._drained.set()

    def _pick_victim(self, pol: PreemptionPolicy) -> int | None:
        """Lowest-priority preemptable row: the YOUNGEST-arrival active
        decoding slot (LIFO victim order keeps the oldest work running,
        matching the longest-pending-first admission order) that has
        decoded at least ``min_progress`` tokens since its own
        (re)admission and whose cold readmit would still fit a slot.
        Mid-prefill rows are never parked — their KV is half-committed
        and they produced nothing to cache. Caller holds the lock."""
        prefilling = {t.slot for t in self._prefills}
        victim, victim_t = None, -1.0
        for slot, req in enumerate(self._slot_req):
            if req is None or slot in prefilling:
                continue
            if len(req.out_tokens) - req.tokens_at_admit < \
                    max(1, pol.min_progress):
                continue
            # a parked row readmits with effective prompt = prompt +
            # generated; if the trie got evicted meanwhile the resume
            # is COLD, so the full bucket must still fit the row
            if _bucket(len(req.prompt) + len(req.out_tokens)) > \
                    self.cache_len:
                continue
            if req.t_submit > victim_t:
                victim, victim_t = slot, req.t_submit
        return victim

    def _maybe_preempt(self) -> None:
        """Park one decoding row for the longest-pending waiter when
        queue-wait pressure crosses the policy's burn-rate threshold.
        At most one preemption per call, gated by the cooldown — the
        scheduler never mass-evicts its own batch."""
        pol = self.preemption
        if pol is None or self._slo is None:
            return
        with self._lock:
            waiter = self._holdover[0] if self._holdover else None
            free = any(r is None for r in self._slot_req)
        if waiter is None or free:
            return
        now = tracing.now()
        wait = now - waiter.pending_since
        if wait < pol.threshold_s or \
                self._steps_since_preempt < pol.cooldown_steps:
            return
        # feed the live head-wait in: a fully wedged engine admits
        # nothing, so admit-time observations alone would never show
        # the burn rising exactly when preemption is needed most
        self._slo.observe("queue_wait", wait, t=now)
        burn = max(self._slo.burn_rates(now=now)["queue_wait"].values())
        if burn < pol.burn_limit:
            return
        with self._lock:
            victim = self._pick_victim(pol)
            if victim is None:
                return
            self._park_slot(victim)
        self._steps_since_preempt = 0
        # admit the waiter into the freed slot NOW — the parked victim
        # re-enters the pending order behind it (pending_since just
        # reset), so each preemption transfers the slot to strictly
        # older work
        self._admit_pending()

    def _drain_spec_group(
        self, first: "_Request"
    ) -> tuple[list["_Request"], "_Request | None"]:
        """Drain queued requests into ``first``'s draft batch.

        The speculative engine is batched (per-row cache offsets carry
        rows advancing at different speeds), so concurrent requests need
        not lose the draft speedup to each other (r3 verdict item 8).
        Joinable: same MODE as the head (greedy with greedy, sampled
        with sampled — the rejection correction and warp knobs are
        per-row, r4 item 5, but the greedy/sampled split is a static
        trace flag), no repetition penalty, same eos id, equal SEED for
        sampled joins, and every member still fits the draft cache at
        the group's max_new high-water mark. The seed requirement is a
        reproducibility guard (ADVICE r5): the group's key stream is
        seeded by the HEAD request only (``_start_spec_group`` passes
        ``first.seed``), so a sampled request joining under a different
        seed would silently sample from the head's stream — same prompt
        + seed + params would then give different tokens depending on
        what else was in flight. Greedy rows draw no noise, so their
        seeds are irrelevant. The first non-joinable request is
        returned as a holdover for slot admission — draining must not
        reorder it behind later arrivals.
        """
        group = [first]
        gmax = first.max_new
        head_sampled = first.temperature > 0
        holdover: _Request | None = None
        while len(group) < self.n_slots:
            try:
                nxt = self._queue.get_nowait()
            except queue.Empty:
                break
            if nxt.cancelled.is_set():
                nxt.done.set()
                continue
            cand_max = max(gmax, nxt.max_new)
            if (
                nxt.rep_penalty == 1.0
                and (nxt.temperature > 0) == head_sampled
                and (not head_sampled or nxt.seed == first.seed)
                and nxt.eos_id == first.eos_id
                and all(
                    self.speculative.fits(len(m.prompt), cand_max)
                    for m in (*group, nxt)
                )
            ):
                group.append(nxt)
                gmax = cand_max
            else:
                holdover = nxt
                break
        return group, holdover

    def _start_spec_group(self, group: list["_Request"]) -> None:
        """Prefill a draft group (scheduler-thread context). Rows ride
        the group's max_new and are truncated back to their own
        request's budget on the way out (a row past its own budget
        costs ride-along rounds, never wrong tokens). Sampled members
        keep their own temperature/top_k/top_p rows; the group key
        stream is seeded by the head request."""
        first = group[0]
        try:
            g = self.speculative.start_group(
                [r.prompt for r in group],
                max_new_tokens=max(r.max_new for r in group),
                eos_id=first.eos_id,
                temperatures=[r.temperature for r in group],
                top_ks=[r.top_k for r in group],
                top_ps=[r.top_p for r in group],
                seed=first.seed,
            )
        except Exception as e:  # noqa: BLE001 — waiters must be released
            for r in group:
                r.failed = f"speculative decode failed: {e}"
                r.done.set()
            return
        with self._lock:
            self._spec_group = (group, g)

    def _step_spec_group(self) -> None:
        """One speculation round for the live group; emit and retire on
        completion. Bounded work per call, so busy slots and a live
        group interleave at step granularity. Device work runs outside
        the lock; the completion handoff re-checks identity under it
        (stop() may have failed the members meanwhile)."""
        with self._lock:
            live = self._spec_group
        if live is None:
            return
        reqs, g = live
        if all(r.cancelled.is_set() for r in reqs):
            # nobody will read any row: drop the group instead of
            # drafting to the budget (a timed-out burst must not pin
            # the draft path on dead work). A PARTIALLY cancelled
            # group keeps riding — rows are interleaved in one batch
            # and the survivors' tokens are still wanted.
            with self._lock:
                if self._spec_group is live:
                    self._spec_group = None
            for r in reqs:
                r.done.set()
            return
        spec_t0 = tracing.now()
        try:
            done = self.speculative.step_group(g)
            out = self.speculative.finish_group(g) if done else None
        except Exception as e:  # noqa: BLE001
            with self._lock:
                if self._spec_group is live:
                    self._spec_group = None
            for r in reqs:
                r.failed = f"speculative decode failed: {e}"
                r.done.set()
            return
        # group tokens only become countable when the group finishes
        # (finish_group copies the accepted rows out); intermediate
        # rounds record zero live tokens but still carry the dispatch
        # duration, so the step histogram sees every device round
        emitted = (
            sum(min(int(out.lengths[b]), r.max_new)
                for b, r in enumerate(reqs))
            if out is not None else 0
        )
        self.profiler.record(
            "spec", bucket=len(reqs), live_rows=len(reqs),
            live_tokens=emitted, padded_tokens=0,
            start=spec_t0, end=tracing.now(),
        )
        if out is None:
            return
        with self._lock:
            if self._spec_group is not live:
                return  # stop() already failed the members
            self._spec_group = None
        # per-group carry, NOT engine.last_stats: the bulk speculative
        # route mutates that shared field from HTTP threads concurrently
        self.spec_accepted += g.accepted_drafts
        fin = tracing.now()
        for b, r in enumerate(reqs):
            n = min(int(out.lengths[b]), r.max_new)
            r.out_tokens.extend(out.tokens[b, :n].tolist())
            self.spec_served += 1
            r.t_done = fin
            # draft groups have no slot timeline; one span covers the
            # whole group residency so spec traffic still shows up in
            # the trace (attrs mark it for the breakdown readers)
            _TRACER.record_span(
                "engine.spec_group", start=r.t_submit, end=fin,
                parent=r.trace_parent, tokens=n, group_size=len(reqs),
            )
            r.done.set()

    def _place(self, req: "_Request") -> bool:
        """Route one pending request: draft group if eligible and none
        is live, else a free slot; False stashes it back at the front
        of the holdover (all slots busy). Resumed requests never join
        draft groups — their generated prefix lives in the paged pool,
        which the speculative engine cannot see. Caller must NOT hold
        the lock."""
        if req.cancelled.is_set():
            req.t_done = tracing.now()
            req.done.set()
            return True
        resumed = bool(req.out_tokens)
        with self._lock:
            group_free = self._spec_group is None
        if (
            self.speculative is not None
            # paged verify windows supersede the dense side-car: when a
            # draft model is wired into the batch itself, every request
            # should ride the paged path (the group would steal exactly
            # the prompts that speculate best)
            and self.spec_draft is None
            and group_free
            and not resumed
            and req.rep_penalty == 1.0
            and self.speculative.fits(len(req.prompt), req.max_new)
        ):
            group, holdover = self._drain_spec_group(req)
            self._start_spec_group(group)
            if holdover is not None:
                with self._lock:
                    # freshly drained from the queue = newest pending
                    self._holdover.append(holdover)
            return True
        with self._lock:
            for slot in range(self.n_slots):
                if self._slot_req[slot] is None:
                    tokens = req.prompt + req.out_tokens
                    kv_plan = self._plan_kv(
                        tokens, req.max_new - len(req.out_tokens),
                        rid=req.rid,
                    )
                    if kv_plan is None:
                        break  # pool backpressure: hold until a retire
                    # lint: allow[blocking-under-lock] known ceiling: the admit-path jit compile (cold bucket ~tens of seconds) runs under _lock so stop() sees a consistent slot/pool state; stats_summary went lockless for exactly this reason (PR 6)
                    self._admit(slot, req, kv_plan, tokens)
                    return True
            # front, not back: this was the oldest pending request and
            # must stay first in line
            self._holdover.appendleft(req)
        return False

    def _pop_pending(self) -> "_Request | None":
        """Longest-pending-first admission order across the three
        waiting populations: the holdover deque, the parked list, and
        the arrival queue (pulled through the holdover so its head's
        age is comparable). This order is the anti-livelock guarantee:
        a just-parked victim's ``pending_since`` restarts at its park
        time, so it can never preempt-loop ahead of the waiter it was
        parked for."""
        with self._lock:
            if not self._holdover:
                try:
                    self._holdover.append(self._queue.get_nowait())
                except queue.Empty:
                    pass
            hold = self._holdover[0] if self._holdover else None
            park = self._parked[0] if self._parked else None
            if park is not None and (
                hold is None or park.pending_since <= hold.pending_since
            ):
                return self._parked.pop(0)
            if hold is not None:
                return self._holdover.popleft()
            return None

    def _admit_pending(self) -> None:
        """Place pending requests (parked readmits and arrivals, oldest
        first) until something has to wait — all slots busy, or pool
        backpressure. Plans staged by ``_plan_admissions`` while the
        last decode window was in flight go first: their radix/alloc
        work is already done, and they were popped from the pending
        order ahead of whatever is still queued."""
        with self._lock:
            staged, self._staged = self._staged, []
        for req, slot, kv_plan, tokens in staged:
            with self._lock:
                if req.cancelled.is_set() or \
                        self._slot_req[slot] is not None:
                    # release the plan's block holds; a cancelled
                    # request retires unserved, an occupied slot (only
                    # reachable through a future scheduler change —
                    # this thread is the sole admitter) sends the
                    # request back to the head of the line
                    table_row, _own, _reuse, total, _spec = kv_plan
                    self._pool.unref(
                        [int(b) for b in table_row[:total]]
                    )
                    if req.cancelled.is_set():
                        req.t_done = tracing.now()
                        req.done.set()
                    else:
                        self._holdover.appendleft(req)
                    continue
                # lint: allow[blocking-under-lock] same ceiling as _place: the admit-path jit compile (cold bucket ~tens of seconds) runs under _lock so stop() sees a consistent slot/pool state
                self._admit(slot, req, kv_plan, tokens)
        while True:
            req = self._pop_pending()
            if req is None:
                return
            if not self._place(req):
                return

    def _plan_admissions(self) -> None:
        """The host half of admission, overlapped with the in-flight
        decode window: pop pending requests (same longest-pending-first
        order as ``_admit_pending``) and run radix match + reuse clamp
        + block alloc, staging ``(req, slot, plan, tokens)`` for the
        next window boundary. No device dispatch and no readback
        happens here, so the whole pass runs while the device chews
        the window; the jit admits (which may compile for tens of
        seconds) stay at the boundary. Spec-eligible heads are pushed
        back for ``_place`` — forming a draft group dispatches device
        work immediately, which must not race the window's donated
        state."""
        while True:
            with self._lock:
                taken = {s for _r, s, _p, _t in self._staged}
                free = [
                    s for s in range(self.n_slots)
                    if self._slot_req[s] is None and s not in taken
                ]
            if not free:
                return
            req = self._pop_pending()
            if req is None:
                return
            if req.cancelled.is_set():
                req.t_done = tracing.now()
                req.done.set()
                continue
            resumed = bool(req.out_tokens)
            with self._lock:
                group_free = self._spec_group is None
            if (
                self.speculative is not None
                and self.spec_draft is None
                and group_free
                and not resumed
                and req.rep_penalty == 1.0
                and self.speculative.fits(len(req.prompt), req.max_new)
            ):
                with self._lock:
                    # head of the line again: _place routes it at the
                    # boundary (it was the oldest pending request)
                    self._holdover.appendleft(req)
                return
            with self._lock:
                tokens = req.prompt + req.out_tokens
                kv_plan = self._plan_kv(
                    tokens, req.max_new - len(req.out_tokens),
                    rid=req.rid,
                )
                if kv_plan is None:
                    self._holdover.appendleft(req)
                    return
                self._staged.append((req, free[0], kv_plan, tokens))

    def _pick_horizon(self, budgets: list[int], host_work: bool) -> int:
        """Decode-window horizon for this pass, from the static bucket
        set (one compiled shape each). K collapses to 1 whenever the
        host has competing work — pending admissions, chunked prefills,
        a live draft group, a cancelled row — so fused windows never
        starve admission, prefill interleave, or retirement; otherwise
        K is the largest bucket no row can overshoot (min remaining
        budget), so ``max_new`` is never crossed mid-window, every
        retirement lands exactly at a window boundary, and every write
        stays inside the row's allocated block span. SLO burn needs no
        separate clamp: preemption pressure requires a waiter, and any
        waiter already forces K=1 (the preemption check itself runs
        between windows, so parks land at boundaries too)."""
        if host_work or not budgets:
            return 1
        lim = min(min(budgets), self.max_window)
        k = 1
        for b in self._window_buckets:
            if b <= lim:
                k = b
        return k

    def _loop(self) -> None:
        while not self._stop.is_set():
            # staged KV imports first (at most one per pass): an import
            # usually precedes the very request that wants its blocks,
            # so servicing it ahead of admissions turns that request's
            # admit into a warm one instead of a cold prefill
            self._step_import()
            with self._lock:
                busy = any(r is not None for r in self._slot_req)
                idle = (not busy and self._spec_group is None
                        and not self._parked)
                have_holdover = bool(self._holdover)
            if idle:
                if self._draining:
                    # drain sweeps the queue itself (racing submits
                    # land there past the lockless refusal) and flips
                    # _drained once every population is empty
                    self._step_drain()
                    self._stop.wait(0.05)
                    continue
                # fully idle: block briefly for the next arrival
                if not have_holdover:
                    try:
                        nxt = self._queue.get(timeout=0.05)
                    except queue.Empty:
                        continue
                    with self._lock:
                        self._holdover.append(nxt)
                self._admit_pending()
                continue
            # live work: non-blocking admissions, a preemption check
            # when the waiters' SLO pressure warrants one, then one
            # step of each active machine — the decode batch, at most
            # ONE prefill chunk, and a live draft group advance in
            # lockstep per loop pass, so none starves the others. This
            # interleave is the tentpole: prefill stopped being one
            # atomic dispatch and became schedulable work competing
            # with decode under an explicit policy.
            if self._draining:
                # admission and preemption stand down; the drain pass
                # streams one chunk (or finalizes one caught-up slot)
                # and the decode window below keeps the batch emitting
                # tokens between chunks
                self._step_drain()
            else:
                self._admit_pending()
                self._maybe_preempt()
            with self._lock:
                # mid-prefill rows are reserved but not yet decoding
                # (active=False, null tables); they are padding in the
                # decode dispatch, not live rows
                prefilling = {t.slot for t in self._prefills}
                budgets = [
                    r.max_new - len(r.out_tokens)
                    for s, r in enumerate(self._slot_req)
                    if r is not None and s not in prefilling
                ]
                decode_rows = len(budgets)
                # verify windows are all-or-nothing: a live row whose
                # admit fell back to the plain block budget (_plan_kv
                # spec_ok=False) has no +spec_k slack, and the fused
                # dispatch cannot exclude single rows — so any such row
                # drops the whole batch to plain decode until it
                # retires or parks
                spec_ready = self.spec_draft is not None and bool(
                    budgets
                ) and all(
                    self._slot_spec_ok[s]
                    for s, r in enumerate(self._slot_req)
                    if r is not None and s not in prefilling
                )
                host_work = (
                    bool(self._holdover) or bool(self._parked)
                    or bool(self._prefills)
                    or self._spec_group is not None
                    or any(
                        r is not None and r.cancelled.is_set()
                        for r in self._slot_req
                    )
                )
            # arrival-queue peek outside the lock (qsize takes the
            # queue's own lock); a racing submit only costs one pass
            # of K=1 or one window of delayed admission — never
            # correctness
            host_work = host_work or not self._queue.empty()
            # draining forces K=1: short windows keep the chunk stream
            # close behind the decode head, so the park-and-move tail
            # (and the drain itself) lands sooner
            host_work = host_work or self._draining
            if decode_rows and spec_ready:
                # the speculative twin of the fused branch below: one
                # verify dispatch advances every row by 1..spec_k+1
                # tokens (data-dependent, unlike the fixed-K window),
                # and the boundary drain is where accept/rollback meets
                # the scheduler — truncation below always coincides
                # with retirement, so discarded device progress never
                # leaks into a continuing row
                step_t0 = tracing.now()
                # lint: allow[lock-discipline] scheduler thread is the only _state writer; see comment above
                self._state, self._dstate, tokens = verify_window(
                    self.params, self._state, self._dparams,
                    self._dstate, self.cfg, self._dcfg, self.spec_k,
                    sharded=self._sharded,
                )
                if not self._draining:
                    # a drain must not stage new plans (their block
                    # holds would just be unwound by the next sweep)
                    self._plan_admissions()
                # lint: allow[host-sync] window boundary: the [n_slots, spec_k+1] token matrix feeds the Python result queues
                toks = np.asarray(tokens)
                step_t = tracing.now()
                self.windows_total += 1
                self._steps_since_preempt += self.spec_k
                accepted = 0
                with self._lock:
                    for slot in range(self.n_slots):
                        req = self._slot_req[slot]
                        row = toks[slot]
                        n_dev = int((row >= 0).sum())
                        if req is None or n_dev == 0:
                            continue
                        self.spec_draft_tokens += self.spec_k
                        if self.kv_dtype == "int8":
                            # offset invariant: p + emitted - 1 (the
                            # newest token's KV is uncommitted); the
                            # device advanced this row n_dev positions
                            # and quantize-committed one block per
                            # boundary crossing
                            old = len(req.prompt) \
                                + len(req.out_tokens) - 1
                            self.quant_blocks_total += (
                                (old + n_dev) // self.block_size
                                - old // self.block_size
                            )
                        # device acceptance may overshoot the request
                        # budget or run past EOS (the window cannot
                        # stop mid-dispatch); the host emits the
                        # truncated prefix and every truncation lands
                        # on a retirement below, so the row's advanced
                        # device state is discarded, never resumed —
                        # that is what keeps truncation identity-safe
                        n_host = min(n_dev, req.max_new
                                     - len(req.out_tokens))
                        if req.eos_id >= 0:
                            for i in range(n_host):
                                if int(row[i]) == req.eos_id:
                                    n_host = i + 1
                                    break
                        for j in range(n_host):
                            t_j = step_t0 + (j + 1) * (
                                step_t - step_t0) / n_host
                            req.out_tokens.append(int(row[j]))
                            req.token_times.append(t_j)
                        if n_host > 1:
                            req.interpolated = True
                        accepted += n_host
                        # n_dev = accepted drafts + the bonus token
                        # the verify forward samples past the last
                        # accepted draft, so drafts-accepted is n_dev-1
                        acc_d = n_dev - 1
                        self.spec_accepted_tokens += acc_d
                        req.spec_accepted += acc_d
                        if acc_d < self.spec_k:
                            self.spec_rollbacks += 1
                            req.spec_rollbacks += 1
                        self._maybe_retire(slot)
                # ONE record per verify dispatch, phase "verify" so the
                # decode-dispatches-per-token summary and the compile
                # proxy (first-seen phase/bucket) stay honest about
                # which compiled shape ran; bucket is spec_k (one
                # compiled verify shape per K)
                self.profiler.record(
                    "verify", bucket=self.spec_k,
                    live_rows=decode_rows, live_tokens=accepted,
                    padded_tokens=(
                        self.n_slots * (self.spec_k + 1) - accepted
                    ),
                    start=step_t0, end=step_t, steps=self.spec_k,
                )
            elif decode_rows:
                k = self._pick_horizon(budgets, host_work)
                # device window outside the lock (it can block on a
                # compile; stop() must still be able to fail the slots)
                step_t0 = tracing.now()
                # lint: allow[lock-discipline] scheduler thread is the only _state writer; see comment above
                self._state, tokens = decode_window(
                    self.params, self._state, self.cfg, k,
                    sharded=self._sharded,
                )
                # the dispatch returns a future immediately (JAX async
                # dispatch): the admission planning below is the host
                # work overlapped with the device window, and the
                # readback after it is the one synchronization point
                if not self._draining:
                    # same stand-down as the verify branch: no new
                    # plans while draining
                    self._plan_admissions()
                # lint: allow[host-sync] window boundary: the [n_slots, k] token matrix feeds the Python result queues
                toks = np.asarray(tokens)
                # one clock read per WINDOW, outside the lock: token
                # times inside the bracket are interpolated below
                # (docs/OBSERVABILITY.md — traces carry
                # kubeinfer.interpolated so nobody reads them as
                # per-step measurements)
                step_t = tracing.now()
                self.windows_total += 1
                self._steps_since_preempt += k
                accepted = 0
                with self._lock:
                    if self.kv_dtype == "int8":
                        # every decoding row advanced k positions on
                        # the device (retirement is host work below);
                        # one tail block quantize-commits per boundary
                        # crossing. Offset invariant: p + emitted - 1.
                        for s, r in enumerate(self._slot_req):
                            if r is None or s in prefilling:
                                continue
                            old = len(r.prompt) + len(r.out_tokens) - 1
                            self.quant_blocks_total += (
                                (old + k) // self.block_size
                                - old // self.block_size
                            )
                    for j in range(k):
                        t_j = step_t0 + (j + 1) * (step_t - step_t0) / k
                        for slot in range(self.n_slots):
                            # host-side EOS masking: _maybe_retire
                            # clears _slot_req at the EOS/budget token,
                            # so a retired row's tail tokens in the
                            # same window fall through the req-is-None
                            # check — the device kept scattering junk
                            # into the row's own refcounted blocks,
                            # which nobody reads (same null-block
                            # discipline as retirement, and always
                            # inside the row's allocated span by the
                            # horizon clamp)
                            req = self._slot_req[slot]
                            if req is None or toks[slot, j] < 0:
                                continue
                            req.out_tokens.append(int(toks[slot, j]))
                            req.token_times.append(t_j)
                            if k > 1:
                                req.interpolated = True
                            accepted += 1
                            self._maybe_retire(slot)
                # ONE record per fused dispatch: bucket=k is the
                # compiled-shape knob (first-seen per window bucket ==
                # one compile each), live_tokens counts only tokens
                # that reached a request — inactive rows and masked
                # post-EOS tails are padding of the n_slots x k window
                self.profiler.record(
                    "decode", bucket=k, live_rows=decode_rows,
                    live_tokens=accepted,
                    padded_tokens=self.n_slots * k - accepted,
                    start=step_t0, end=step_t, steps=k,
                )
            self._step_prefill()  # at most one chunk per pass
            self._step_spec_group()  # locked no-op when no group is live
        # epilogue: anything published after stop()'s sweep (admission
        # was mid-compile during the snapshot) is released here — the
        # last observer of the handoff fields cleans up
        if self._stop.is_set():
            self._fail_inflight()
