"""Checkpoint/resume for the native model (orbax-backed).

SURVEY.md §5 records the reference's checkpoint story as "none" (its
only persistent state is the model cache dir). Here fine-tuning /
training state checkpoints properly: orbax handles the array
serialization (async-capable, atomic finalization), and restore can
target a sharded layout directly — params land on their TP mesh
placement without a host-memory detour, which is what makes 70B-class
restores feasible.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any

import jax

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.model import Params


def save_checkpoint(
    path: str, params: Params, cfg: ModelConfig, step: int = 0
) -> None:
    """Write params + config + step to ``path`` (atomic on completion)."""
    import orbax.checkpoint as ocp

    root = pathlib.Path(path).absolute()
    root.parent.mkdir(parents=True, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(root / "params", params)
    (root / "meta.json").write_text(json.dumps({
        "step": step,
        "config": dataclasses.asdict(cfg),
        "param_dtype": str(params["norm"].dtype),
        "tied": "lm_head" not in params,
    }))


def restore_checkpoint(
    path: str,
    mesh: jax.sharding.Mesh | None = None,
) -> tuple[Params, ModelConfig, int]:
    """Restore (params, config, step).

    With ``mesh``, arrays restore DIRECTLY into the TP sharded layout
    (sharding.param_specs) — each host/device reads only its shard.
    """
    import orbax.checkpoint as ocp

    root = pathlib.Path(path).absolute()
    meta = json.loads((root / "meta.json").read_text())
    cfg = ModelConfig(**meta["config"])

    with ocp.StandardCheckpointer() as ckptr:
        if mesh is None:
            params = ckptr.restore(root / "params")
        else:
            import jax.numpy as jnp
            from jax.sharding import NamedSharding

            from kubeinfer_tpu.inference.model import init_params
            from kubeinfer_tpu.inference.sharding import param_specs

            # abstract target tree: shapes from eval_shape (no
            # allocation), dtype from the save-time record, shardings
            # from the TP specs — orbax then reads each shard straight
            # to its device
            dtype = jnp.dtype(meta.get("param_dtype", "float32"))
            template: Any = jax.eval_shape(
                lambda: init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
            )
            specs = param_specs(cfg)
            if meta.get("tied", False):
                specs = dict(specs)
                specs.pop("lm_head", None)
            abstract = jax.tree.map(
                lambda m, s: jax.ShapeDtypeStruct(
                    m.shape, m.dtype, sharding=NamedSharding(mesh, s)
                ),
                template, specs,
            )
            params = ckptr.restore(root / "params", abstract)
    return params, cfg, int(meta["step"])
