"""Checkpoint/resume for the native model (orbax-backed).

SURVEY.md §5 records the reference's checkpoint story as "none" (its
only persistent state is the model cache dir). Here fine-tuning /
training state checkpoints properly: orbax handles the array
serialization (async-capable, atomic finalization), and restore can
target a sharded layout directly — params land on their TP mesh
placement without a host-memory detour, which is what makes 70B-class
restores feasible.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any

import jax

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.model import Params


def save_checkpoint(
    path: str, params: Params, cfg: ModelConfig, step: int = 0
) -> None:
    """Write params + config + step to ``path`` (atomic on completion)."""
    import orbax.checkpoint as ocp

    root = pathlib.Path(path).absolute()
    root.parent.mkdir(parents=True, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(root / "params", params)
    from kubeinfer_tpu.inference.weight_quant import params_weight_dtype

    (root / "meta.json").write_text(json.dumps({
        "step": step,
        "config": dataclasses.asdict(cfg),
        "param_dtype": str(params["norm"].dtype),
        "tied": "lm_head" not in params,
        # weight precision axis, recorded so restore rebuilds the
        # quantized template (int8 codes + f32 scale planes) instead of
        # re-quantizing — a double quantization would re-derive scales
        # FROM int8 codes and silently corrupt the model
        "weight_dtype": params_weight_dtype(params),
    }))


def restore_checkpoint(
    path: str,
    mesh: jax.sharding.Mesh | None = None,
) -> tuple[Params, ModelConfig, int]:
    """Restore (params, config, step).

    With ``mesh``, arrays restore DIRECTLY into the TP sharded layout
    (sharding.param_specs) — each host/device reads only its shard.
    """
    import orbax.checkpoint as ocp

    root = pathlib.Path(path).absolute()
    meta = json.loads((root / "meta.json").read_text())
    cfg = ModelConfig(**meta["config"])

    with ocp.StandardCheckpointer() as ckptr:
        if mesh is None:
            params = ckptr.restore(root / "params")
        else:
            import jax.numpy as jnp
            from jax.sharding import NamedSharding

            from kubeinfer_tpu.inference.model import init_params
            from kubeinfer_tpu.inference.sharding import (
                expand_quant_specs, param_specs,
            )

            # abstract target tree: shapes from eval_shape (no
            # allocation), dtype from the save-time record, shardings
            # from the TP specs — orbax then reads each shard straight
            # to its device. A weight-quantized save rebuilds the
            # quantized template the same way (eval_shape over
            # init_params' weight_dtype axis — still zero allocation),
            # so the restored tree is losslessly the saved one and the
            # engine's double-quantize guard never trips.
            dtype = jnp.dtype(meta.get("param_dtype", "float32"))
            wdt = meta.get("weight_dtype", "bf16")
            template: Any = jax.eval_shape(
                lambda: init_params(
                    cfg, jax.random.PRNGKey(0), dtype=dtype,
                    weight_dtype=wdt,
                )
            )
            specs = param_specs(cfg)
            if meta.get("tied", False):
                specs = dict(specs)
                specs.pop("lm_head", None)
            specs = expand_quant_specs(specs, template)
            abstract = jax.tree.map(
                lambda m, s: jax.ShapeDtypeStruct(
                    m.shape, m.dtype, sharding=NamedSharding(mesh, s)
                ),
                template, specs,
            )
            params = ckptr.restore(root / "params", abstract)
    return params, cfg, int(meta["step"])
