"""The one decode stepper: every serving path's token loop lives here.

Three decode loops used to coexist — ``Engine.generate``'s dense-cache
``lax.scan``, ``SPEngine``'s copy of the same call, and
``ContinuousEngine``'s per-token ``_decode_step`` dispatch — divergent
in everything but intent (ROADMAP item 3). This module collapses them:

- :func:`step_forward` is the single-token forward both loops share —
  the dense-cache route (per-row ``[B, S, ...]`` caches) and the paged
  route (shared block pool + ``i32[B, max_blocks]`` tables) differ only
  in which attention reader the trace binds, so the jnp twin and the
  block-table Pallas kernel are reached per-step exactly as before.
- :func:`decode_scan` is the fused fixed-horizon loop the per-request
  and sequence-parallel engines jit (prefill hands it dense caches).
- :func:`decode_window` is the continuous batcher's fused K-step window:
  ONE jitted dispatch runs K steps as a ``lax.scan`` over the donated
  :class:`SlotState` and returns the ``[n_slots, K]`` token matrix.
  K is static — the scheduler picks it from a small bucket set, one
  compiled shape each — so the per-dispatch floor (BENCH_r02–r04:
  ~70–90 ms on the relay vs ~1–4 ms of solve) is paid once per K
  tokens instead of once per token.

Bit-identity across horizons is by construction, not luck: sampling
keys are position-folded (``sample_rows`` folds ``offset + 1``; admit
folds ``prompt_len``), so a fused window draws exactly the noise the
same steps would draw dispatched one at a time — the parity tests pin
K∈{1,2,4,8} against single-step streams, greedy and sampled.

Reference divergence: the reference operator never owns a decode loop —
it delegates stepping wholesale to the vLLM subprocess
(internal/agent/vllm.go:93-112) and multi-step scheduling is vLLM's
internal affair. Our engine owns its schedule, so the window, its
horizon policy, and the host/device overlap are built natively here.
"""

from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.engine import (
    apply_repetition_penalty,
    filter_logits,
    gumbel_pick,
    gumbel_sample,
    record_seen,
    seen_from_prompt,
)
from kubeinfer_tpu.inference.flash_attention import (
    decode_attention_auto,
    decode_attention_blocks_auto,
    decode_attention_blocks_q8_auto,
)
from kubeinfer_tpu.inference.kv_blocks import quantize_blocks
from kubeinfer_tpu.inference.model import Params, forward

__all__ = [
    "SlotState", "init_slot_state", "sample_rows", "step_forward",
    "decode_body", "decode_window", "decode_scan", "WINDOW_BUCKETS",
    "DraftState", "init_draft_state", "spec_accept", "verify_window",
]

# Static decode-window horizons: one compiled shape each, so the
# scheduler can retune K per pass without ever paying a fresh compile.
# Powers of two keep the set tiny while spanning the useful range — by
# K=8 the dispatch floor is already amortized below the solve time.
WINDOW_BUCKETS = (1, 2, 4, 8)


# --- device state ----------------------------------------------------------


@dataclass
class SlotState:
    """All device-resident decode state (fixed shapes).

    The KV pool is SHARED across slots: row b's logical cache position
    p lives in ``caches_k[l][tables[b, p // bs], p % bs]``. Block 0 is
    the reserved null block (kv_blocks.NULL_BLOCK): dead table entries
    and retired rows point there, so every gather/scatter index is
    always valid without data-dependent control flow under jit.

    ``kv_dtype="int8"`` (trace-static: ``caches_k[0].dtype``) adds the
    quantized-pool companions: per-(block, head) dequant scales and the
    per-slot bf16 TAIL [B, 2, bs, n_kv, D] — slot 0 is the row's
    current partial block (logical block offset // bs), slot 1 the one
    a verify window may spill into. Decode scatters land in the tail
    (model.decoder_layer), attention overlays it past the committed
    blocks (flash_attention q8 readers), and the window boundary
    quantizes just-filled slot-0 blocks into the pool
    (:func:`_commit_full_tails`). In bf16 mode all four are EMPTY
    lists — valid pytrees that keep every trace byte-identical to the
    pre-quantization engine."""

    caches_k: list[jax.Array]  # L x [num_blocks, block_size, n_kv, D]
    caches_v: list[jax.Array]
    tables: jax.Array  # i32[B, max_blocks] pool indices, seq order
    last_token: jax.Array  # i32[B]
    offset: jax.Array  # i32[B] next cache position (= current length)
    active: jax.Array  # bool[B]
    temperature: jax.Array  # f32[B]; <=0 = greedy
    top_k: jax.Array  # i32[B]; <1 = disabled
    top_p: jax.Array  # f32[B]; >=1 = disabled
    rep_penalty: jax.Array  # f32[B]; 1.0 = disabled
    seen: jax.Array  # bool[B, V] ids in prompt or generated so far
    rng: jax.Array  # u32[B, 2] per-slot PRNG key data
    scales_k: list[jax.Array]  # int8: L x f32[num_blocks, n_kv]; else []
    scales_v: list[jax.Array]
    tails_k: list[jax.Array]  # int8: L x [B, 2, bs, n_kv, D]; else []
    tails_v: list[jax.Array]


jax.tree_util.register_dataclass(
    SlotState,
    data_fields=["caches_k", "caches_v", "tables", "last_token", "offset",
                 "active", "temperature", "top_k", "top_p", "rep_penalty",
                 "seen", "rng", "scales_k", "scales_v", "tails_k",
                 "tails_v"],
    meta_fields=[],
)


def init_slot_state(cfg: ModelConfig, n_slots: int, cache_len: int,
                    dtype, num_blocks: int, block_size: int,
                    kv_dtype: str = "bf16") -> SlotState:
    """``kv_dtype="bf16"`` stores pool pages in the compute ``dtype``
    (the historical layout — the name is the CLI axis, not the literal
    array dtype, so f32 test engines stay f32); ``"int8"`` stores int8
    pages + f32 scales and allocates the per-slot bf16 tails."""
    L = cfg.num_hidden_layers
    shape = (num_blocks, block_size, cfg.num_key_value_heads, cfg.head_dim)
    if kv_dtype == "int8":
        page_dt = jnp.int8
        sshape = (num_blocks, cfg.num_key_value_heads)
        tshape = (n_slots, 2, block_size, cfg.num_key_value_heads,
                  cfg.head_dim)
        scales_k = [jnp.ones(sshape, jnp.float32) for _ in range(L)]
        scales_v = [jnp.ones(sshape, jnp.float32) for _ in range(L)]
        tails_k = [jnp.zeros(tshape, dtype) for _ in range(L)]
        tails_v = [jnp.zeros(tshape, dtype) for _ in range(L)]
    elif kv_dtype == "bf16":
        page_dt = dtype
        scales_k, scales_v, tails_k, tails_v = [], [], [], []
    else:
        raise ValueError(
            f"kv_dtype must be 'bf16' or 'int8', got {kv_dtype!r}"
        )
    return SlotState(
        caches_k=[jnp.zeros(shape, page_dt) for _ in range(L)],
        caches_v=[jnp.zeros(shape, page_dt) for _ in range(L)],
        scales_k=scales_k,
        scales_v=scales_v,
        tails_k=tails_k,
        tails_v=tails_v,
        tables=jnp.zeros((n_slots, cache_len // block_size), jnp.int32),
        last_token=jnp.zeros((n_slots,), jnp.int32),
        offset=jnp.zeros((n_slots,), jnp.int32),
        active=jnp.zeros((n_slots,), bool),
        temperature=jnp.zeros((n_slots,), jnp.float32),
        top_k=jnp.zeros((n_slots,), jnp.int32),
        top_p=jnp.ones((n_slots,), jnp.float32),
        rep_penalty=jnp.ones((n_slots,), jnp.float32),
        # [n_slots, V] bool lives for the engine's lifetime and the
        # keep-mask select threads through every decode step even when
        # no request sets repetition_penalty (advisor r2: megabytes at
        # production vocab x slot counts, not gigabytes — acceptable; if
        # slot counts grow, allocate lazily / gate the select on
        # any-penalty-enabled)
        seen=jnp.zeros((n_slots, cfg.vocab_size), bool),
        rng=jnp.zeros((n_slots, 2), jnp.uint32),
    )


def sample_rows(
    logits: jax.Array,  # f32[B, V]
    temperature: jax.Array,  # f32[B]
    top_k: jax.Array,  # i32[B]
    top_p: jax.Array,  # f32[B]
    rep_penalty: jax.Array,  # f32[B]
    seen: jax.Array,  # bool[B, V]
    rng: jax.Array,  # u32[B, 2]
    counter: jax.Array,  # i32[B] — folded in so each step draws fresh noise
) -> jax.Array:
    logits = apply_repetition_penalty(logits, seen, rep_penalty)

    # filter at BATCH level so filter_logits' lax.cond fast-paths engage
    # (inside the vmap a batched predicate would lower to select and pay
    # the full-vocab nucleus sort on every step even with filters off);
    # only the per-row gumbel pick is vmapped
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    def pick_sampled(_):
        filtered = filter_logits(scaled, top_k, top_p)

        def pick_one(row_logits, row_filtered, key_data, ctr, temp):
            key = jax.random.fold_in(
                jax.random.wrap_key_data(key_data, impl="threefry2x32"),
                ctr,
            )
            return gumbel_pick(row_logits, row_filtered, key, temp)

        return jax.vmap(pick_one)(
            logits, filtered, rng, counter, temperature
        )

    def pick_greedy(_):
        # exactly gumbel_pick's temperature <= 0 branch: argmax of the
        # RAW (post-penalty) logits, so an all-greedy batch draws
        # bit-identical tokens to the sampled path's per-row select
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # all-greedy fast path: the per-row threefry fold + full-vocab
    # gumbel noise is the dominant per-draw cost (not the argmax), and
    # a verify window draws 2k+1 times per dispatch — skipping the RNG
    # when no row samples is what keeps speculation ahead of plain
    # decode on dispatch-bound hosts
    return jax.lax.cond(
        jnp.any(temperature > 0), pick_sampled, pick_greedy, None
    )


# --- the shared single-token forward ---------------------------------------


def step_forward(
    params: Params,
    cfg: ModelConfig,
    tok: jax.Array,  # i32[B] each row's last token
    offset: jax.Array,  # i32[B] each row's next cache position
    kv_caches,  # per-layer (k, v): dense [B, S, ...] or paged pool
    cache_len: int,  # logical per-row cache width S
    block_tables: jax.Array | None = None,  # i32[B, max_blocks] = paged
    sharded: bool = False,  # caller jits under a tp-sharded EngineLayout
):
    """One decode token's forward pass for a length-ragged batch;
    returns (logits f32[B, V], updated kv_caches).

    The dense and paged routes share everything but the attention
    reader: both scatter the step's K/V at each row's own offset
    (decoder_layer picks the table-indirect scatter when
    ``block_tables`` is given) and attend to positions ``< offset + 1``.
    On TPU the decode kernels DMA only each row's live tiles (the
    lengths operand == the mask's live set); the bool mask remains the
    dense fallback operand.

    ``sharded`` pins the attention routers' GSPMD-partitionable branch
    (flash_attention: Pallas custom calls cannot be split over heads) —
    the rest of the trace is einsums and the table scatter/gather,
    which partition over the pool's n_kv axis as-is."""
    B = tok.shape[0]
    mask = (jnp.arange(cache_len)[None, None, :]
            < (offset + 1)[:, None, None])
    mask = jnp.broadcast_to(mask, (B, 1, cache_len))
    if block_tables is None:
        def attn_fn(q, k, v, m):
            return decode_attention_auto(
                q, k, v, offset + 1, m, gspmd=sharded
            )
    else:
        # int8 pool: cache entries are (pages, scales, tail) triples
        # (trace-static pytree structure), routed to the dequant-in-
        # kernel readers; decoder_layer scattered the step's K/V into
        # the tail, never the quantized pages
        quantized = bool(kv_caches) and isinstance(kv_caches[0][0], tuple)

        def attn_fn(q, kc, vc, m):
            if quantized:
                kp, ks, ktl = kc
                vp, vs, vtl = vc
                return decode_attention_blocks_q8_auto(
                    q, kp, vp, ks, vs, ktl, vtl, block_tables,
                    offset + 1, m, gspmd=sharded,
                )
            return decode_attention_blocks_auto(
                q, kc, vc, block_tables, offset + 1, m, gspmd=sharded
            )
    logits, kv_caches = forward(
        params, tok[:, None], cfg,
        positions=offset[:, None],
        attn_mask=mask,
        kv_caches=kv_caches,
        cache_offset=offset,
        block_tables=block_tables,
        attn_fn=attn_fn,
        wq_gspmd=sharded,
    )
    return logits[:, 0], kv_caches


# --- the continuous batcher's fused window ---------------------------------


def _zip_kv(state: SlotState):
    """Per-layer cache entries for forward(): (k, v) pairs in bf16
    mode, ((pages, scales, tail), ...) triples in int8 mode. The
    branch is trace-static (pool dtype), so each kv_dtype compiles its
    own program — exactly the one-shape-per-(K, layout, kv_dtype)
    contract."""
    if state.caches_k and state.caches_k[0].dtype == jnp.int8:
        return [
            ((pk, sk, tk), (pv, sv, tv))
            for pk, sk, tk, pv, sv, tv in zip(
                state.caches_k, state.scales_k, state.tails_k,
                state.caches_v, state.scales_v, state.tails_v,
            )
        ]
    return list(zip(state.caches_k, state.caches_v))


def _commit_full_tails(pools, scales, tails, tables, old_off, new_off,
                       keep, block_size):
    """Quantize-on-commit: rows whose window moved ``offset`` across a
    block boundary have just FILLED tail slot 0 — quantize it
    (kv_blocks.quantize_blocks) into the row's pool block + scale row
    and shift the tail down a block (slot 0 <- slot 1, slot 1 <-
    zeros). At most one boundary per window by construction: n_emit <=
    k+1 <= WINDOW_BUCKETS[-1]+1 < block_size. Non-crossed rows scatter
    their block's CURRENT value back at its own index, so duplicate
    indices (inactive rows all naming null block 0) write identical
    values — deterministic, the same discipline as the null-block
    decode scatter. Returns (pools, scales, tails) lists."""
    B = old_off.shape[0]
    M = tables.shape[1]
    rows = jnp.arange(B)
    crossed = keep & (new_off // block_size > old_off // block_size)
    blk = tables[rows, jnp.clip(old_off // block_size, 0, M - 1)]
    out_p, out_s, out_t = [], [], []
    for pool, sc, tail in zip(pools, scales, tails):
        qv, sv = quantize_blocks(tail[:, 0])
        out_p.append(pool.at[blk].set(
            jnp.where(crossed[:, None, None, None], qv, pool[blk])
        ))
        out_s.append(sc.at[blk].set(
            jnp.where(crossed[:, None], sv, sc[blk])
        ))
        shifted = jnp.concatenate(
            [tail[:, 1:], jnp.zeros_like(tail[:, :1])], axis=1
        )
        out_t.append(jnp.where(
            crossed[:, None, None, None, None], shifted, tail
        ))
    return out_p, out_s, out_t


def decode_body(
    params: Params, state: SlotState, cfg: ModelConfig,
    sharded: bool = False,
) -> tuple[SlotState, jax.Array]:
    """One token for every active slot (greedy, or per-slot temperature
    sampling keyed by the slot PRNG + offset); returns (state, tokens).

    Inactive slots still flow through the math (static shapes) but their
    cache/offset/token state is preserved unchanged. This is the scan
    body of :func:`decode_window` — kept un-jitted so the window's K
    steps trace into one program."""
    block_size = state.caches_k[0].shape[1]
    S = state.tables.shape[1] * block_size  # logical per-row cache width
    quantized = state.caches_k[0].dtype == jnp.int8
    logits, caches = step_forward(
        params, cfg, state.last_token, state.offset,
        _zip_kv(state), S,
        block_tables=state.tables, sharded=sharded,
    )
    # counter offset+1: admit folds prompt_len (== first decode offset),
    # so folding the bare offset here would reuse the admit-time gumbel
    # draw and systematically double the first sampled token
    nxt = sample_rows(
        logits, state.temperature, state.top_k, state.top_p,
        state.rep_penalty, state.seen, state.rng, state.offset + 1,
    )

    keep = state.active
    new_off = jnp.where(keep, state.offset + 1, state.offset)
    if quantized:
        # the step's K/V landed in the tails; pages/scales passed
        # through forward untouched, and the boundary commit below
        # quantizes any tail block this token just filled
        tails_k = [c[0][2] for c in caches]
        tails_v = [c[1][2] for c in caches]
        pk, sk, tk = _commit_full_tails(
            state.caches_k, state.scales_k, tails_k, state.tables,
            state.offset, new_off, keep, block_size,
        )
        pv, sv, tv = _commit_full_tails(
            state.caches_v, state.scales_v, tails_v, state.tables,
            state.offset, new_off, keep, block_size,
        )
        kv_fields = dict(caches_k=pk, caches_v=pv, scales_k=sk,
                         scales_v=sv, tails_k=tk, tails_v=tv)
    else:
        kv_fields = dict(
            # no keep-masking on the pool: a retired slot's table row
            # is all-null (see batching._maybe_retire), so an inactive
            # row's scatter lands in the sacrificial block 0 and the
            # pool is taken as-is (a per-row where over a SHARED pool
            # would be wrong anyway — rows no longer own disjoint
            # stripes)
            caches_k=[c[0] for c in caches],
            caches_v=[c[1] for c in caches],
        )
    # dataclasses.replace carries unchanged fields automatically — a
    # full-constructor copy here silently reset any SlotState field
    # added later (this diff had to hand-thread top_k/top_p through two
    # such copies before the conversion)
    new_state = dataclasses.replace(
        state,
        last_token=jnp.where(keep, nxt, state.last_token),
        offset=new_off,
        # record_seen self-gates on any-penalty-enabled; masking by
        # keep afterwards preserves inactive slots
        seen=jnp.where(
            keep[:, None],
            record_seen(state.seen, nxt, state.rep_penalty),
            state.seen,
        ),
        **kv_fields,
    )
    return new_state, jnp.where(keep, nxt, -1)


@functools.partial(
    jax.jit, static_argnames=("cfg", "k", "sharded"), donate_argnums=(1,)
)
def decode_window(
    params: Params, state: SlotState, cfg: ModelConfig, k: int,
    sharded: bool = False,
) -> tuple[SlotState, jax.Array]:
    """K fused decode steps in ONE dispatch; returns (state, i32[B, K]).

    The scan threads the donated SlotState through K copies of
    :func:`decode_body`, so each step's sampling sees exactly the state
    a lone dispatch would have seen — token streams are bit-identical
    to K single-step dispatches (the keys are position-folded, not
    stream-split). ``active`` never changes mid-window (retirement is
    host work): a row whose EOS lands mid-window keeps stepping and
    keeps scattering into its own refcounted blocks — positions nobody
    will ever read, since the host masks the tail tokens on readback
    and the horizon clamp keeps every write inside the row's allocated
    block span. -1 marks inactive rows' tokens, exactly as at K=1.

    Under a sharded EngineLayout the ENGINE passes ``sharded=True`` and
    a SlotState whose leaves are placed (pool along n_kv, rest
    replicated): jit keys the executable on those input shardings, so
    the donated scan carry keeps its placement across windows and the
    compiled-shape set stays one per (K-bucket, layout) — the same
    donation discipline as tp=1, with GSPMD's psums inside the scan
    body. The static flag only pins the attention routers' dense
    branch; at tp=1 its False default leaves the trace byte-identical
    to the pre-layout engine."""

    def step(st, _):
        return decode_body(params, st, cfg, sharded)

    state, toks = jax.lax.scan(step, state, None, length=k)
    # scan stacks on the leading (time) axis; callers want [slot, step]
    return state, jnp.swapaxes(toks, 0, 1)


# --- speculative verify window ---------------------------------------------


@dataclass
class DraftState:
    """Device-resident draft-model state for the verify window, one row
    per slot. The draft keeps DENSE per-row caches (``[n_slots, Ld,
    n_kv_d, D_d]``): it is orders of magnitude smaller than the target,
    so paging it would buy nothing and would couple its block accounting
    to the pool's. Invariant at rest (target offset ``o``): committed
    draft KV covers positions ``0 .. o-2``; positions ``o-1`` and ``o``
    are rewritten by each window's repair forward from ``prev`` and the
    slot's ``last_token``, so stale KV from rejected proposals is never
    attended (every position the propose scan reads was either
    committed, repaired this window, or written earlier in the same
    scan)."""

    caches_k: list[jax.Array]  # L_d x [n_slots, Ld, n_kv_d, D_d]
    caches_v: list[jax.Array]
    prev: jax.Array  # i32[n_slots] token at target position offset - 1


jax.tree_util.register_dataclass(
    DraftState,
    data_fields=["caches_k", "caches_v", "prev"],
    meta_fields=[],
)


def init_draft_state(dcfg: ModelConfig, n_slots: int, cache_len: int,
                     dtype) -> DraftState:
    # Ld == cache_len suffices: the propose scan's deepest write is
    # position o + k - 1, and the engine only dispatches verify for
    # rows with prompt + max_new + k <= cache_len (spec_ok), which
    # bounds o + k - 1 <= cache_len - 2.
    shape = (n_slots, cache_len, dcfg.num_key_value_heads, dcfg.head_dim)
    return DraftState(
        caches_k=[jnp.zeros(shape, dtype)
                  for _ in range(dcfg.num_hidden_layers)],
        caches_v=[jnp.zeros(shape, dtype)
                  for _ in range(dcfg.num_hidden_layers)],
        prev=jnp.zeros((n_slots,), jnp.int32),
    )


def spec_accept(drafts: jax.Array, target_toks: jax.Array) -> jax.Array:
    """THE acceptance rule — the only implementation in the repo
    (speculative.py routes here too). ``drafts`` i32[B, k] are the
    proposals; ``target_toks`` i32[B, k+1] are the target model's own
    samples at the same positions under the same position-folded noise.
    Draft i survives iff it equals the target's sample AND every
    earlier draft survived (cumprod); the target's sample after the
    last survivor is always emitted, so n_emit = m + 1 in [1, k+1].

    Exact-match acceptance (not rejection sampling) is what buys token
    identity: the emitted row IS the target's sample stream, so the
    output distribution equals the non-speculative engine's by
    construction — correlated draft/target noise only moves the
    acceptance RATE, never the output law."""
    k = drafts.shape[1]
    agree = (drafts == target_toks[:, :k]).astype(jnp.int32)
    m = jnp.cumprod(agree, axis=1).sum(axis=1)
    return m + 1


@functools.partial(
    jax.jit, static_argnames=("cfg", "dcfg", "k", "sharded"),
    donate_argnums=(1, 3),
)
def verify_window(
    params: Params, state: SlotState,
    dparams: Params, dstate: DraftState,
    cfg: ModelConfig, dcfg: ModelConfig, k: int,
    sharded: bool = False,
) -> tuple[SlotState, DraftState, jax.Array]:
    """Speculative twin of :func:`decode_window`: ONE dispatch proposes
    k draft tokens per live row, scores all k+1 window positions
    through the block-table attention path ([k+1, G, D] queries — the
    kernel's verify generalization), and emits each row's accepted
    prefix. Returns (state, dstate, toks i32[B, k+1]) where row b's
    first n_emit entries are its emitted tokens and the rest are -1
    (the same negative-token skip convention the host drain already
    applies to decode_window's output).

    Token identity with the plain engine is by construction, not by
    tuning: target tokens are drawn by the SAME :func:`sample_rows`
    with the SAME position-folded counters the single-step path folds
    (position p -> counter p), the seen-set evolves only along the
    emitted (alive) prefix, and ``rng`` is never mutated — so the
    emitted stream bitwise-equals what decode_window would have
    produced, window boundaries and acceptance rate notwithstanding.

    Rollback is free on the device side: rejected positions' KV stays
    in the row's own refcounted blocks but past the new offset, where
    the next window's scatter-before-attend overwrites it (positions
    ``o' .. o'+k`` cover the junk span because n_emit <= k+1). The
    HOST must still never publish those positions (radix inserts stay
    behind the committed offset — batching's ``toks[:-1]`` rule).

    ``active`` gates everything: inactive rows propose/verify into the
    null block 0 (static shapes), their n_emit is 0, and their
    last_token/offset/seen/prev are preserved unchanged.

    Reference divergence: vLLM keeps draft scheduling inside the
    subprocess (internal/agent/vllm.go:93-112); here the verify window
    is a first-class engine dispatch so it composes with the paged
    pool, preemption, and the sharded layout."""
    B = state.last_token.shape[0]
    block_size = state.caches_k[0].shape[1]
    S = state.tables.shape[1] * block_size
    # a 0-layer (bigram) draft carries no KV at all — Ld then only
    # shapes the repair mask, which no layer reads; S keeps the shape
    # well-formed without a dedicated branch downstream
    Ld = S
    if dcfg.num_hidden_layers > 0:
        Ld = dstate.caches_k[0].shape[1]
    o = state.offset
    T = k + 1

    # --- draft propose -----------------------------------------------------
    # Repair forward: rewrite draft KV at positions o-1 (prev) and o
    # (last_token). This is what makes preemption/rollback cheap — the
    # draft cache never needs host fixup because the only positions a
    # fresh window depends on beyond the committed prefix are rebuilt
    # here from host-verified tokens. dlogits[:, 1] predicts position
    # o+1, the first proposal.
    rep_tok = jnp.stack([dstate.prev, state.last_token], axis=1)
    rep_pos = jnp.stack([o - 1, o], axis=1)
    rep_mask = (jnp.arange(Ld)[None, None, :] <= rep_pos[:, :, None])
    dcaches = list(zip(dstate.caches_k, dstate.caches_v))
    # attn_fn=None -> dense attention: the draft's caches are dense
    # per-row, and the model is small enough that a kernel would be
    # dispatch-bound anyway.
    dlogits, dcaches = forward(
        dparams, rep_tok, dcfg,
        positions=rep_pos, attn_mask=rep_mask,
        kv_caches=dcaches, cache_offset=o - 1,
    )
    dseen = state.seen
    d1 = sample_rows(
        dlogits[:, 1], state.temperature, state.top_k, state.top_p,
        state.rep_penalty, dseen, state.rng, o + 1,
    )
    dseen = record_seen(dseen, d1, state.rep_penalty)

    if k > 1:
        def dstep(carry, i):
            caches_i, tok, seen_i = carry
            lg, caches_i = step_forward(
                dparams, dcfg, tok, o + i, caches_i, Ld, sharded=sharded,
            )
            nxt = sample_rows(
                lg, state.temperature, state.top_k, state.top_p,
                state.rep_penalty, seen_i, state.rng, o + i + 1,
            )
            seen_i = record_seen(seen_i, nxt, state.rep_penalty)
            return (caches_i, nxt, seen_i), nxt

        (dcaches, _, _), rest = jax.lax.scan(
            dstep, (dcaches, d1, dseen),
            jnp.arange(1, k, dtype=jnp.int32),
        )
        drafts = jnp.concatenate(
            [d1[:, None], jnp.swapaxes(rest, 0, 1)], axis=1
        )
    else:
        drafts = d1[:, None]

    # --- fused verify ------------------------------------------------------
    # The window's T tokens [last, d_1 .. d_k] occupy logical positions
    # o .. o+k: decoder_layer scatters their KV into the row's blocks
    # FIRST, then the T-query kernel attends s <= o + t per query —
    # exactly the mask below, per decode_attention_blocks_auto's
    # contract (lengths == o + T).
    window = jnp.concatenate([state.last_token[:, None], drafts], axis=1)
    positions = o[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    mask = jnp.arange(S)[None, None, :] <= positions[:, :, None]
    lengths = o + T
    quantized = state.caches_k[0].dtype == jnp.int8

    if quantized:
        # q8 router derives tail_base = (lengths - T) // block_size
        # == o // block_size, the block the tail slots were pinned to
        # at window start — exactly where decoder_layer lands the
        # window's scatter (rel in {0, 1}, one crossing max per window
        # since T <= k + 1 < block_size)
        def attn_fn(q, kc, vc, m):
            kp, ks, ktl = kc
            vp, vs, vtl = vc
            return decode_attention_blocks_q8_auto(
                q, kp, vp, ks, vs, ktl, vtl, state.tables, lengths, m,
                gspmd=sharded,
            )
    else:
        def attn_fn(q, kp, vp, m):
            return decode_attention_blocks_auto(
                q, kp, vp, state.tables, lengths, m, gspmd=sharded
            )

    logits, caches = forward(
        params, window, cfg,
        positions=positions, attn_mask=mask,
        kv_caches=_zip_kv(state),
        cache_offset=o, block_tables=state.tables, attn_fn=attn_fn,
        wq_gspmd=sharded,
    )

    # --- acceptance --------------------------------------------------------
    # logits[:, i] predicts position o+1+i; sample it with counter
    # o+1+i — the identical draw the single-step engine would make at
    # that position. The scan threads the seen-set along the ALIVE
    # prefix only: a row's seen must reflect exactly its emitted
    # tokens, and sampling depends on seen, so acceptance and sampling
    # have to interleave sequentially (this is VPU-cheap next to the
    # fused forward above).
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    xs = (
        jnp.swapaxes(logits, 0, 1),
        jnp.swapaxes(drafts_pad, 0, 1),
        jnp.arange(T, dtype=jnp.int32),
    )

    def astep(carry, xs_i):
        seen_i, alive = carry
        lg, d_next, i = xs_i
        t = sample_rows(
            lg, state.temperature, state.top_k, state.top_p,
            state.rep_penalty, seen_i, state.rng, o + 1 + i,
        )
        seen_i = jnp.where(
            alive[:, None],
            record_seen(seen_i, t, state.rep_penalty),
            seen_i,
        )
        alive = alive & (i < k) & (d_next == t)
        return (seen_i, alive), t

    (seen_f, _), t_seq = jax.lax.scan(astep, (state.seen, state.active), xs)
    target = jnp.swapaxes(t_seq, 0, 1)  # [B, T]
    n_emit = jnp.where(state.active, spec_accept(drafts, target), 0)

    # --- boundary state ----------------------------------------------------
    # Token at the new offset o' = o + n_emit is target[n_emit-1]; the
    # one at o'-1 (the next repair window's `prev`) is target[n_emit-2]
    # when two or more tokens were emitted, else the old last_token.
    rows = jnp.arange(B)
    last_new = target[rows, jnp.clip(n_emit - 1, 0, k)]
    prev_new = jnp.where(
        n_emit >= 2, target[rows, jnp.clip(n_emit - 2, 0, k)],
        state.last_token,
    )
    keep = state.active
    new_off = jnp.where(keep, o + n_emit, o)
    if quantized:
        tails_k = [c[0][2] for c in caches]
        tails_v = [c[1][2] for c in caches]
        pk, sk, tk = _commit_full_tails(
            state.caches_k, state.scales_k, tails_k, state.tables,
            o, new_off, keep, block_size,
        )
        pv, sv, tv = _commit_full_tails(
            state.caches_v, state.scales_v, tails_v, state.tables,
            o, new_off, keep, block_size,
        )
        kv_fields = dict(caches_k=pk, caches_v=pv, scales_k=sk,
                         scales_v=sv, tails_k=tk, tails_v=tv)
    else:
        kv_fields = dict(
            caches_k=[c[0] for c in caches],
            caches_v=[c[1] for c in caches],
        )
    new_state = dataclasses.replace(
        state,
        last_token=jnp.where(keep, last_new, state.last_token),
        offset=new_off,
        seen=seen_f,  # already alive-masked in-scan; alive_0 = active
        **kv_fields,
    )
    new_dstate = dataclasses.replace(
        dstate,
        caches_k=[c[0] for c in dcaches],
        caches_v=[c[1] for c in dcaches],
        prev=jnp.where(keep, prev_new, dstate.prev),
    )
    toks = jnp.where(
        jnp.arange(T, dtype=jnp.int32)[None, :] < n_emit[:, None],
        target, -1,
    )
    return new_state, new_dstate, toks


# --- the per-request / sequence-parallel fused loop ------------------------


def decode_scan(
    params: Params,
    cfg: ModelConfig,
    caches,  # per-layer (k, v) with the prompt's KV already written
    next_logits: jax.Array,  # f32[B, V] logits at each row's last prompt pos
    prompt: jax.Array,  # i32[B, T_bucket] (repetition-penalty seed state)
    prompt_len: jax.Array,  # i32[B]; rows may be length-ragged
    max_new: int,
    cache_len: int,
    eos_id: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    rep_penalty: jax.Array,
    rng_key: jax.Array,
):
    """The decode loop shared by every prefill strategy (chunked single-
    device, sequence-parallel ring — sp_engine.py): sample from
    ``next_logits``, then scan single-token steps against the caches.
    Callers jit.

    Key-schedule note: this loop pre-splits a per-call PRNG key, the
    slot path folds per-position counters — the two streams are
    intentionally different (a generate() is one key universe, a slot
    survives many requests), which is why cross-engine parity tests
    compare greedy streams only."""
    B = prompt.shape[0]

    def sample(logits, key, seen):
        logits = apply_repetition_penalty(logits, seen, rep_penalty)
        return gumbel_sample(logits, key, temperature, top_k, top_p)

    seen = seen_from_prompt(prompt, prompt_len, cfg.vocab_size)
    k0, krest = jax.random.split(rng_key)
    first = sample(next_logits, k0, seen)
    seen = record_seen(seen, first, rep_penalty)

    def step(carry, key):
        caches, tok, offset, done, seen = carry
        # per-row offsets: each row writes its token at its OWN cache
        # position (batched scatter in decoder_layer) and attends to
        # its own live prefix — one dispatch decodes a length-ragged
        # batch (step_forward builds the identical mask/attention the
        # paged window uses, minus the table indirection)
        logits, caches = step_forward(
            params, cfg, tok, offset, caches, cache_len,
        )
        nxt = sample(logits, key, seen)
        seen = record_seen(seen, nxt, rep_penalty)
        newly_done = (nxt == eos_id) & (eos_id >= 0)
        nxt = jnp.where(done, eos_id, nxt)
        done = done | newly_done
        return (caches, nxt, offset + 1, done, seen), nxt

    done0 = (first == eos_id) & (eos_id >= 0)
    if max_new > 1:
        keys = jax.random.split(krest, max_new - 1)
        (_, _, _, done, _), rest = jax.lax.scan(
            step,
            (caches, first, prompt_len, done0, seen),
            keys,
            length=max_new - 1,
        )
        toks = jnp.concatenate(
            [first[:, None], rest.swapaxes(0, 1)], axis=1
        )
    else:
        toks = first[:, None]
    # generated length = tokens up to and including first EOS
    is_eos = (toks == eos_id) & (eos_id >= 0)
    first_eos = jnp.where(
        is_eos.any(axis=1), is_eos.argmax(axis=1) + 1, max_new
    )
    return toks, first_eos.astype(jnp.int32)
