"""Static-shape generation engine: prefill + KV-cache decode.

XLA-friendly by construction (SURVEY.md §7 / task brief "no
data-dependent Python control flow inside jit"):

- prompts pad to bucketed lengths (powers of two), so prefill compiles
  once per bucket;
- the decode loop is ONE jitted ``lax.scan`` over ``max_new_tokens``
  steps writing into a fixed-capacity KV cache — no per-token dispatch,
  no dynamic shapes; finished sequences (EOS) keep stepping but their
  outputs are masked (the standard static-shape idiom). The loop itself
  lives in stepper.py (shared with the sequence-parallel engine and the
  continuous batcher's fused windows — ROADMAP item 3's unification);
- sampling is greedy or temperature (gumbel trick) selected by a traced
  scalar, so one compilation serves both.

The engine is deliberately single-batch-slot-array: request batching
happens by stacking prompts into the [B] axis (the server batches
per-request today; continuous batching slots into the same static
shapes).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.flash_attention import (
    attention_auto,
    flash_attention_ragged,
    flash_available,
)
from kubeinfer_tpu.inference.model import Params, forward
from kubeinfer_tpu.observability import tracing

_TRACER = tracing.get_tracer("engine")

PROMPT_BUCKETS = (
    16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768,
    65536, 131072,  # long-context models advertise up to 128k positions
)
# Prefill processes the prompt in chunks of this many tokens (peak
# attention memory O(chunk * cache_len), not O(T^2)); every bucket > 512
# is a multiple of it.
PREFILL_CHUNK = 512
# Per-call prefill token budget: larger chunks amortize the per-chunk
# weight sweep and per-tile entry costs (measured on v5e at the 280M
# bench model, 2048-token prompt: chunk 512 -> 0.37 MFU, 2048 -> 0.46),
# while the budget bounds the transient [B, C, *] activation memory as
# the batch grows. The full-vocab head no longer scales with C
# (chunked_prefill applies it once on the selected rows).
PREFILL_TOKEN_BUDGET = 2048


def prefill_chunk_for(batch: int, prompt_bucket: int) -> int:
    """Adaptive prefill chunk: as much of the token budget as one row's
    bucket can use, never below PREFILL_CHUNK (the long-prompt floor).

    Floored to a power of two so the chunk always DIVIDES the bucket
    (PROMPT_BUCKETS are powers of two; PREFILL_CHUNK is too): a
    non-dividing chunk would make the scan's final dynamic_slice clamp
    its start and silently re-process tokens at wrong RoPE/cache
    positions (review-found with batch=3)."""
    per_row = max(PREFILL_TOKEN_BUDGET // max(batch, 1), 1)
    pow2 = 1 << (per_row.bit_length() - 1)
    return min(prompt_bucket, max(PREFILL_CHUNK, pow2))


def _bucket(n: int) -> int:
    for b in PROMPT_BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds {PROMPT_BUCKETS[-1]}")


@dataclass
class GenerationResult:
    tokens: np.ndarray  # i32[B, max_new] generated ids (EOS-padded)
    lengths: np.ndarray  # i32[B] generated length per sequence


# Static cap for the top-k filter: lax.top_k needs a static k, so the
# kth-largest threshold reads from a fixed [.., TOP_K_CAP] candidate
# slice; requested k above the cap clips to it (k=64 is already far past
# any practically distinguishable nucleus).
TOP_K_CAP = 64


def filter_logits(
    logits: jax.Array,  # f32[..., V]
    top_k: jax.Array,  # i32 broadcastable to logits[..., 0]; <1 = off
    top_p: jax.Array,  # f32 broadcastable to logits[..., 0]; >=1 = off
) -> jax.Array:
    """Top-k then nucleus (top-p) filtering: non-kept logits -> -inf.

    Both knobs are traced (per-row in the continuous batcher), so the
    expensive parts — the top-k candidate scan and the full-vocab sort
    nucleus needs — sit behind ``lax.cond`` on "any row has the filter
    on": a disabled filter costs nothing per decode step at runtime.
    Order matches HF: temperature scaling happens in the caller BEFORE
    filtering, so top-p nuclei are computed on the tempered
    distribution. One documented divergence (advisor r2): the nucleus
    cut is a probability THRESHOLD, so vocab entries exactly tying the
    boundary token's probability are all kept — a slightly wider nucleus
    than HF's shift-right positional cutoff on exact ties (e.g. sorted
    probs [.4, .3, .3] at top_p=0.7 keep 3 here, 2 in HF). Exact
    probability ties are measure-zero for real logits; the threshold
    form avoids a scatter back through argsort indices on TPU.
    """
    V = logits.shape[-1]
    cap = min(TOP_K_CAP, V)
    lead = logits.shape[:-1]
    top_k = jnp.broadcast_to(top_k, lead)
    top_p = jnp.broadcast_to(top_p, lead)

    def apply_topk(x):
        topvals = jax.lax.top_k(x, cap)[0]  # [..., cap] descending
        k_idx = jnp.clip(top_k - 1, 0, cap - 1)[..., None]
        kth = jnp.take_along_axis(topvals, k_idx, axis=-1)
        on = (top_k >= 1)[..., None]
        return jnp.where(on & (x < kth), -jnp.inf, x)

    def apply_topp(x):
        probs = jax.nn.softmax(x, axis=-1)
        sorted_p = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
        cum_excl = jnp.cumsum(sorted_p, axis=-1) - sorted_p
        keep_sorted = cum_excl < top_p[..., None]
        # the argmax always survives, even for top_p <= 0 (where the
        # cumulative test keeps nothing and sampling would otherwise
        # collapse to token id 0 via an all -inf row)
        keep_sorted = keep_sorted.at[..., 0].set(True)
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_p, jnp.inf), axis=-1,
            keepdims=True,
        )
        on = (top_p < 1.0)[..., None]
        return jnp.where(on & (probs < thresh), -jnp.inf, x)

    logits = jax.lax.cond(
        jnp.any(top_k >= 1), apply_topk, lambda x: x, logits
    )
    return jax.lax.cond(
        jnp.any(top_p < 1.0), apply_topp, lambda x: x, logits
    )


def seen_from_prompt(
    prompt: jax.Array,  # i32[B, T] 0-padded
    prompt_len: jax.Array,  # i32[B]
    vocab: int,
) -> jax.Array:
    """bool[B, V]: which vocab ids appear in each row's real prompt.
    Pad columns are excluded (id 0 would otherwise always count).

    Scatter-max, NOT a one-hot contraction: a [B, T, V] one-hot at
    production vocab (152k) and a 4096 bucket is ~20GB of f32 — the
    scatter is O(B*T) work into the O(B*V) output. It runs once per
    generate(), so the TPU scatter-serialization cost is irrelevant.
    """
    B, T = prompt.shape
    valid = jnp.arange(T)[None, :] < prompt_len[:, None]
    return (
        jnp.zeros((B, vocab), bool)
        .at[jnp.arange(B)[:, None], prompt]
        .max(valid)
    )


def record_seen(
    seen: jax.Array,  # bool[B, V]
    tokens: jax.Array,  # i32[B] newly generated ids
    penalty: jax.Array,  # f32 broadcastable to [B]; 1.0 = disabled
) -> jax.Array:
    """Mark freshly generated ids as seen — behind the same disabled
    check as the penalty itself, so penalty-free decodes don't pay a
    [B, V] update per step."""

    def update(s):
        B = tokens.shape[0]
        return s.at[jnp.arange(B), tokens].max(True)

    return jax.lax.cond(jnp.any(penalty != 1.0), update, lambda s: s, seen)


def apply_repetition_penalty(
    logits: jax.Array,  # f32[B, V]
    seen: jax.Array,  # bool[B, V] ids present in prompt or generated
    penalty: jax.Array,  # f32 broadcastable to [B]; 1.0 = disabled
) -> jax.Array:
    """HF RepetitionPenaltyLogitsProcessor semantics: seen ids get
    logit/penalty when positive, logit*penalty when negative. Runs on
    RAW logits before temperature, and — unlike the top-k/top-p
    filters — affects the greedy argmax too (it reshapes the
    distribution, not just the sampling set). Behind lax.cond: disabled
    costs nothing per step."""
    penalty = jnp.broadcast_to(jnp.asarray(penalty, jnp.float32),
                               logits.shape[:-1])

    def apply(x):
        pen = penalty[..., None]
        adj = jnp.where(x > 0, x / pen, x * pen)
        return jnp.where(seen, adj, x)

    return jax.lax.cond(
        jnp.any(penalty != 1.0), apply, lambda x: x, logits
    )


def gumbel_pick(
    raw_logits: jax.Array,
    filtered_scaled: jax.Array,
    key: jax.Array,
    temperature: jax.Array,
) -> jax.Array:
    """Final sampling step shared by every path: greedy argmax on the
    RAW logits when temperature <= 0, gumbel-argmax on the pre-tempered,
    pre-filtered logits otherwise. Split out so the continuous batcher
    can run ``filter_logits`` once at batch level (its lax.cond
    fast-path dies under vmap — a batched predicate lowers to select)
    and still share this exact pick."""
    greedy = jnp.argmax(raw_logits, axis=-1).astype(jnp.int32)
    g = jax.random.gumbel(key, raw_logits.shape, jnp.float32)
    sampled = jnp.argmax(filtered_scaled + g, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def gumbel_sample(
    logits: jax.Array,
    key: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array | int = 0,
    top_p: jax.Array | float = 1.0,
) -> jax.Array:
    """Temperature sampling via the gumbel trick; temperature <= 0 means
    greedy (filters don't apply — argmax always survives both). ONE home
    for the sampling math — the per-request engine and the continuous
    batcher must sample identically for the same params.
    """
    scaled = logits / jnp.maximum(temperature, 1e-6)
    filtered = filter_logits(
        scaled, jnp.asarray(top_k, jnp.int32), jnp.asarray(top_p, jnp.float32)
    )
    return gumbel_pick(logits, filtered, key, temperature)


def chunked_prefill(
    params: Params,
    prompt: jax.Array,  # i32[B, T] left-aligned, 0-padded
    prompt_len: jax.Array,  # i32[B]
    cfg: ModelConfig,
    caches,  # per-layer (k, v) fixed-capacity caches
    prefill_chunk: int,
):
    """Scan the prompt through the model in fixed-size chunks, filling
    the KV caches; returns (caches, next_logits) where next_logits[b] is
    the logits at row b's LAST real prompt position.

    Shared by the per-request engine and the speculative decoder — the
    chunking (peak attention memory O(chunk * cache_len), one trace for
    any prompt bucket) and the last-real-position logit selection must
    behave identically everywhere. Trace-time cost only: callers jit.
    """
    B, T = prompt.shape
    cache_len = caches[0][0].shape[1]
    C = min(T, prefill_chunk)
    pos = jnp.arange(cache_len)
    last = jnp.clip(prompt_len - 1, 0, T - 1)
    D = cfg.head_dim
    # static branch: kernel vs dense is decided by shapes/backend at
    # trace time, so only one path exists in the compiled program
    use_flash = flash_available(C, cache_len, D)

    def prefill_step(carry, c0):
        caches, next_hidden = carry
        chunk = jax.lax.dynamic_slice(prompt, (0, c0), (B, C))
        q_pos = c0 + jnp.arange(C)
        # attend to cache positions <= own position, and only to real
        # (non-pad) prompt positions. On the flash path this bool
        # [B, C, cache_len] is never consumed (the kernel derives the
        # identical mask in-kernel from (c0, prompt_len) iotas) and XLA
        # dead-code-eliminates its construction — nothing [T, S]-sized
        # exists at runtime there.
        mask = (
            (pos[None, None, :] <= q_pos[None, :, None])
            & (pos[None, None, :] < prompt_len[:, None, None])
        )
        mask = jnp.broadcast_to(mask, (B, C, cache_len))
        if use_flash:
            def attn_fn(q, k, v, _mask):
                return flash_attention_ragged(q, k, v, c0, prompt_len)
        else:
            # dense jnp path. Numerically equivalent to flash within
            # dtype tolerance, NOT bit-identical (online-softmax
            # reorders the summation), so near-tied greedy decodes may
            # differ across backends.
            attn_fn = attention_auto
        # hidden states, not logits: only ONE position per row feeds the
        # first sampled token, so the full-vocab head runs once on the
        # selected rows after the scan instead of per chunk token (~20%
        # of prefill FLOPs at 32k vocab, and no [C, V] f32 per chunk)
        hidden, caches = forward(
            params, chunk, cfg, attn_mask=mask, kv_caches=caches,
            cache_offset=c0, attn_fn=attn_fn, return_hidden=True,
        )
        # the row's next-token state lives in whichever chunk holds its
        # LAST REAL prompt position
        in_chunk = (last >= c0) & (last < c0 + C)
        idx = jnp.clip(last - c0, 0, C - 1)
        chunk_last = jnp.take_along_axis(
            hidden, idx[:, None, None], axis=1
        )[:, 0]
        next_hidden = jnp.where(in_chunk[:, None], chunk_last, next_hidden)
        return (caches, next_hidden), ()

    from kubeinfer_tpu.inference.model import lm_head_matrix

    (caches, next_hidden), _ = jax.lax.scan(
        prefill_step,
        (
            caches,
            jnp.zeros((B, cfg.hidden_size), params["norm"].dtype),
        ),
        jnp.arange(0, T, C),
    )
    next_logits = (next_hidden @ lm_head_matrix(params, cfg)).astype(
        jnp.float32
    )
    return caches, next_logits


def make_caches(cfg: ModelConfig, B: int, cache_len: int, dtype):
    return [
        (
            jnp.zeros((B, cache_len, cfg.num_key_value_heads, cfg.head_dim), dtype),
            jnp.zeros((B, cache_len, cfg.num_key_value_heads, cfg.head_dim), dtype),
        )
        for _ in range(cfg.num_hidden_layers)
    ]


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new", "cache_len", "prefill_chunk"),
)
def _generate_jit(
    params: Params,
    prompt: jax.Array,  # i32[B, T_bucket] left-aligned, 0-padded
    prompt_len: jax.Array,  # i32[B]
    cfg: ModelConfig,
    max_new: int,
    cache_len: int,
    prefill_chunk: int,
    eos_id: jax.Array,  # i32 (negative = never stop)
    temperature: jax.Array,  # f32; <=0 = greedy
    top_k: jax.Array,  # i32; <1 = disabled
    top_p: jax.Array,  # f32; >=1 = disabled
    rep_penalty: jax.Array,  # f32; 1.0 = disabled
    rng_key: jax.Array,
):
    # stepper imports this module's sampling helpers at module level, so
    # the decode loop comes back lazily (trace time only — inside the
    # jit, like batching's kernel imports)
    from kubeinfer_tpu.inference.stepper import decode_scan

    B, T = prompt.shape
    caches = make_caches(cfg, B, cache_len, params["norm"].dtype)

    # --- prefill: chunked so long prompts never materialize [T, T] ------
    # Each chunk of C tokens attends causally against the cache (a
    # [C, cache_len] mask), so peak attention memory is O(C * S) instead
    # of O(T^2) — the difference between a 128k-token prompt fitting in
    # HBM or not. The chunk loop is a scan (one trace regardless of
    # chunk count; 131072/512 unrolled copies would blow up compile).
    caches, next_logits = chunked_prefill(
        params, prompt, prompt_len, cfg, caches, prefill_chunk
    )
    return decode_scan(
        params, cfg, caches, next_logits, prompt, prompt_len, max_new,
        cache_len, eos_id, temperature, top_k, top_p, rep_penalty, rng_key,
    )


def prepare_prompts(
    prompts: list[list[int]],
    max_new_tokens: int,
    max_cache_len: int,
    slack: int = 0,
):
    """Host-side prompt prep shared by the engines: validate, bucket,
    pad, and size the KV cache. ``slack`` is extra cache capacity beyond
    prompt+new (the speculative decoder writes up to k+1 entries past
    the frontier). Returns (padded i32[B, T_bucket], lens i32[B],
    cache_len)."""
    lens = np.asarray([len(p) for p in prompts], np.int32)
    if lens.min() < 1:
        raise ValueError("empty prompt")
    T = _bucket(int(lens.max()))
    need = int(lens.max()) + max_new_tokens + slack
    if need > max_cache_len:
        raise ValueError(
            f"prompt+new tokens ({need}) exceed the model's context "
            f"capacity ({max_cache_len})"
        )
    # cache width: bucketed for jit-cache reuse, but never below the
    # prefill bucket T (a cache narrower than the prefill width would
    # write out of bounds). Bucket rounding may exceed max_cache_len;
    # positions stay < max_cache_len, extra columns are masked.
    cache_len = max(T, _bucket(need))
    padded = np.zeros((len(prompts), T), np.int32)
    for i, p in enumerate(prompts):
        padded[i, : len(p)] = p
    return padded, lens, cache_len


class Engine:
    """Generation front-end over a loaded model."""

    def __init__(self, params: Params, cfg: ModelConfig,
                 max_cache_len: int = 0) -> None:
        self.params = params
        self.cfg = cfg
        self.max_cache_len = max_cache_len or cfg.max_position_embeddings

    def generate(
        self,
        prompts: list[list[int]],
        max_new_tokens: int = 32,
        eos_id: int = -1,
        temperature: float = 0.0,
        seed: int = 0,
        top_k: int = 0,
        top_p: float = 1.0,
        repetition_penalty: float = 1.0,
    ) -> GenerationResult:
        """Batch generation, exact for ragged prompts.

        Prompts pad to a shared bucket for the prefill (pad columns
        masked via prompt_len) and the whole batch — mixed lengths
        included — decodes in ONE jit invocation: decode_scan carries a
        per-row cache offset, so no per-length grouping (the pre-ragged
        engine fragmented mixed traffic into per-length micro-batches,
        forfeiting the batch-scaling BENCH_r04 measured).
        """
        if not prompts:
            return GenerationResult(
                np.zeros((0, 0), np.int32), np.zeros((0,), np.int32)
            )
        B = len(prompts)
        with _TRACER.span("engine.generate", batch=B,
                          max_new=max_new_tokens):
            padded, lens, cache_len = prepare_prompts(
                prompts, max_new_tokens, self.max_cache_len
            )
            toks, glens = _generate_jit(
                self.params,
                jnp.asarray(padded),
                jnp.asarray(lens),
                self.cfg,
                max_new_tokens,
                cache_len,
                prefill_chunk_for(B, int(padded.shape[1])),
                jnp.int32(eos_id),
                jnp.float32(temperature),
                jnp.int32(top_k),
                jnp.float32(top_p),
                jnp.float32(repetition_penalty),
                jax.random.PRNGKey(seed),
            )
            # lint: allow[host-sync] serving boundary: one readback per batch
            toks_out = np.asarray(toks)
            lens_out = np.asarray(glens)  # lint: allow[host-sync] same readback as the line above
        return GenerationResult(toks_out, lens_out)
