"""Int8 weights: load-time per-tile quantization + fused dequant-matmul.

The reference hands quantization to vLLM as an opaque engine argument
(vllm.go:57-61 — the flag rides the subprocess command line and the Go
plane never sees a weight); here the engine owns the execution plane,
so the quantized representation must compose with everything the plane
already does: TP sharding (scale planes shard along the weight's out
axis, sharding.expand_quant_specs), speculative verify and chunked
prefill (both just call model.forward, which routes every projection
matmul through ``wq_dot``), and checkpointing (meta.json records
``weight_dtype`` so a restore never double-quantizes).

Quantization math is kv_blocks.quantize_blocks' absmax scheme applied
per (out-column tile) instead of per (block, head): symmetric,
scale = amax/127 with the zero-tile guard pinning scale to 1.0, and the
same dequant→requant-exact property. Granularity rationale: one scale
per out-tile (default 128 columns — one MXU lane tile) keeps the scale
plane a single f32 row that dequantizes INSIDE the matmul epilogue
(acc * scale after the int8 dot), so the bf16 weight never exists in
HBM — not at load (quantization happens on the host copy) and not at
step time (the kernel reads int8 pages + one f32 row per out tile).
Scales are stored per-COLUMN (values constant within a tile) so the
plane shards along the same mesh axis as its weight's out dimension
with no tile-divisibility coupling to the TP degree.

A quantized leaf is the dict ``{"qw": int8[in, out], "scale":
f32[out]}`` replacing the plain ``[in, out]`` array. Only the
matmul-heavy projections quantize (QUANT_LEAVES); embeddings, norms,
biases, lm_head, and the MoE expert stacks stay in the load dtype, so
``weight_dtype="bf16"`` leaves the pytree — and therefore traces and
the compile cache — byte-identical to the pre-quantization engine.

Kernel discipline per the solver invariant: ``quant_matmul`` (Pallas)
and ``quant_matmul_jnp`` (twin) share ``_tile_operands`` /
``_wq_tile_dot`` / ``_wq_finish`` verbatim and accumulate over
identically-shaped [bm, bk] x [bk, bn] tile dots in the same k order —
the twin iterates the tile grid with lax.map/scan rather than issuing
one whole-array dot precisely because XLA may re-associate a
differently-shaped contraction. ``quant_matmul_dense`` is the
tolerance-class dense route (CPU fallback and the GSPMD path, like
flash_attention.dequant_gather_block_kv): one whole dot_general whose
every op partitions cleanly under TP.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Param leaves that route through the fused kernel. 2-D projections
# only: the MoE expert stacks are [E, ...] gathers with tiny per-token
# activation, not weight-bandwidth-bound, and lm_head/embed stay full
# precision because logit quality is the whole product.
QUANT_LEAVES = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)

DEFAULT_TILE = 128


# --- quantization (host/load-time) -----------------------------------------


def quantize_weight(w, tile: int = DEFAULT_TILE):
    """Per-out-tile symmetric absmax int8: kv_blocks.quantize_blocks'
    math (scale = amax/127, zero guard to 1.0) with the group axis
    being ``tile`` consecutive out columns. Returns the quantized-leaf
    dict; ragged final tiles reduce over zero padding, which cannot
    raise an absmax."""
    if w.ndim != 2:
        raise ValueError(f"quantize_weight expects 2-D, got {w.shape}")
    K, N = w.shape
    wf = jnp.asarray(w, jnp.float32)
    nt = -(-N // tile)
    wp = jnp.pad(wf, ((0, 0), (0, nt * tile - N)))
    amax = jnp.max(jnp.abs(wp.reshape(K, nt, tile)), axis=(0, 2))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    scol = jnp.repeat(scale, tile)[:N]
    q = jnp.clip(jnp.round(wf / scol[None, :]), -127, 127).astype(jnp.int8)
    return {"qw": q, "scale": scol}


def dequantize_weight(d, dtype=jnp.float32):
    """Inverse of quantize_weight (kv_blocks.dequantize_blocks' cast
    order: int8 -> f32, scale in f32, cast last)."""
    return (
        d["qw"].astype(jnp.float32) * d["scale"].astype(jnp.float32)[None, :]
    ).astype(dtype)


def _is_quant_leaf(v) -> bool:
    return isinstance(v, dict) and "qw" in v and "scale" in v


def quantize_layer(layer: dict, tile: int = DEFAULT_TILE) -> dict:
    """New layer dict with every QUANT_LEAVES member quantized; norms,
    biases, and the moe subtree pass through untouched."""
    out = dict(layer)
    for name in QUANT_LEAVES:
        w = layer.get(name)
        if w is not None and not _is_quant_leaf(w):
            out[name] = quantize_weight(w, tile=tile)
    return out


def quantize_params(params: dict, tile: int = DEFAULT_TILE) -> dict:
    """Quantize the projection leaves of a full param pytree. Raises on
    an already-quantized tree — double quantization would silently
    re-derive scales from int8 codes (checkpoint.py restores rely on
    this guard)."""
    if params_weight_dtype(params) == "int8":
        raise ValueError(
            "params already weight-quantized (double-quantize guard)"
        )
    out = dict(params)
    out["layers"] = [quantize_layer(l, tile=tile) for l in params["layers"]]
    return out


def dequantize_params(params: dict, dtype=None) -> dict:
    """Plain-array pytree from a quantized one (checkpoint export path
    and the parity tests' exact-grid reference construction)."""
    if dtype is None:
        dtype = params["norm"].dtype
    out = dict(params)
    layers = []
    for layer in params["layers"]:
        nl = dict(layer)
        for name, v in layer.items():
            if _is_quant_leaf(v):
                nl[name] = dequantize_weight(v, dtype)
        layers.append(nl)
    out["layers"] = layers
    return out


def params_weight_dtype(params: dict) -> str:
    """The tree's weight_dtype axis value, inferred from representation
    (quant-dict leaves present or not) so engines/checkpoints never
    need a side channel."""
    for layer in params.get("layers", ()):
        for name in QUANT_LEAVES:
            if _is_quant_leaf(layer.get(name)):
                return "int8"
    return "bf16"


# --- fused dequant-matmul kernel + bit-identical twin ----------------------


def _wq_tile_dot(x_tile, qw_tile):
    """One [bm, bk] x [bk, bn] tile contraction with the int8 tile cast
    to the activation dtype (exact: |q| <= 127 is representable in
    bf16) and f32 accumulation. Shared verbatim by the kernel and the
    twin — the bit-identity contract runs through this function like
    flash_attention's _dequant_tile."""
    return jax.lax.dot_general(
        x_tile, qw_tile.astype(x_tile.dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _wq_finish(acc, scale_row, out_dtype):
    """Dequant epilogue: fold the per-column scale into the f32
    accumulator, cast once. Shared verbatim by kernel and twin."""
    return (acc * scale_row.astype(jnp.float32)).astype(out_dtype)


def _tile_operands(x, qw, scale, bm: int, bn: int, bk: int):
    """Zero-pad all three operands to whole tiles. Shared by the kernel
    wrapper and the twin so both walk the same padded grid; zero k rows
    contribute exact +0.0 to the f32 accumulation, so padding is
    bit-neutral on the un-sliced region."""
    M, K = x.shape
    N = qw.shape[1]
    mt, nt, kt = -(-M // bm), -(-N // bn), -(-K // bk)
    xp = jnp.pad(x, ((0, mt * bm - M), (0, kt * bk - K)))
    qp = jnp.pad(qw, ((0, kt * bk - K), (0, nt * bn - N)))
    sp = jnp.pad(scale, (0, nt * bn - N)).reshape(1, nt * bn)
    return xp, qp, sp, mt, nt, kt


def _quant_matmul_kernel(x_ref, qw_ref, s_ref, o_ref, acc_ref):
    """Grid (mt, nt, kt), k innermost: the out tile and its f32 scratch
    accumulator stay VMEM-resident across the whole k walk; the scale
    row is read once at the finish step."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] = acc_ref[:] + _wq_tile_dot(x_ref[:], qw_ref[:])

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _finish():
        o_ref[:] = _wq_finish(acc_ref[:], s_ref[0], o_ref.dtype)


def quant_matmul(
    x: jax.Array,  # [M, K] activations (f32 or bf16)
    qw: jax.Array,  # int8 [K, N]
    scale: jax.Array,  # f32 [N] per-column (constant within a tile)
    *,
    block_m: int = 128,
    block_n: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Fused dequant-matmul: int8 pages stream through VMEM, dequant
    happens on the f32 accumulator in the epilogue — N*K bf16 bytes
    never exist. Twin: quant_matmul_jnp (bit-identical — parity in
    tests/test_weight_quant.py)."""
    M, N = x.shape[0], qw.shape[1]
    xp, qp, sp, mt, nt, kt = _tile_operands(
        x, qw, scale, block_m, block_n, block_k
    )
    out = pl.pallas_call(
        _quant_matmul_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=(mt, nt, kt),
            in_specs=[
                pl.BlockSpec(
                    (block_m, block_k), lambda m, n, k: (m, k),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (block_k, block_n), lambda m, n, k: (k, n),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, block_n), lambda m, n, k: (0, n),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (block_m, block_n), lambda m, n, k: (m, n),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((mt * block_m, nt * block_n), x.dtype),
        interpret=interpret,
    )(xp, qp, sp)
    return out[:M, :N]


def quant_matmul_jnp(
    x: jax.Array,
    qw: jax.Array,
    scale: jax.Array,
    *,
    block_m: int = 128,
    block_n: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """The kernel's jnp twin: same padded grid (shared _tile_operands),
    same per-tile [bm, bk] x [bk, bn] dots via the shared _wq_tile_dot,
    same ascending-k f32 accumulation, same epilogue. Deliberately NOT
    one whole-array dot — XLA may re-associate a differently-shaped
    contraction, and the twin's job is to pin the kernel's arithmetic,
    not to be fast."""
    M, N = x.shape[0], qw.shape[1]
    xp, qp, sp, mt, nt, kt = _tile_operands(
        x, qw, scale, block_m, block_n, block_k
    )
    xt = xp.reshape(mt, block_m, kt, block_k).transpose(0, 2, 1, 3)
    qt = qp.reshape(kt, block_k, nt, block_n).transpose(0, 2, 1, 3)
    st = sp.reshape(nt, block_n)

    def _tile(idx):
        m, n = idx // nt, idx % nt

        def step(acc, k):
            return acc + _wq_tile_dot(xt[m, k], qt[k, n]), None

        acc, _ = jax.lax.scan(
            step,
            jnp.zeros((block_m, block_n), jnp.float32),
            jnp.arange(kt, dtype=jnp.int32),
        )
        return _wq_finish(acc, st[n], x.dtype)

    tiles = jax.lax.map(_tile, jnp.arange(mt * nt, dtype=jnp.int32))
    out = tiles.reshape(mt, nt, block_m, block_n).transpose(
        0, 2, 1, 3
    ).reshape(mt * block_m, nt * block_n)
    return out[:M, :N]


def quant_matmul_dense(x: jax.Array, qw: jax.Array, scale: jax.Array):
    """Dense fallback AND the GSPMD route (custom calls cannot be
    partitioned — flash_attention.dequant_gather_block_kv's
    constraint): one whole dot_general over the last axis, scale folded
    after. Tolerance-class vs the kernel/twin pair, exact in
    expectation; handles arbitrary leading batch dims."""
    acc = jax.lax.dot_general(
        x, qw.astype(x.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (acc * scale.astype(jnp.float32)).astype(x.dtype)


def quant_matmul_available() -> bool:
    """Kernel gate: real TPU only (ragged shapes are padded away, so
    there is no alignment door — CPU runs the dense route, interpret
    mode is for tests)."""
    return jax.default_backend() == "tpu"


def quant_matmul_auto(
    x: jax.Array, qw: jax.Array, scale: jax.Array, *, gspmd: bool = False
) -> jax.Array:
    """Route one projection matmul: Pallas on TPU (leading dims folded
    into M), dense otherwise and always under gspmd."""
    if (not gspmd) and quant_matmul_available():
        lead = x.shape[:-1]
        out = quant_matmul(x.reshape(-1, x.shape[-1]), qw, scale)
        return out.reshape(*lead, qw.shape[1])
    return quant_matmul_dense(x, qw, scale)


def wq_dot(x: jax.Array, w, *, gspmd: bool = False) -> jax.Array:
    """``x @ w`` for a param leaf that may be plain or quantized — the
    single call site model.decoder_layer threads every projection
    through, so bf16 engines trace the exact pre-PR graph (plain leaf
    -> plain matmul, no new ops)."""
    if _is_quant_leaf(w):
        return quant_matmul_auto(x, w["qw"], w["scale"], gspmd=gspmd)
    return x @ w


@functools.partial(jax.jit, static_argnames=("gspmd",), donate_argnums=(0,))
def quant_matmul_step(x, qw, scale, gspmd=False):
    """Standalone jitted entry for the fused kernel (bench phases and
    the analysis registries — jitlint/donatecheck collect decoration
    forms). Donates the activation: a projection consumes its input."""
    return quant_matmul_auto(x, qw, scale, gspmd=gspmd)
