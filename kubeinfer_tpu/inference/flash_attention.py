"""Pallas TPU flash attention for the prefill/training hot op.

The dense path (model.attention) materializes [B, n_kv, G, T, S] f32
scores through HBM; at long context that is the dominant memory term
(a 512-token chunk against a 128k cache is 0.5GB of scores per layer at
B=8, H=32). These kernels stream K/V tiles through VMEM with the online
softmax recurrence (running rowmax m, normalizer l, accumulator o — the
same algebra as ring_attention.py's block fold, here over the LOCAL S
axis instead of a device ring), so the f32 score/probability tensors
never touch HBM.

Two mask sources share one softmax body (``_softmax_fold``):

- ``flash_attention_ragged``: the causal+length mask
  ((s <= q_offset + t) & (s < row_len)) derived IN-KERNEL from two
  scalars via iotas — nothing [T, S]-sized exists anywhere, in HBM or
  out. This covers BOTH production mask shapes: the engine's chunked
  prefill (q_offset = chunk start, row_len = prompt length) and plain
  causal self-attention (q_offset = 0, row_len = S —
  ``causal_attention_auto``, the no-cache forward's path). r2 shipped
  the general kernel an int8 [B, T, S] mask (O(B·T·S) HBM traffic);
  r2's verdict item 8 relegates that to the arbitrary-mask fallback
  below.
- ``flash_attention``: a caller-supplied bool[B, T, S] mask ships to
  the kernel as int8 (head-independent — 4*n_kv*G times smaller than
  the scores it replaces). The ARBITRARY-mask fallback: correct for any
  mask, but pays the mask's HBM traffic — production paths use the
  in-kernel variants; this remains for exotic masks (blockwise-sparse
  experiments, bidirectional scoring).

A third variant serves the decode step: ``decode_attention`` (T == 1,
per-row live lengths as a scalar-prefetch operand) reads only each
row's live KV tiles — the BlockSpec index_map clamps past the length so
the dead tiles' DMAs are elided, not just their compute. Its twin
``decode_attention_jnp`` shares ``_fold_tile_math`` and is bit-identical
(parity in tests/test_flash_attention.py).

Layout: GQA folds the (T, G) axes into MXU rows — q becomes
[B*n_kv, T*G, D], each S tile is one [T_q*G, D] x [D, S_k] matmul plus
one [T_q*G, S_k] x [S_k, D] matmul, and the mask penalty (which depends
on T alone, not G) broadcasts across the G subrows in-register. The S
grid axis is innermost with the accumulators in VMEM scratch, so state
stays resident across the sweep (same accumulate-across-grid idiom as
the solver's accept kernel).

Fully-masked rows reproduce the dense path's uniform-softmax output
exactly (all scores -1e30 -> p == 1 everywhere -> o/l is the mean over
S), so parity holds even on padding rows.

Backward (r3 verdict item 6): ``flash_attention_causal_diff`` wraps the
ragged kernel in a custom_vjp with the recompute-based backward from the
public flash-attention literature — the forward additionally emits the
per-row logsumexp L = m + log(l), and the backward re-materializes each
[tile_t*G, tile_s] probability block in VMEM from (q, k, L) instead of
ever having stored it: dv += p^T dO, ds = p * (dO v^T − rowsum(dO*O)),
dq += ds k, dk += ds^T q. Two kernels mirror the forward's
accumulate-across-inner-grid idiom: dq sweeps S with a resident [TqG, D]
accumulator; dk/dv sweep T with resident [Sk, D] accumulators (the GQA
row fold makes the G-group reduction implicit in the ds^T q contraction).
``causal_attention_auto`` routes through the differentiable wrapper, so
training no longer pins the dense path (train.causal_lm_loss).

No reference counterpart: the reference delegates all attention to the
external vLLM process (SURVEY.md §2, vllm.go:93-112).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubeinfer_tpu.inference.model import attention as dense_attention

TILE_T = 256  # query positions per tile (rows = TILE_T * G)
TILE_S = 512  # key/value positions per tile


def _fold_tile_math(
    q,  # [TqG, D] folded (t, g) query rows
    k,  # [Sk, D]
    v,  # [Sk, D]
    pen,  # f32[Tq, Sk]: 0 = attend, -1e30 = masked
    m_prev,  # f32[TqG, 1]
    l_prev,  # f32[TqG, 1]
    acc_prev,  # f32[TqG, D]
    *,
    groups: int,
    scale: float,
):
    """The pure value-level online-softmax step: one (q-tile, s-tile)
    fold of the running (m, l, acc) state. Shared between the Pallas
    kernels (via _softmax_fold / _decode_attn_kernel) and the decode
    jnp twin — bit-identity between a kernel and its twin is only
    checkable if both run THIS function, not a re-derivation (the same
    contract as pallas_kernels.mega_rounds_jnp sharing the round math).

    Batched use: the decode twin vmaps this over the B*n_kv axis, so
    every operand here is one grid instance's tile."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [TqG, Sk]
    # Masking as an f32 additive penalty broadcast across the G
    # subrows. Mosaic cannot relayout i1 vectors ("unsupported shape
    # cast" on a bool [Tq, 1, Sk] broadcast), so rank changes happen
    # on f32 values; the add is exact (|s| << 1e23, so s + -1e30
    # rounds to -1e30).
    tq, sk = pen.shape
    s = (s.reshape(tq, groups, sk) + pen[:, None, :]).reshape(
        tq * groups, sk
    )

    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)  # [TqG, Sk] f32
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [TqG, D]
    acc_new = acc_prev * alpha + pv
    return m_new, l_new, acc_new


def _softmax_fold(
    q_ref,  # [1, TILE_T * G, D] folded (t, g) query rows
    k_ref,  # [1, TILE_S, D]
    v_ref,  # [1, TILE_S, D]
    pen,  # f32[TILE_T, TILE_S]: 0 = attend, -1e30 = masked
    o_ref,  # [1, TILE_T * G, D] out
    m_scr,  # f32[TILE_T * G, 1] scratch: running rowmax
    l_scr,  # f32[TILE_T * G, 1] scratch: running normalizer
    acc_scr,  # f32[TILE_T * G, D] scratch: running accumulator
    *,
    groups: int,
    scale: float,
    s_tiles: int,
    active=None,  # scalar bool: False = this tile provably contributes 0
):
    """One S-tile step of the online softmax, shared by both kernels —
    the recurrence, scratch lifecycle, and GQA penalty broadcast must
    never diverge between the mask-tensor and iota-mask variants.

    ``active=False`` skips the fold for a tile whose every slot is
    masked. BIT-identical by construction, not an approximation: with
    pen == -1e30 everywhere, s == -1e30 exactly (f32 absorbs the |qk|
    term), so m_new == m_prev, alpha == 1, and p == exp(-1e30 - m)
    underflows to exactly 0 — the skipped fold would add 0 to l and acc
    and rewrite m with itself. The one exception is a row that has seen
    NO unmasked tile yet (m == -1e30, making p == 1, not 0) — callers
    must keep such rows' tiles active (the ragged kernels run row_len==0
    rows dense, preserving their defined uniform-average output). This
    is the prefill MFU lever (r4 verdict item 2): the causal upper
    triangle is ~half of every prefill grid, and the fold's exp/max VPU
    sweep — not the MXU matmuls — is what those tiles burn."""
    ts = pl.program_id(2)  # innermost: S sweep with resident scratch

    @pl.when(ts == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _fold():
        m_new, l_new, acc_new = _fold_tile_math(
            q_ref[0], k_ref[0], v_ref[0], pen,
            m_scr[:], l_scr[:], acc_scr[:],
            groups=groups, scale=scale,
        )
        l_scr[:] = l_new
        acc_scr[:] = acc_new
        m_scr[:] = m_new

    if active is None:
        _fold()
    else:
        pl.when(active)(_fold)

    @pl.when(ts == s_tiles - 1)
    def _finish():
        # l == 0 cannot happen (even fully-masked rows accumulate
        # p == 1 per position); the guard keeps hypothetical S == 0
        # grids finite.
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(
            o_ref.dtype
        )


def _flash_kernel(
    mask_ref,  # [1, TILE_T, TILE_S] int8 (1 = attend); extras lead
    q_ref, k_ref, v_ref,
    o_ref, m_scr, l_scr, acc_scr,
    *, groups: int, scale: float, s_tiles: int,
):
    pen = (mask_ref[0].astype(jnp.float32) - 1.0) * 1e30
    _softmax_fold(
        q_ref, k_ref, v_ref, pen, o_ref, m_scr, l_scr, acc_scr,
        groups=groups, scale=scale, s_tiles=s_tiles,
    )


def _flash_ragged_kernel(
    c0_ref,  # SMEM i32[1]: global position of the first query row
    len_ref,  # SMEM i32[B]: per-row valid sequence lengths (whole vector:
    #           Mosaic rank-1 blocks must equal the array or tile to 128,
    #           so a (1,)-block per batch row only lowers at B == 1 —
    #           indexed in-kernel by program_id instead)
    q_ref, k_ref, v_ref,
    o_ref, m_scr, l_scr, acc_scr,
    *, groups: int, scale: float, s_tiles: int, tile_t: int, tile_s: int,
    n_kv: int,
):
    """The engine's prefill mask — attend cache slots <= own global
    position AND < the row's valid length — from iotas on two scalars
    instead of a shipped [B, T, S] int8 tensor."""
    row_len = len_ref[pl.program_id(0) // n_kv]
    tq = pl.program_id(1)
    ts = pl.program_id(2)
    q_pos = (
        c0_ref[0] + tq * tile_t
        + jax.lax.broadcasted_iota(jnp.int32, (tile_t, tile_s), 0)
    )
    s_pos = ts * tile_s + jax.lax.broadcasted_iota(
        jnp.int32, (tile_t, tile_s), 1
    )
    attend = (s_pos <= q_pos) & (s_pos < row_len)
    pen = jnp.where(attend, 0.0, -1e30)  # i1 never changes rank
    _softmax_fold(
        q_ref, k_ref, v_ref, pen, o_ref, m_scr, l_scr, acc_scr,
        groups=groups, scale=scale, s_tiles=s_tiles,
        active=_ragged_tile_active(
            c0_ref[0], row_len, tq, ts, tile_t, tile_s
        ),
    )


def _ragged_tile_active(c0, row_len, tq, ts, tile_t, tile_s):
    """Whether this (tq, ts) tile can contain any unmasked slot under
    the causal+length mask. Tile 0 of the S sweep always runs — it owns
    the scratch init, and keeping every tile of a row_len == 0 row
    active preserves that row's defined output (see _softmax_fold)."""
    s_start = ts * tile_s
    q_max = c0 + (tq + 1) * tile_t - 1
    return (
        (ts == 0)
        | (row_len == 0)
        | ((s_start <= q_max) & (s_start < row_len))
    )


def _run_flash(
    kern,
    extra_arrays: tuple,
    extra_specs: list,
    q: jax.Array,  # [B, T, n_heads, D]
    k: jax.Array,  # [B, S, n_kv, D]
    v: jax.Array,
    tile_t: int,
    tile_s: int,
    interpret: bool,
    name: str,
) -> jax.Array:
    """Shared host plumbing: GQA row fold, tile validation, pallas_call,
    and the inverse fold. ``extra_arrays``/``extra_specs`` prepend the
    kernel's mask source (int8 tensor or SMEM scalars)."""
    B, T, n_heads, D = q.shape
    S, n_kv = k.shape[1], k.shape[2]
    G = n_heads // n_kv
    tile_t = min(tile_t, T)
    tile_s = min(tile_s, S)
    if T % tile_t or S % tile_s:
        raise ValueError(
            f"{name} needs T divisible by {tile_t} and S by {tile_s}; "
            f"got T={T} S={S} (use attention_auto for fallback)"
        )
    t_tiles, s_tiles = T // tile_t, S // tile_s

    # fold (B, n_kv) into the grid axis and (T, G) into MXU rows
    qf = q.reshape(B, T, n_kv, G, D).transpose(0, 2, 1, 3, 4)
    qf = qf.reshape(B * n_kv, T * G, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)

    out = pl.pallas_call(
        functools.partial(
            kern, groups=G, scale=1.0 / float(D) ** 0.5, s_tiles=s_tiles
        ),
        grid=(B * n_kv, t_tiles, s_tiles),
        in_specs=extra_specs + [
            pl.BlockSpec(
                (1, tile_t * G, D), lambda bh, tq, ts: (bh, tq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, tile_s, D), lambda bh, tq, ts: (bh, ts, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, tile_s, D), lambda bh, tq, ts: (bh, ts, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, tile_t * G, D), lambda bh, tq, ts: (bh, tq, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((B * n_kv, T * G, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((tile_t * G, 1), jnp.float32),
            pltpu.VMEM((tile_t * G, 1), jnp.float32),
            pltpu.VMEM((tile_t * G, D), jnp.float32),
        ],
        interpret=interpret,
    )(*extra_arrays, qf, kf, vf)
    out = out.reshape(B, n_kv, T, G, D).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, T, n_heads, D)


def flash_attention(
    q: jax.Array,  # [B, T, n_heads, D]
    k: jax.Array,  # [B, S, n_kv, D]
    v: jax.Array,  # [B, S, n_kv, D]
    mask: jax.Array,  # bool[B, T, S] True = attend
    *,
    tile_t: int = TILE_T,
    tile_s: int = TILE_S,
    interpret: bool = False,
) -> jax.Array:
    """Exact attention, streamed; requires T % tile_t == S % tile_s == 0.

    Callers wanting automatic fallback for unaligned shapes use
    ``attention_auto``.
    """
    n_kv = k.shape[2]
    tt = min(tile_t, q.shape[1])
    ts_ = min(tile_s, k.shape[1])
    return _run_flash(
        _flash_kernel,
        (mask.astype(jnp.int8),),
        [
            pl.BlockSpec(
                (1, tt, ts_),
                lambda bh, tq, ts, n_kv=n_kv: (bh // n_kv, tq, ts),
                memory_space=pltpu.VMEM,
            ),
        ],
        q, k, v, tile_t, tile_s, interpret, "flash_attention",
    )


def flash_attention_ragged(
    q: jax.Array,  # [B, T, n_heads, D]
    k: jax.Array,  # [B, S, n_kv, D]
    v: jax.Array,  # [B, S, n_kv, D]
    q_offset: jax.Array,  # i32 scalar: global position of q[:, 0]
    row_lens: jax.Array,  # i32[B] valid sequence length per row
    *,
    tile_t: int = TILE_T,
    tile_s: int = TILE_S,
    interpret: bool = False,
) -> jax.Array:
    """flash_attention specialized to the chunked-prefill mask
    ``(s <= q_offset + t) & (s < row_lens[b])``, computed in-kernel from
    scalars — nothing [T, S]-sized exists anywhere, in HBM or out."""
    n_kv = k.shape[2]
    tt = min(tile_t, q.shape[1])
    ts_ = min(tile_s, k.shape[1])
    lens = jnp.asarray(row_lens, jnp.int32)
    kern = functools.partial(
        _flash_ragged_kernel, tile_t=tt, tile_s=ts_, n_kv=n_kv
    )
    return _run_flash(
        kern,
        (
            jnp.asarray(q_offset, jnp.int32).reshape(1),
            lens,
        ),
        [
            pl.BlockSpec(
                (1,), lambda bh, tq, ts: (0,), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(
                lens.shape, lambda bh, tq, ts: (0,),
                memory_space=pltpu.SMEM,
            ),
        ],
        q, k, v, tile_t, tile_s, interpret, "flash_attention_ragged",
    )


# --- batched decode attention (T == 1, per-row live lengths) ---------------


def _decode_attn_kernel(
    len_ref,  # scalar-prefetch i32[B]: per-row live lengths
    q_ref,  # [1, G, D] — the row's single query, groups as MXU rows
    k_ref,  # [1, tile_s, D]
    v_ref,  # [1, tile_s, D]
    o_ref,  # [1, G, D] out
    m_scr,  # f32[G, 1]
    l_scr,  # f32[G, 1]
    acc_scr,  # f32[G, D]
    *,
    groups: int,
    scale: float,
    s_tiles: int,
    tile_s: int,
    n_kv: int,
):
    """One decode step's attention for one (batch row, kv head): sweep
    the row's live S tiles with the shared fold. The grid is 2D
    (B*n_kv, s_tiles) — T == 1 makes the q-tile axis pointless — and
    the per-row length lives in the scalar-prefetch operand so the k/v
    BlockSpec index_map can clamp DMAs past the live length (see
    decode_attention). Tiles past the length are also compute-skipped;
    both are bit-identical no-ops (see _softmax_fold's active note:
    row_len == 0 rows keep every tile live to preserve their defined
    uniform-average output)."""
    row_len = len_ref[pl.program_id(0) // n_kv]
    ts = pl.program_id(1)  # innermost: S sweep with resident scratch

    @pl.when(ts == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when((ts == 0) | (row_len == 0) | (ts * tile_s < row_len))
    def _fold():
        s_pos = ts * tile_s + jax.lax.broadcasted_iota(
            jnp.int32, (1, tile_s), 1
        )
        pen = jnp.where(s_pos < row_len, 0.0, -1e30)
        m_new, l_new, acc_new = _fold_tile_math(
            q_ref[0], k_ref[0], v_ref[0], pen,
            m_scr[:], l_scr[:], acc_scr[:],
            groups=groups, scale=scale,
        )
        l_scr[:] = l_new
        acc_scr[:] = acc_new
        m_scr[:] = m_new

    @pl.when(ts == s_tiles - 1)
    def _finish():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(
            o_ref.dtype
        )


def decode_attention(
    q: jax.Array,  # [B, 1, n_heads, D] — one new token per row
    k: jax.Array,  # [B, S, n_kv, D] padded KV cache
    v: jax.Array,  # [B, S, n_kv, D]
    lengths: jax.Array,  # i32[B]: live entries per row (offset + 1)
    *,
    tile_s: int = TILE_S,
    interpret: bool = False,
) -> jax.Array:
    """Batched ragged decode attention: each row attends to its own
    first ``lengths[b]`` cache slots. HBM traffic is the point — the
    lengths ride in as a scalar-prefetch operand, so the k/v index_map
    below clamps the block index past each row's live length and
    Pallas elides the repeated-block DMAs: a row that is 1k tokens
    into a 128k cache reads ~1k positions, not 128k. The dense path
    this replaces reads the full padded cache every step for every
    row. Twin: decode_attention_jnp (bit-identical, parity-tested)."""
    B, T, n_heads, D = q.shape
    if T != 1:
        raise ValueError(f"decode_attention is T == 1 only; got T={T}")
    S, n_kv = k.shape[1], k.shape[2]
    G = n_heads // n_kv
    tile_s = min(tile_s, S)
    if S % tile_s:
        raise ValueError(
            f"decode_attention needs S divisible by {tile_s}; got S={S} "
            "(use decode_attention_auto for fallback)"
        )
    s_tiles = S // tile_s

    qf = q.reshape(B, n_kv, G, D).reshape(B * n_kv, G, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)
    lens = jnp.asarray(lengths, jnp.int32)

    def _kv_map(bh, ts, lens_ref, n_kv=n_kv, tile_s=tile_s):
        # Clamp the S block index to the row's last live tile: Pallas
        # skips the DMA when consecutive steps name the same block, so
        # dead tiles cost nothing. row_len == 0 rows must NOT clamp —
        # their (defined) output is the uniform average over the real
        # cache contents, so they read every true tile.
        rl = lens_ref[bh // n_kv]
        live_last = jnp.maximum(rl - 1, 0) // tile_s
        return (bh, jnp.where(rl == 0, ts, jnp.minimum(ts, live_last)), 0)

    q_spec = pl.BlockSpec(
        (1, G, D), lambda bh, ts, lens_ref: (bh, 0, 0),
        memory_space=pltpu.VMEM,
    )
    kv_spec = pl.BlockSpec((1, tile_s, D), _kv_map, memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(
            _decode_attn_kernel, groups=G, scale=1.0 / float(D) ** 0.5,
            s_tiles=s_tiles, tile_s=tile_s, n_kv=n_kv,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B * n_kv, s_tiles),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=q_spec,
            scratch_shapes=[
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, 1), jnp.float32),
                pltpu.VMEM((G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * n_kv, G, D), q.dtype),
        interpret=interpret,
    )(lens, qf, kf, vf)
    return out.reshape(B, 1, n_heads, D)


def decode_attention_jnp(
    q: jax.Array,  # [B, 1, n_heads, D]
    k: jax.Array,  # [B, S, n_kv, D]
    v: jax.Array,
    lengths: jax.Array,  # i32[B]
    *,
    tile_s: int = TILE_S,
) -> jax.Array:
    """The decode kernel's jnp twin: the SAME _fold_tile_math, iterated
    over the B*n_kv grid axis with lax.map and over the S tiles with
    lax.scan, with the same penalty construction. Sequential per-row
    execution (not vmap) is deliberate: it keeps every dot_general the
    exact per-instance shape the interpreted kernel runs, so XLA:CPU
    picks the same lowering and kernel-vs-twin parity is exact
    (np.array_equal), per the repo invariant — a vmapped batched dot
    accumulates differently at G == 1. The twin runs every tile
    densely; the kernel's skipped tiles contribute exactly 0 (p
    underflows against a finite running max), so skipping never shows
    up in the bits."""
    B, T, n_heads, D = q.shape
    if T != 1:
        raise ValueError(f"decode_attention_jnp is T == 1 only; got T={T}")
    S, n_kv = k.shape[1], k.shape[2]
    G = n_heads // n_kv
    tile_s = min(tile_s, S)
    if S % tile_s:
        raise ValueError(f"S={S} must divide by tile_s={tile_s}")
    s_tiles = S // tile_s
    BH = B * n_kv
    scale = 1.0 / float(D) ** 0.5

    qf = q.reshape(B, n_kv, G, D).reshape(BH, G, D)
    kf = k.transpose(0, 2, 1, 3).reshape(BH, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(BH, S, D)
    row_len = jnp.repeat(jnp.asarray(lengths, jnp.int32), n_kv)  # [BH]

    def _row(args):
        qr, kr, vr, rl = args  # [G, D], [S, D], [S, D], i32

        def step(carry, ts):
            m, l, acc = carry
            k_t = jax.lax.dynamic_slice_in_dim(kr, ts * tile_s, tile_s, 0)
            v_t = jax.lax.dynamic_slice_in_dim(vr, ts * tile_s, tile_s, 0)
            s_pos = ts * tile_s + jax.lax.broadcasted_iota(
                jnp.int32, (1, tile_s), 1
            )
            pen = jnp.where(s_pos < rl, 0.0, -1e30)
            return _fold_tile_math(
                qr, k_t, v_t, pen, m, l, acc, groups=G, scale=scale
            ), None

        init = (
            jnp.full((G, 1), -1e30, jnp.float32),
            jnp.zeros((G, 1), jnp.float32),
            jnp.zeros((G, D), jnp.float32),
        )
        (m, l, acc), _ = jax.lax.scan(
            step, init, jnp.arange(s_tiles, dtype=jnp.int32)
        )
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    out = jax.lax.map(_row, (qf, kf, vf, row_len))  # [BH, G, D]
    return out.reshape(B, 1, n_heads, D)


def decode_flash_available(S: int, D: int) -> bool:
    """Shapes the decode kernel handles on the current default backend
    — same conservative contract as flash_available (a wrong True is a
    trace-time Mosaic error), minus the T constraints (T is always 1
    here, folded into the G rows)."""
    return (
        jax.default_backend() == "tpu"
        and S % min(TILE_S, S) == 0
        and S % 128 == 0
        and S >= 128
        and D % 64 == 0
    )


def decode_attention_auto(q, k, v, lengths, mask, gspmd=False):
    """Decode-step attention router: the length-clamped Pallas kernel
    when shapes/backend allow, dense jnp over ``mask`` otherwise. The
    flash branch never reads ``mask`` — XLA dead-code-eliminates its
    construction (the chunked_prefill contract). ``lengths`` and
    ``mask`` must describe the same live set (mask[b] true exactly on
    slots < lengths[b]) or the two branches diverge.

    ``gspmd=True`` pins the dense branch: a caller tracing under a
    sharded jit needs every op partitionable, and a Pallas kernel is a
    custom call GSPMD cannot split over heads — it would replicate (or
    fail to lower), same constraint forward_tensor_parallel documents
    for the prefill kernel."""
    if (not gspmd and q.shape[1] == 1
            and decode_flash_available(k.shape[1], q.shape[3])):
        return decode_attention(q, k, v, lengths)
    return dense_attention(q, k, v, mask)


# --- block-table (paged) decode attention ----------------------------------
#
# The serving engine's KV lives in a shared pool [num_blocks, block_size,
# n_kv, D]; each batch row owns an i32[max_blocks] table naming its blocks
# in sequence order. The decode kernel below is _decode_attn_kernel with
# one change: the k/v index_map resolves the S-tile index through the
# scalar-prefetched table, so a tile IS a pool block and rows sharing a
# prefix DMA the same physical blocks. No reference counterpart — the
# reference hands paging to the vLLM subprocess (vllm.go:93-112); vLLM's
# PagedAttention (Kwon et al. 2023) is the design source.
#
# Table contract: EVERY entry of every row — including entries past the
# row's live blocks — must be a valid pool index (the host pads with the
# reserved null block 0). Dead entries are never folded (same exact-zero
# skip as the linear kernel) but the index_map still names them when
# row_len == 0, and the twin gathers them unconditionally.


def _decode_blocks_kernel(
    tbl_ref,  # scalar-prefetch i32[B, max_blocks]: per-row block tables
    len_ref,  # scalar-prefetch i32[B]: per-row live lengths
    q_ref,  # [1, T*G, D] — the row's T queries, (t, group) as MXU rows
    k_ref,  # [1, 1, block_size, D] — one pool block for one kv head
    v_ref,  # [1, 1, block_size, D]
    o_ref,  # [1, T*G, D] out
    m_scr,  # f32[T*G, 1]
    l_scr,  # f32[T*G, 1]
    acc_scr,  # f32[T*G, D]
    *,
    groups: int,
    scale: float,
    n_blocks: int,
    block_size: int,
    n_kv: int,
    n_q: int,
):
    """_decode_attn_kernel over a paged cache: the grid's S axis walks
    the row's block table (resolved in the index_map — tbl_ref is unused
    here) and the penalty is derived from the LOGICAL position
    ts * block_size + i, so the fold math is position-for-position the
    linear kernel's. Same bit-identical skip/clamp story: a tile past
    the live length folds exactly 0, rows shorter than the window stay
    dense over whatever their (null-padded) table names.

    ``n_q`` > 1 is the speculative verify window: query t of a row sits
    at logical position row_len - n_q + t (the window's tokens are the
    cache's LAST n_q positions, scattered by the caller before the
    read), so the causal mask within the window is the only new math —
    pen row t admits s_pos <= row_len - n_q + t, which at n_q == 1
    reduces exactly to the decode rule s_pos < row_len. The live tile
    set is unchanged (the last query attends precisely s_pos < row_len),
    so the skip predicate needs no T term — EXCEPT rows with
    row_len < n_q, whose leading queries are fully masked: their
    uniform-over-junk output depends on every tile the twin folds, so
    the dense fallback generalizes from row_len == 0 to row_len < n_q
    (identical at n_q == 1; the engine never emits such rows, since a
    verify dispatch sets lengths = offset + T, but the twin contract
    must hold on the whole operand domain)."""
    del tbl_ref  # consumed by the BlockSpec index_map, not the body
    row_len = len_ref[pl.program_id(0) // n_kv]
    ts = pl.program_id(1)  # innermost: table walk with resident scratch

    @pl.when(ts == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when((ts == 0) | (row_len < n_q) | (ts * block_size < row_len))
    def _fold():
        s_pos = ts * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (n_q, block_size), 1
        )
        q_pos = row_len - n_q + jax.lax.broadcasted_iota(
            jnp.int32, (n_q, block_size), 0
        )
        pen = jnp.where(s_pos <= q_pos, 0.0, -1e30)
        m_new, l_new, acc_new = _fold_tile_math(
            q_ref[0], k_ref[0, 0], v_ref[0, 0], pen,
            m_scr[:], l_scr[:], acc_scr[:],
            groups=groups, scale=scale,
        )
        l_scr[:] = l_new
        acc_scr[:] = acc_new
        m_scr[:] = m_new

    @pl.when(ts == n_blocks - 1)
    def _finish():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(
            o_ref.dtype
        )


def decode_attention_blocks(
    q: jax.Array,  # [B, T, n_heads, D] — the row's last T tokens
    k_pool: jax.Array,  # [num_blocks, block_size, n_kv, D] shared pool
    v_pool: jax.Array,  # [num_blocks, block_size, n_kv, D]
    block_tables: jax.Array,  # i32[B, max_blocks]: pool indices, seq order
    lengths: jax.Array,  # i32[B]: live entries per row (offset + T)
    *,
    interpret: bool = False,
) -> jax.Array:
    """Paged decode attention: row b's logical cache position p lives in
    pool block block_tables[b, p // block_size] at slot p % block_size.
    Both scalar operands prefetch; the k/v index_map clamps the table
    walk past each row's last live block (same DMA-elision contract as
    decode_attention) and then indirects through the table, so shared
    prefix blocks are fetched once per consecutive reuse rather than
    duplicated per row. T > 1 is the speculative verify window: query t
    attends cache positions <= lengths[b] - T + t (the window occupies
    the row's last T live positions, already scattered into the pool by
    the caller), folded causally inside the kernel's penalty — the T
    axis rides the MXU row dim next to the GQA groups, so the tile walk
    and DMA schedule are the T == 1 kernel's unchanged. Twin:
    decode_attention_blocks_jnp (bit-identical, parity-tested in
    tests/test_flash_attention.py)."""
    B, T, n_heads, D = q.shape
    num_blocks, block_size, n_kv = k_pool.shape[:3]
    max_blocks = block_tables.shape[1]
    G = n_heads // n_kv

    # (t, group) flatten with t OUTER: _fold_tile_math reshapes rows as
    # (tq, groups, sk) + pen[:, None, :], so pen row t must cover the
    # contiguous run of G MXU rows belonging to query t.
    qf = q.reshape(B, T, n_kv, G, D).transpose(0, 2, 1, 3, 4).reshape(
        B * n_kv, T * G, D
    )
    # [num_blocks, n_kv, block_size, D]: one (block, head) pair per tile
    kp = k_pool.transpose(0, 2, 1, 3)
    vp = v_pool.transpose(0, 2, 1, 3)
    tbl = jnp.asarray(block_tables, jnp.int32)
    lens = jnp.asarray(lengths, jnp.int32)

    def _kv_map(bh, ts, tbl_ref, lens_ref, n_kv=n_kv, bs=block_size,
                nq=T):
        # Same clamp as decode_attention's _kv_map, then the table
        # lookup: dead steps re-name the row's last live block so
        # Pallas elides their DMAs. Rows shorter than the window
        # (row_len < nq, the kernel's dense-fallback predicate) walk
        # their true (null-padded) table — their defined output is the
        # uniform average over what the table names, mirroring the
        # twin, which a clamp would silently re-point at live data.
        b = bh // n_kv
        rl = lens_ref[b]
        live_last = jnp.maximum(rl - 1, 0) // bs
        step = jnp.where(rl < nq, ts, jnp.minimum(ts, live_last))
        return (tbl_ref[b, step], bh % n_kv, 0, 0)

    q_spec = pl.BlockSpec(
        (1, T * G, D), lambda bh, ts, tbl_ref, lens_ref: (bh, 0, 0),
        memory_space=pltpu.VMEM,
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_size, D), _kv_map, memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_blocks_kernel, groups=G, scale=1.0 / float(D) ** 0.5,
            n_blocks=max_blocks, block_size=block_size, n_kv=n_kv,
            n_q=T,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * n_kv, max_blocks),
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=q_spec,
            scratch_shapes=[
                pltpu.VMEM((T * G, 1), jnp.float32),
                pltpu.VMEM((T * G, 1), jnp.float32),
                pltpu.VMEM((T * G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * n_kv, T * G, D), q.dtype),
        interpret=interpret,
    )(tbl, lens, qf, kp, vp)
    return out.reshape(B, n_kv, T, G, D).transpose(0, 2, 1, 3, 4).reshape(
        B, T, n_heads, D
    )


def decode_attention_blocks_jnp(
    q: jax.Array,  # [B, T, n_heads, D]
    k_pool: jax.Array,  # [num_blocks, block_size, n_kv, D]
    v_pool: jax.Array,
    block_tables: jax.Array,  # i32[B, max_blocks]
    lengths: jax.Array,  # i32[B]
) -> jax.Array:
    """The block kernel's jnp twin: the SAME _fold_tile_math walked
    per-row with lax.map and per-block with lax.scan, gathering each
    tile through the row's table exactly as the kernel's index_map does
    (minus the clamp — dead tiles fold exactly 0 either way, see
    decode_attention_jnp's note). T > 1 mirrors the kernel's in-window
    causal penalty (query t at logical position rl - T + t). Because a
    gathered block holds the same values as the linear cache's
    corresponding tile, this twin is also bitwise equal to
    decode_attention_jnp(tile_s=block_size) on the gathered cache —
    parity-tested both ways."""
    B, T, n_heads, D = q.shape
    block_size, n_kv = k_pool.shape[1], k_pool.shape[2]
    max_blocks = block_tables.shape[1]
    G = n_heads // n_kv
    BH = B * n_kv
    scale = 1.0 / float(D) ** 0.5

    # Same (t, group) row order as the kernel's qf flatten.
    qf = q.reshape(B, T, n_kv, G, D).transpose(0, 2, 1, 3, 4).reshape(
        BH, T * G, D
    )
    kp = k_pool.transpose(0, 2, 1, 3)  # [num_blocks, n_kv, bs, D]
    vp = v_pool.transpose(0, 2, 1, 3)
    tbl = jnp.asarray(block_tables, jnp.int32)
    row_tbl = jnp.repeat(tbl, n_kv, axis=0)  # [BH, max_blocks]
    row_head = jnp.tile(jnp.arange(n_kv, dtype=jnp.int32), B)  # [BH]
    row_len = jnp.repeat(jnp.asarray(lengths, jnp.int32), n_kv)  # [BH]

    def _row(args):
        qr, trow, h, rl = args  # [T*G, D], i32[max_blocks], i32, i32

        def step(carry, ts):
            m, l, acc = carry
            k_t = kp[trow[ts], h]  # [bs, D] — the kernel's tile, gathered
            v_t = vp[trow[ts], h]
            s_pos = ts * block_size + jax.lax.broadcasted_iota(
                jnp.int32, (T, block_size), 1
            )
            q_pos = rl - T + jax.lax.broadcasted_iota(
                jnp.int32, (T, block_size), 0
            )
            pen = jnp.where(s_pos <= q_pos, 0.0, -1e30)
            return _fold_tile_math(
                qr, k_t, v_t, pen, m, l, acc, groups=G, scale=scale
            ), None

        init = (
            jnp.full((T * G, 1), -1e30, jnp.float32),
            jnp.zeros((T * G, 1), jnp.float32),
            jnp.zeros((T * G, D), jnp.float32),
        )
        (m, l, acc), _ = jax.lax.scan(
            step, init, jnp.arange(max_blocks, dtype=jnp.int32)
        )
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    out = jax.lax.map(_row, (qf, row_tbl, row_head, row_len))
    return out.reshape(B, n_kv, T, G, D).transpose(0, 2, 1, 3, 4).reshape(
        B, T, n_heads, D
    )


def gather_block_kv(pool: jax.Array, block_tables: jax.Array) -> jax.Array:
    """[num_blocks, bs, n_kv, D] pool -> [B, max_blocks * bs, n_kv, D]
    per-row linear view through the tables — the dense-fallback (and
    warm-prefill) materialization of what the block kernel reads
    in-place. One XLA gather; rows sharing blocks duplicate them here,
    which is exactly the copy the paged kernel exists to avoid."""
    nb, bs, n_kv, D = pool.shape
    B, M = block_tables.shape
    return pool[block_tables].reshape(B, M * bs, n_kv, D)


def decode_blocks_available(block_size: int, D: int) -> bool:
    """Shapes the block kernel handles on the current default backend —
    decode_flash_available's contract with S replaced by the pool's
    block_size (each tile is one block, so the block itself must be
    lane-aligned). Small-block test configs route to the gather+dense
    fallback."""
    return (
        jax.default_backend() == "tpu"
        and block_size % 128 == 0
        and block_size >= 128
        and D % 64 == 0
    )


def decode_attention_blocks_auto(q, k_pool, v_pool, block_tables, lengths,
                                 mask, gspmd=False):
    """Paged decode-step router: the block-table Pallas kernel when
    shapes/backend allow, gather-through-the-table + dense jnp over
    ``mask`` otherwise. The flash branch never reads ``mask`` (XLA
    dead-code-eliminates its construction); ``lengths`` and ``mask``
    must describe the same live set, per decode_attention_auto.

    ``gspmd=True`` pins the gather+dense branch (the sharded engine's
    route): the table gather indexes the pool's REPLICATED num_blocks
    axis, so with the pool sharded along n_kv each device gathers its
    own heads' slice of the named blocks through the same host i32
    tables, and the dense einsum partitions over heads — whereas the
    block kernel is a custom call GSPMD cannot split (see
    decode_attention_auto).

    Any T >= 1 routes to the kernel: T > 1 is the speculative verify
    window, whose in-window causal rule the kernel derives from
    ``lengths`` alone — ``mask`` must equal that rule
    (mask[b, t, s] = s <= lengths[b] - T + t) for the branches to
    agree."""
    if (not gspmd) and decode_blocks_available(
        k_pool.shape[1], q.shape[3]
    ):
        return decode_attention_blocks(
            q, k_pool, v_pool, block_tables, lengths
        )
    return dense_attention(
        q,
        gather_block_kv(k_pool, block_tables),
        gather_block_kv(v_pool, block_tables),
        mask,
    )


# --- int8 (quantized pool) block-table decode attention --------------------
#
# kv_dtype="int8" splits each pool side into three tensors: int8 pages
# [num_blocks, bs, n_kv, D], f32 scales [num_blocks, n_kv] (symmetric
# per-block-per-head, kv_blocks.quantize_blocks), and a per-slot bf16
# TAIL [n_slots, 2, bs, n_kv, D] holding the row's current partial
# block plus the one a verify window can spill into (n_emit <= k+1 <
# block_size bounds a window to ONE boundary crossing). Dequantization
# happens HERE, next to the table gather — committed blocks never
# round-trip to bf16 in HBM — while tiles at or past the row's tail
# base (lengths - T) // bs read the bf16 tail verbatim, so the partial
# block is bit-exact until the stepper commits it
# (stepper._commit_full_tails). Scales ride the scalar-prefetch SMEM
# path bitcast to i32 (SMEM is integer-typed; one f32 per (bh, ts) grid
# step), the same trick as the guide's quantized-matmul example.


def _dequant_tile(kq, scale_bits, tail, use_tail, out_dtype):
    """One tile's effective K (or V): dequantized int8 page, or the
    bf16 tail verbatim when ``use_tail``. Shared verbatim by the q8
    kernel and its jnp twin — the bit-identity contract runs through
    this function exactly as the fold runs through _fold_tile_math."""
    scale = jax.lax.bitcast_convert_type(scale_bits, jnp.float32)
    deq = kq.astype(jnp.float32) * scale
    return jnp.where(use_tail, tail.astype(jnp.float32), deq).astype(
        out_dtype
    )


def _decode_blocks_q8_kernel(
    tbl_ref,  # scalar-prefetch i32[B, max_blocks]
    len_ref,  # scalar-prefetch i32[B]
    tb_ref,  # scalar-prefetch i32[B]: first tail-resident block per row
    ks_ref,  # scalar-prefetch i32[B*n_kv, max_blocks]: f32 K scales, bitcast
    vs_ref,  # scalar-prefetch i32[B*n_kv, max_blocks]
    q_ref,  # [1, T*G, D]
    k_ref,  # [1, 1, block_size, D] int8 pool tile
    v_ref,  # [1, 1, block_size, D] int8
    kt_ref,  # [1, 1, 1, block_size, D] bf16 tail tile
    vt_ref,  # [1, 1, 1, block_size, D]
    o_ref,  # [1, T*G, D] out
    m_scr,
    l_scr,
    acc_scr,
    *,
    groups: int,
    scale: float,
    n_blocks: int,
    block_size: int,
    n_kv: int,
    n_q: int,
):
    """_decode_blocks_kernel with the dequant-or-tail select spliced in
    front of the fold; init/skip/penalty/finish are carried over
    unchanged (the quantized pool changes tile VALUES, never the walk).
    Dead tiles still fold exactly 0 whatever junk they dequantize to —
    int8 * finite scale is always finite — so the clamp-elision story
    survives quantization untouched."""
    del tbl_ref  # consumed by the BlockSpec index_maps, not the body
    bh = pl.program_id(0)
    row_len = len_ref[bh // n_kv]
    ts = pl.program_id(1)

    @pl.when(ts == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when((ts == 0) | (row_len < n_q) | (ts * block_size < row_len))
    def _fold():
        use_tail = ts >= tb_ref[bh // n_kv]
        k_eff = _dequant_tile(
            k_ref[0, 0], ks_ref[bh, ts], kt_ref[0, 0, 0], use_tail,
            o_ref.dtype,
        )
        v_eff = _dequant_tile(
            v_ref[0, 0], vs_ref[bh, ts], vt_ref[0, 0, 0], use_tail,
            o_ref.dtype,
        )
        s_pos = ts * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (n_q, block_size), 1
        )
        q_pos = row_len - n_q + jax.lax.broadcasted_iota(
            jnp.int32, (n_q, block_size), 0
        )
        pen = jnp.where(s_pos <= q_pos, 0.0, -1e30)
        m_new, l_new, acc_new = _fold_tile_math(
            q_ref[0], k_eff, v_eff, pen,
            m_scr[:], l_scr[:], acc_scr[:],
            groups=groups, scale=scale,
        )
        l_scr[:] = l_new
        acc_scr[:] = acc_new
        m_scr[:] = m_new

    @pl.when(ts == n_blocks - 1)
    def _finish():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(
            o_ref.dtype
        )


def decode_attention_blocks_q8(
    q: jax.Array,  # [B, T, n_heads, D]
    k_pool: jax.Array,  # int8[num_blocks, block_size, n_kv, D]
    v_pool: jax.Array,  # int8
    k_scales: jax.Array,  # f32[num_blocks, n_kv]
    v_scales: jax.Array,  # f32[num_blocks, n_kv]
    k_tail: jax.Array,  # [B, 2, block_size, n_kv, D] bf16 partial blocks
    v_tail: jax.Array,
    block_tables: jax.Array,  # i32[B, max_blocks]
    lengths: jax.Array,  # i32[B] live entries per row (offset + T)
    *,
    interpret: bool = False,
) -> jax.Array:
    """Paged decode attention over the quantized pool. Tile walk, DMA
    clamp, and penalty are decode_attention_blocks'; the new operands
    are the two scale rows (gathered through the table at trace time —
    ONE f32 per folded tile — and prefetched to SMEM as i32 bits) and
    the per-row tails, whose BlockSpec resolves tile ts to tail slot
    clip(ts - tail_base, 0, 1). tail_base is derived from ``lengths``
    (the window START block (lengths - T) // bs), not passed, so the
    kernel and every caller agree on it by construction. Twin:
    decode_attention_blocks_q8_jnp (bit-identical — parity-tested in
    tests/test_kv_quant.py)."""
    B, T, n_heads, D = q.shape
    num_blocks, block_size, n_kv = k_pool.shape[:3]
    max_blocks = block_tables.shape[1]
    G = n_heads // n_kv

    qf = q.reshape(B, T, n_kv, G, D).transpose(0, 2, 1, 3, 4).reshape(
        B * n_kv, T * G, D
    )
    kp = k_pool.transpose(0, 2, 1, 3)  # int8 [num_blocks, n_kv, bs, D]
    vp = v_pool.transpose(0, 2, 1, 3)
    kt = k_tail.transpose(0, 1, 3, 2, 4)  # [B, 2, n_kv, bs, D]
    vt = v_tail.transpose(0, 1, 3, 2, 4)
    tbl = jnp.asarray(block_tables, jnp.int32)
    lens = jnp.asarray(lengths, jnp.int32)
    tb = jnp.maximum(lens - T, 0) // block_size  # i32[B]
    # [B, max_blocks, n_kv] gather -> one scale per (row-head, tile),
    # bitcast because scalar-prefetch SMEM is integer-typed
    ksb = jax.lax.bitcast_convert_type(
        k_scales[tbl].transpose(0, 2, 1).reshape(B * n_kv, max_blocks),
        jnp.int32,
    )
    vsb = jax.lax.bitcast_convert_type(
        v_scales[tbl].transpose(0, 2, 1).reshape(B * n_kv, max_blocks),
        jnp.int32,
    )

    def _kv_map(bh, ts, tbl_ref, lens_ref, tb_ref, ks_ref, vs_ref,
                n_kv=n_kv, bs=block_size, nq=T):
        # decode_attention_blocks' clamp, verbatim (the scale gather
        # above uses the UNclamped table — dead tiles never fold, so
        # the pair only has to agree on folded tiles, where the clamp
        # is the identity)
        b = bh // n_kv
        rl = lens_ref[b]
        live_last = jnp.maximum(rl - 1, 0) // bs
        step = jnp.where(rl < nq, ts, jnp.minimum(ts, live_last))
        return (tbl_ref[b, step], bh % n_kv, 0, 0)

    def _tail_map(bh, ts, tbl_ref, lens_ref, tb_ref, ks_ref, vs_ref,
                  n_kv=n_kv):
        b = bh // n_kv
        return (b, jnp.clip(ts - tb_ref[b], 0, 1), bh % n_kv, 0, 0)

    q_spec = pl.BlockSpec(
        (1, T * G, D),
        lambda bh, ts, tbl_ref, lens_ref, tb_ref, ks_ref, vs_ref: (
            bh, 0, 0
        ),
        memory_space=pltpu.VMEM,
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_size, D), _kv_map, memory_space=pltpu.VMEM
    )
    tail_spec = pl.BlockSpec(
        (1, 1, 1, block_size, D), _tail_map, memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_blocks_q8_kernel, groups=G,
            scale=1.0 / float(D) ** 0.5,
            n_blocks=max_blocks, block_size=block_size, n_kv=n_kv,
            n_q=T,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(B * n_kv, max_blocks),
            in_specs=[q_spec, kv_spec, kv_spec, tail_spec, tail_spec],
            out_specs=q_spec,
            scratch_shapes=[
                pltpu.VMEM((T * G, 1), jnp.float32),
                pltpu.VMEM((T * G, 1), jnp.float32),
                pltpu.VMEM((T * G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * n_kv, T * G, D), q.dtype),
        interpret=interpret,
    )(tbl, lens, tb, ksb, vsb, qf, kp, vp, kt, vt)
    return out.reshape(B, n_kv, T, G, D).transpose(0, 2, 1, 3, 4).reshape(
        B, T, n_heads, D
    )


def decode_attention_blocks_q8_jnp(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    k_scales: jax.Array,
    v_scales: jax.Array,
    k_tail: jax.Array,
    v_tail: jax.Array,
    block_tables: jax.Array,
    lengths: jax.Array,
) -> jax.Array:
    """The q8 kernel's jnp twin: decode_attention_blocks_jnp's walk
    with _dequant_tile spliced in front of the fold, mirroring the
    kernel op for op (same bitcast round-trip, same clip-to-tail-slot,
    same cast order). Gathers the UNclamped table like the bf16 twin —
    dead tiles fold exactly 0 on both sides whatever they dequantize
    to."""
    B, T, n_heads, D = q.shape
    block_size, n_kv = k_pool.shape[1], k_pool.shape[2]
    max_blocks = block_tables.shape[1]
    G = n_heads // n_kv
    BH = B * n_kv
    scale = 1.0 / float(D) ** 0.5

    qf = q.reshape(B, T, n_kv, G, D).transpose(0, 2, 1, 3, 4).reshape(
        BH, T * G, D
    )
    kp = k_pool.transpose(0, 2, 1, 3)
    vp = v_pool.transpose(0, 2, 1, 3)
    kt = k_tail.transpose(0, 1, 3, 2, 4)  # [B, 2, n_kv, bs, D]
    vt = v_tail.transpose(0, 1, 3, 2, 4)
    tbl = jnp.asarray(block_tables, jnp.int32)
    lens = jnp.asarray(lengths, jnp.int32)
    tb = jnp.maximum(lens - T, 0) // block_size
    ksb = jax.lax.bitcast_convert_type(
        k_scales[tbl].transpose(0, 2, 1).reshape(BH, max_blocks),
        jnp.int32,
    )
    vsb = jax.lax.bitcast_convert_type(
        v_scales[tbl].transpose(0, 2, 1).reshape(BH, max_blocks),
        jnp.int32,
    )
    row_tbl = jnp.repeat(tbl, n_kv, axis=0)
    row_head = jnp.tile(jnp.arange(n_kv, dtype=jnp.int32), B)
    row_len = jnp.repeat(lens, n_kv)
    row_tb = jnp.repeat(tb, n_kv)
    row_b = jnp.repeat(jnp.arange(B, dtype=jnp.int32), n_kv)

    def _row(args):
        qr, trow, h, rl, tbase, b, ks_row, vs_row = args

        def step(carry, ts):
            m, l, acc = carry
            use_tail = ts >= tbase
            rel = jnp.clip(ts - tbase, 0, 1)
            k_eff = _dequant_tile(
                kp[trow[ts], h], ks_row[ts], kt[b, rel, h], use_tail,
                q.dtype,
            )
            v_eff = _dequant_tile(
                vp[trow[ts], h], vs_row[ts], vt[b, rel, h], use_tail,
                q.dtype,
            )
            s_pos = ts * block_size + jax.lax.broadcasted_iota(
                jnp.int32, (T, block_size), 1
            )
            q_pos = rl - T + jax.lax.broadcasted_iota(
                jnp.int32, (T, block_size), 0
            )
            pen = jnp.where(s_pos <= q_pos, 0.0, -1e30)
            return _fold_tile_math(
                qr, k_eff, v_eff, pen, m, l, acc, groups=G, scale=scale
            ), None

        init = (
            jnp.full((T * G, 1), -1e30, jnp.float32),
            jnp.zeros((T * G, 1), jnp.float32),
            jnp.zeros((T * G, D), jnp.float32),
        )
        (m, l, acc), _ = jax.lax.scan(
            step, init, jnp.arange(max_blocks, dtype=jnp.int32)
        )
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    out = jax.lax.map(
        _row, (qf, row_tbl, row_head, row_len, row_tb, row_b, ksb, vsb)
    )
    return out.reshape(B, n_kv, T, G, D).transpose(0, 2, 1, 3, 4).reshape(
        B, T, n_heads, D
    )


def dequant_gather_block_kv(pool, scales, tail, block_tables, tail_base):
    """gather_block_kv for the quantized pool: dequantize the gathered
    pages (bitwise kv_blocks.dequantize_blocks' math) and overlay the
    two tail-resident tiles verbatim, returning the [B, max_blocks*bs,
    n_kv, D] linear view in the tail's (compute) dtype. The dense
    fallback AND the GSPMD route: every op here partitions over n_kv
    (pages axis 2, scales axis 1, tail axis 3), the gathers index only
    replicated axes."""
    nb, bs, n_kv, D = pool.shape
    B, M = block_tables.shape
    deq = (
        pool[block_tables].astype(jnp.float32)
        * scales[block_tables][:, :, None, :, None]
    )  # [B, M, bs, n_kv, D] f32
    rel = jnp.arange(M, dtype=jnp.int32)[None, :] - tail_base[:, None]
    use_tail = (rel >= 0) & (rel < 2)
    tg = tail[jnp.arange(B)[:, None], jnp.clip(rel, 0, 1)]
    out = jnp.where(
        use_tail[:, :, None, None, None], tg.astype(jnp.float32), deq
    )
    return out.astype(tail.dtype).reshape(B, M * bs, n_kv, D)


def decode_attention_blocks_q8_auto(
    q, k_pool, v_pool, k_scales, v_scales, k_tail, v_tail,
    block_tables, lengths, mask, gspmd=False,
):
    """Quantized-pool twin of decode_attention_blocks_auto: the q8
    Pallas kernel when shapes/backend allow, dequantize-gather + dense
    jnp over ``mask`` otherwise (and always under ``gspmd`` — same
    custom-call constraint). Same lengths/mask live-set contract; the
    tail base both branches derive is (lengths - T) // block_size."""
    T = q.shape[1]
    block_size = k_pool.shape[1]
    if (not gspmd) and decode_blocks_available(block_size, q.shape[3]):
        return decode_attention_blocks_q8(
            q, k_pool, v_pool, k_scales, v_scales, k_tail, v_tail,
            block_tables, lengths,
        )
    tb = jnp.maximum(jnp.asarray(lengths, jnp.int32) - T, 0) // block_size
    return dense_attention(
        q,
        dequant_gather_block_kv(
            k_pool, k_scales, k_tail, block_tables, tb
        ),
        dequant_gather_block_kv(
            v_pool, v_scales, v_tail, block_tables, tb
        ),
        mask,
    )


# --- backward (recompute-based custom_vjp over the ragged kernel) ----------


def _ragged_pen(c0, row_len, tq, ts, tile_t, tile_s):
    """The ragged causal penalty tile, shared by the lse-forward and both
    backward kernels — identical mask derivation is what makes the
    recomputed probabilities match the forward bit-for-bit."""
    q_pos = (
        c0 + tq * tile_t
        + jax.lax.broadcasted_iota(jnp.int32, (tile_t, tile_s), 0)
    )
    s_pos = ts * tile_s + jax.lax.broadcasted_iota(
        jnp.int32, (tile_t, tile_s), 1
    )
    attend = (s_pos <= q_pos) & (s_pos < row_len)
    return jnp.where(attend, 0.0, -1e30)


def _flash_ragged_lse_kernel(
    c0_ref, len_ref,  # len_ref: SMEM i32[B], indexed in-kernel
    q_ref, k_ref, v_ref,
    o_ref,
    lse_ref,  # [1, TILE_T * G, 1] out: per-row logsumexp (m + log l)
    m_scr, l_scr, acc_scr,
    *, groups: int, scale: float, s_tiles: int, tile_t: int, tile_s: int,
    n_kv: int,
):
    """The ragged forward, additionally emitting the logsumexp the
    backward's probability recompute needs. Identical o math to
    _flash_ragged_kernel (same _softmax_fold) — custom_vjp requires the
    fwd path to reproduce the primal's output exactly."""
    tq = pl.program_id(1)
    ts = pl.program_id(2)
    row_len = len_ref[pl.program_id(0) // n_kv]
    pen = _ragged_pen(c0_ref[0], row_len, tq, ts, tile_t, tile_s)
    _softmax_fold(
        q_ref, k_ref, v_ref, pen, o_ref, m_scr, l_scr, acc_scr,
        groups=groups, scale=scale, s_tiles=s_tiles,
        active=_ragged_tile_active(
            c0_ref[0], row_len, tq, ts, tile_t, tile_s
        ),
    )

    @pl.when(ts == s_tiles - 1)
    def _emit_lse():
        lse_ref[0] = m_scr[:] + jnp.log(jnp.maximum(l_scr[:], 1e-30))


def _recompute_p(q, k, pen, lse_col, row_len, groups, scale):
    """[TqG, Sk] softmax probabilities from (q, k, L): exp(qk*scale +
    pen - L). Exact — L is the forward's converged logsumexp. A fully
    masked row (row_len == 0) is degenerate: s and L both saturate at
    -1e30 in f32 so exp(s - L) would be 1 per slot, not 0 — gate to 0 so
    dq/dk/dv for such rows vanish like the dense path's."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    tq, sk = pen.shape
    s = (s.reshape(tq, groups, sk) + pen[:, None, :]).reshape(
        tq * groups, sk
    )
    return jnp.where(row_len > 0, jnp.exp(s - lse_col), 0.0)


def _flash_bwd_dq_kernel(
    c0_ref, len_ref,
    q_ref, k_ref, v_ref, do_ref,  # [1, TqG, D] / [1, Sk, D] blocks
    lse_ref,  # [1, TqG]
    drow_ref,  # [1, TqG] rowsum(dO * O)
    dq_ref,  # [1, TqG, D] out
    dq_scr,  # f32[TqG, D] scratch
    *, groups: int, scale: float, s_tiles: int, tile_t: int, tile_s: int,
    n_kv: int,
):
    tq = pl.program_id(1)
    ts = pl.program_id(2)  # innermost: S sweep, dq resident

    @pl.when(ts == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    row_len = len_ref[pl.program_id(0) // n_kv]

    # Fully-masked tiles contribute exactly 0 (p underflows to 0 against
    # the row-global lse; row_len == 0 rows are gated to p == 0 inside
    # _recompute_p), so skipping them is bit-identical — same causal
    # upper-triangle VPU saving as the forward's _ragged_tile_active.
    @pl.when(
        (ts * tile_s <= c0_ref[0] + (tq + 1) * tile_t - 1)
        & (ts * tile_s < row_len)
    )
    def _accum():
        pen = _ragged_pen(c0_ref[0], row_len, tq, ts, tile_t, tile_s)
        p = _recompute_p(
            q_ref[0], k_ref[0], pen, lse_ref[0], row_len, groups, scale
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [TqG, Sk]
        ds = p * (dp - drow_ref[0])
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ts == s_tiles - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    c0_ref, len_ref,
    q_ref, k_ref, v_ref, do_ref,
    lse_ref, drow_ref,
    dk_ref, dv_ref,  # [1, Sk, D] out
    dk_scr, dv_scr,  # f32[Sk, D] scratch
    *, groups: int, scale: float, t_tiles: int, tile_t: int, tile_s: int,
    n_kv: int,
):
    ts = pl.program_id(1)
    tq = pl.program_id(2)  # innermost: T sweep, dk/dv resident

    @pl.when(tq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    row_len = len_ref[pl.program_id(0) // n_kv]

    # same provably-zero-tile skip as the dq kernel (grid here is
    # (bn, ts, tq), so the guard reads the swapped program ids)
    @pl.when(
        (ts * tile_s <= c0_ref[0] + (tq + 1) * tile_t - 1)
        & (ts * tile_s < row_len)
    )
    def _accum():
        pen = _ragged_pen(c0_ref[0], row_len, tq, ts, tile_t, tile_s)
        p = _recompute_p(
            q_ref[0], k_ref[0], pen, lse_ref[0], row_len, groups, scale
        )
        # dv += p^T dO; the folded (t, g) rows make the GQA group
        # reduction implicit in the row contraction
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - drow_ref[0])
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(tq == t_tiles - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _check_diff_tiles(T, S, tile_t, tile_s):
    if T % tile_t or S % tile_s:
        raise ValueError(
            f"flash_attention_causal_diff needs T divisible by {tile_t} "
            f"and S by {tile_s}; got T={T} S={S} (use the dense path for "
            "unaligned shapes)"
        )


def _fold_qlike(x, n_kv):
    """[B, T, n_heads, D] -> [B*n_kv, T*G, D] (the kernels' row fold)."""
    B, T, n_heads, D = x.shape
    G = n_heads // n_kv
    return (
        x.reshape(B, T, n_kv, G, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B * n_kv, T * G, D)
    )


def _unfold_qlike(x, B, n_kv, T, G, D):
    return (
        x.reshape(B, n_kv, T, G, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, T, n_kv * G, D)
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def flash_attention_causal_diff(interpret, q, k, v, q_offset, row_lens):
    """Differentiable ragged-causal flash attention.

    Primal = flash_attention_ragged (bit-identical); under jax.grad the
    fwd re-runs with the lse output and the bwd runs the recompute
    kernels. ``interpret`` is a nondiff static for CPU parity tests.
    """
    # tile sizes resolved at CALL time from the module globals — the
    # vjp fwd below reads the same globals, so primal and fwd always
    # tile (and therefore accumulate) identically, even under tests
    # that monkeypatch TILE_T/TILE_S
    return flash_attention_ragged(
        q, k, v, q_offset, row_lens,
        tile_t=TILE_T, tile_s=TILE_S, interpret=interpret,
    )


def _diff_fwd(interpret, q, k, v, q_offset, row_lens):
    B, T, n_heads, D = q.shape
    S, n_kv = k.shape[1], k.shape[2]
    G = n_heads // n_kv
    tile_t = min(TILE_T, T)
    tile_s = min(TILE_S, S)
    _check_diff_tiles(T, S, tile_t, tile_s)
    t_tiles, s_tiles = T // tile_t, S // tile_s
    qf = _fold_qlike(q, n_kv)
    kf = k.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)
    c0 = jnp.asarray(q_offset, jnp.int32).reshape(1)
    lens = jnp.asarray(row_lens, jnp.int32)
    kern = functools.partial(
        _flash_ragged_lse_kernel, groups=G, scale=1.0 / float(D) ** 0.5,
        s_tiles=s_tiles, tile_t=tile_t, tile_s=tile_s, n_kv=n_kv,
    )
    smem1 = pl.BlockSpec(
        (1,), lambda bh, tq, ts: (0,), memory_space=pltpu.SMEM
    )
    smem_b = pl.BlockSpec(
        (B,), lambda bh, tq, ts: (0,), memory_space=pltpu.SMEM
    )
    qspec = pl.BlockSpec(
        (1, tile_t * G, D), lambda bh, tq, ts: (bh, tq, 0),
        memory_space=pltpu.VMEM,
    )
    kspec = pl.BlockSpec(
        (1, tile_s, D), lambda bh, tq, ts: (bh, ts, 0),
        memory_space=pltpu.VMEM,
    )
    out, lse = pl.pallas_call(
        kern,
        grid=(B * n_kv, t_tiles, s_tiles),
        in_specs=[smem1, smem_b, qspec, kspec, kspec],
        out_specs=[
            qspec,
            pl.BlockSpec(
                (1, tile_t * G, 1), lambda bh, tq, ts: (bh, tq, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * n_kv, T * G, D), q.dtype),
            jax.ShapeDtypeStruct((B * n_kv, T * G, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_t * G, 1), jnp.float32),
            pltpu.VMEM((tile_t * G, 1), jnp.float32),
            pltpu.VMEM((tile_t * G, D), jnp.float32),
        ],
        interpret=interpret,
    )(c0, lens, qf, kf, vf)
    o = _unfold_qlike(out, B, n_kv, T, G, D)
    return o, (q, k, v, c0, lens, out, lse)


def _diff_bwd(interpret, res, do):
    q, k, v, c0, lens, of, lse = res
    B, T, n_heads, D = q.shape
    S, n_kv = k.shape[1], k.shape[2]
    G = n_heads // n_kv
    tile_t = min(TILE_T, T)
    tile_s = min(TILE_S, S)
    t_tiles, s_tiles = T // tile_t, S // tile_s
    scale = 1.0 / float(D) ** 0.5
    qf = _fold_qlike(q, n_kv)
    kf = k.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)
    dof = _fold_qlike(do, n_kv)
    # rowsum(dO * O): cheap fused XLA reduce, shared by both kernels
    drow = jnp.sum(
        dof.astype(jnp.float32) * of.astype(jnp.float32), axis=2,
        keepdims=True,
    )  # [B*n_kv, T*G, 1]

    smem1 = pl.BlockSpec(
        (1,), lambda bh, a, b: (0,), memory_space=pltpu.SMEM
    )
    smem_b = pl.BlockSpec(
        (B,), lambda bh, a, b: (0,), memory_space=pltpu.SMEM
    )

    # dq: grid (bh, tq, ts), S innermost
    q_at_tq = pl.BlockSpec(
        (1, tile_t * G, D), lambda bh, tq, ts: (bh, tq, 0),
        memory_space=pltpu.VMEM,
    )
    kv_at_ts = pl.BlockSpec(
        (1, tile_s, D), lambda bh, tq, ts: (bh, ts, 0),
        memory_space=pltpu.VMEM,
    )
    row_at_tq = pl.BlockSpec(
        (1, tile_t * G, 1), lambda bh, tq, ts: (bh, tq, 0),
        memory_space=pltpu.VMEM,
    )
    dqf = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, groups=G, scale=scale, s_tiles=s_tiles,
            tile_t=tile_t, tile_s=tile_s, n_kv=n_kv,
        ),
        grid=(B * n_kv, t_tiles, s_tiles),
        in_specs=[smem1, smem_b, q_at_tq, kv_at_ts, kv_at_ts, q_at_tq,
                  row_at_tq, row_at_tq],
        out_specs=q_at_tq,
        out_shape=jax.ShapeDtypeStruct((B * n_kv, T * G, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((tile_t * G, D), jnp.float32)],
        interpret=interpret,
    )(c0, lens, qf, kf, vf, dof, lse, drow)

    # dk/dv: grid (bh, ts, tq), T innermost
    q_at_tq2 = pl.BlockSpec(
        (1, tile_t * G, D), lambda bh, ts, tq: (bh, tq, 0),
        memory_space=pltpu.VMEM,
    )
    kv_at_ts2 = pl.BlockSpec(
        (1, tile_s, D), lambda bh, ts, tq: (bh, ts, 0),
        memory_space=pltpu.VMEM,
    )
    row_at_tq2 = pl.BlockSpec(
        (1, tile_t * G, 1), lambda bh, ts, tq: (bh, tq, 0),
        memory_space=pltpu.VMEM,
    )
    dkf, dvf = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, groups=G, scale=scale, t_tiles=t_tiles,
            tile_t=tile_t, tile_s=tile_s, n_kv=n_kv,
        ),
        grid=(B * n_kv, s_tiles, t_tiles),
        in_specs=[smem1, smem_b, q_at_tq2, kv_at_ts2, kv_at_ts2,
                  q_at_tq2, row_at_tq2, row_at_tq2],
        out_specs=[kv_at_ts2, kv_at_ts2],
        out_shape=[
            jax.ShapeDtypeStruct((B * n_kv, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * n_kv, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_s, D), jnp.float32),
            pltpu.VMEM((tile_s, D), jnp.float32),
        ],
        interpret=interpret,
    )(c0, lens, qf, kf, vf, dof, lse, drow)

    dq = _unfold_qlike(dqf, B, n_kv, T, G, D)
    dk = dkf.reshape(B, n_kv, S, D).transpose(0, 2, 1, 3)
    dv = dvf.reshape(B, n_kv, S, D).transpose(0, 2, 1, 3)
    import numpy as _np

    f0 = jax.dtypes.float0
    return (
        dq, dk, dv,
        _np.zeros(jnp.shape(jnp.asarray(0, jnp.int32)), f0),
        _np.zeros(res[4].shape, f0),
    )


flash_attention_causal_diff.defvjp(_diff_fwd, _diff_bwd)


def flash_available(T: int, S: int, D: int) -> bool:
    """Shapes the kernels handle on the current default backend.

    Deliberately conservative: a wrong True here is a Mosaic compile
    error at trace time (there is no catchable fallback once the outer
    jit lowers), so the guard admits only shapes of the class actually
    exercised on hardware — sublane-aligned T, lane-aligned S tiles, and
    the production head dims (64/128/256). Tiny test models (D=16) route
    to the dense path.
    """
    return (
        jax.default_backend() == "tpu"
        and T % 8 == 0
        and T % min(TILE_T, T) == 0
        and S % min(TILE_S, S) == 0
        and S % 128 == 0
        and T >= 8
        and S >= 128
        and D % 64 == 0
    )


def attention_auto(q, k, v, mask):
    """model.attention signature; Pallas kernel when shapes/backend
    allow, dense jnp otherwise. Drop-in for ``forward(attn_fn=...)``."""
    if flash_available(q.shape[1], k.shape[1], q.shape[3]):
        return flash_attention(q, k, v, mask)
    return dense_attention(q, k, v, mask)


def causal_attention_auto(q, k, v, mask):
    """Plain causal self-attention (T == S) with the mask derived
    in-kernel — model.forward's no-cache path binds this so training
    and full-sequence prefill never ship a [B, T, T] tensor to the
    kernel. ``mask`` is the caller's dense-fallback mask: the flash
    branch never reads it and XLA dead-code-eliminates its
    construction (the same contract as engine.chunked_prefill's flash
    branch). Differentiable: the flash branch routes through the
    custom_vjp wrapper, so this binding works under jax.grad (training
    at long context no longer needs the dense path's [T, T] scores)."""
    B, T = q.shape[0], q.shape[1]
    S, D = k.shape[1], q.shape[3]
    if T == S and flash_available(T, S, D):
        return flash_attention_causal_diff(
            False, q, k, v, 0, jnp.full((B,), S, jnp.int32)
        )
    return dense_attention(q, k, v, mask)
