"""Pallas TPU flash attention for the prefill/training hot op.

The dense path (model.attention) materializes [B, n_kv, G, T, S] f32
scores through HBM; at long context that is the dominant memory term
(a 512-token chunk against a 128k cache is 0.5GB of scores per layer at
B=8, H=32). This kernel streams K/V tiles through VMEM with the online
softmax recurrence (running rowmax m, normalizer l, accumulator o — the
same algebra as ring_attention.py's block fold, here over the LOCAL S
axis instead of a device ring), so the f32 score/probability tensors
never touch HBM. The caller's bool[B, T, S] mask does still ship to the
kernel (as int8, head-independent — 4*n_kv*G times smaller than the
scores it replaces); deriving the engine's causal/ragged mask in-kernel
from (chunk offset, row lengths) iotas would remove that last
[T, S]-sized term and is the natural next step if profiles demand it.

Layout: GQA folds the (T, G) axes into MXU rows — q becomes
[B*n_kv, T*G, D], each S tile is one [T_q*G, D] x [D, S_k] matmul plus
one [T_q*G, S_k] x [S_k, D] matmul, and the boolean mask (which depends
on T alone, not G) broadcasts across the G subrows in-register. The S
grid axis is innermost with the accumulators in VMEM scratch, so state
stays resident across the sweep (same accumulate-across-grid idiom as
the solver's accept kernel).

The public entry ``flash_attention`` matches model.attention's signature
([B, T, H, D] q, [B, S, n_kv, D] k/v, bool[B, T, S] mask) so it plugs
into ``forward(attn_fn=...)`` unchanged; ``attention_auto`` picks the
kernel when the backend and shapes allow and falls back to the dense
jnp path otherwise. Fully-masked rows reproduce the dense path's
uniform-softmax output exactly (all scores -1e30 -> p == 1 everywhere
-> o/l is the mean over S), so parity holds even on padding rows.

No reference counterpart: the reference delegates all attention to the
external vLLM process (SURVEY.md §2, vllm.go:93-112).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubeinfer_tpu.inference.model import attention as dense_attention

TILE_T = 256  # query positions per tile (rows = TILE_T * G)
TILE_S = 512  # key/value positions per tile


def _flash_kernel(
    q_ref,  # [1, TILE_T * G, D] folded (t, g) query rows
    k_ref,  # [1, TILE_S, D]
    v_ref,  # [1, TILE_S, D]
    mask_ref,  # [1, TILE_T, TILE_S] int8 (1 = attend)
    o_ref,  # [1, TILE_T * G, D] out
    m_scr,  # f32[TILE_T * G, 1] scratch: running rowmax
    l_scr,  # f32[TILE_T * G, 1] scratch: running normalizer
    acc_scr,  # f32[TILE_T * G, D] scratch: running accumulator
    *,
    groups: int,
    scale: float,
    s_tiles: int,
):
    ts = pl.program_id(2)  # innermost: S sweep with resident scratch

    @pl.when(ts == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]  # [TqG, D]
    k = k_ref[0]  # [Sk, D]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [TqG, Sk]
    # Masking as an f32 additive penalty broadcast across the G subrows.
    # Mosaic cannot relayout i1 vectors ("unsupported shape cast" on a
    # bool [Tq, 1, Sk] broadcast), so the bool never changes rank: it
    # converts to f32 first, and the rank changes happen on f32 values.
    pen = (mask_ref[0].astype(jnp.float32) - 1.0) * 1e30  # 0 attend, -1e30 not
    tq, sk = pen.shape
    s = (s.reshape(tq, groups, sk) + pen[:, None, :]).reshape(
        tq * groups, sk
    )

    m_prev = m_scr[:]  # [TqG, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)  # [TqG, Sk] f32
    l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [TqG, D]
    acc_scr[:] = acc_scr[:] * alpha + pv
    m_scr[:] = m_new

    @pl.when(ts == s_tiles - 1)
    def _finish():
        # l == 0 cannot happen (even fully-masked rows accumulate
        # p == 1 per position); the guard keeps hypothetical S == 0
        # grids finite.
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(
            o_ref.dtype
        )


def flash_attention(
    q: jax.Array,  # [B, T, n_heads, D]
    k: jax.Array,  # [B, S, n_kv, D]
    v: jax.Array,  # [B, S, n_kv, D]
    mask: jax.Array,  # bool[B, T, S] True = attend
    *,
    tile_t: int = TILE_T,
    tile_s: int = TILE_S,
    interpret: bool = False,
) -> jax.Array:
    """Exact attention, streamed; requires T % tile_t == S % tile_s == 0.

    Callers wanting automatic fallback for unaligned shapes use
    ``attention_auto``.
    """
    B, T, n_heads, D = q.shape
    S, n_kv = k.shape[1], k.shape[2]
    G = n_heads // n_kv
    tile_t = min(tile_t, T)
    tile_s = min(tile_s, S)
    if T % tile_t or S % tile_s:
        raise ValueError(
            f"flash_attention needs T divisible by {tile_t} and S by "
            f"{tile_s}; got T={T} S={S} (use attention_auto for fallback)"
        )
    t_tiles, s_tiles = T // tile_t, S // tile_s

    # fold (B, n_kv) into the grid axis and (T, G) into MXU rows
    qf = q.reshape(B, T, n_kv, G, D).transpose(0, 2, 1, 3, 4)
    qf = qf.reshape(B * n_kv, T * G, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * n_kv, S, D)
    mask8 = mask.astype(jnp.int8)

    kern = functools.partial(
        _flash_kernel,
        groups=G,
        scale=1.0 / float(D) ** 0.5,
        s_tiles=s_tiles,
    )
    out = pl.pallas_call(
        kern,
        grid=(B * n_kv, t_tiles, s_tiles),
        in_specs=[
            pl.BlockSpec(
                (1, tile_t * G, D), lambda bh, tq, ts: (bh, tq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, tile_s, D), lambda bh, tq, ts: (bh, ts, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, tile_s, D), lambda bh, tq, ts: (bh, ts, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, tile_t, tile_s),
                lambda bh, tq, ts, n_kv=n_kv: (bh // n_kv, tq, ts),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, tile_t * G, D), lambda bh, tq, ts: (bh, tq, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((B * n_kv, T * G, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((tile_t * G, 1), jnp.float32),
            pltpu.VMEM((tile_t * G, 1), jnp.float32),
            pltpu.VMEM((tile_t * G, D), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, mask8)
    out = out.reshape(B, n_kv, T, G, D).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, T, n_heads, D)


def flash_available(T: int, S: int, D: int) -> bool:
    """Shapes the kernel handles on the current default backend.

    Deliberately conservative: a wrong True here is a Mosaic compile
    error at trace time (there is no catchable fallback once the outer
    jit lowers), so the guard admits only shapes of the class actually
    exercised on hardware — sublane-aligned T, lane-aligned S tiles, and
    the production head dims (64/128/256). Tiny test models (D=16) route
    to the dense path.
    """
    return (
        jax.default_backend() == "tpu"
        and T % 8 == 0
        and T % min(TILE_T, T) == 0
        and S % min(TILE_S, S) == 0
        and S % 128 == 0
        and T >= 8
        and S >= 128
        and D % 64 == 0
    )


def attention_auto(q, k, v, mask):
    """model.attention signature; Pallas kernel when shapes/backend
    allow, dense jnp otherwise. Drop-in for ``forward(attn_fn=...)``."""
    if flash_available(q.shape[1], k.shape[1], q.shape[3]):
        return flash_attention(q, k, v, mask)
    return dense_attention(q, k, v, mask)
