"""Speculative decoding: a small draft model proposes, the target verifies.

ONE acceptance rule, greedy and sampled alike: ``stepper.spec_accept``
— accept draft token i iff it equals the token the TARGET itself draws
at that position, under the position-folded noise schedule the decode
stepper uses everywhere (``stepper.sample_rows``: a per-row key folded
with the token's output index). The emitted row IS the target's own
sample stream, so:

- **Greedy** (temperature <= 0) output is **token-identical to vanilla
  greedy decoding** for ANY draft model — the draft only changes how
  many target forwards the sequence costs, never what it says
  (tests/test_speculative.py pins it against Engine.generate). Caveat
  (advisor r2): the identity additionally assumes the backend produces
  shape-independent matmul/softmax numerics — the verification forward
  runs at T=k+1 while vanilla decode runs T=1, and XLA may fuse or
  reassociate differently per shape, so a near-tied argmax could
  diverge on some backends even though the CPU tests pin it (same class
  of caveat as the flash-vs-dense note in engine.chunked_prefill).
- **Sampled** (temperature > 0) output follows EXACTLY the target's
  sampling law — not an approximation — for any draft, because every
  emitted token is the target's own draw under fresh per-position
  noise; the draft only decides how many of those draws one verify
  forward can confirm. The draft proposes with the SAME key/counter as
  the target draw it is guessing, so a perfect draft proposes the
  identical token and acceptance is ~1.0 (correlated noise moves the
  acceptance RATE, never the output distribution). This replaces the
  earlier rejection-sampling correction: match-acceptance needs no
  residual resample, keeps one acceptance implementation for this
  engine and the paged verify window (stepper.verify_window), and is
  what makes the paged twin token-identical to its plain engine.
  Repetition penalty stays excluded (it reshapes the distribution per
  step from generated-token state the verifier's parallel window
  cannot see; the server routes such requests away).

Static shapes throughout (the jit discipline of engine.py):

- each speculation round runs exactly ``k`` draft steps (T=1 forwards on
  the draft's KV cache) and ONE target forward over the ``k+1`` window
  [current token, draft_1..draft_k];
- acceptance is a prefix-AND reduction; every round emits between 1 and
  k+1 tokens into a fixed [B, max_new + k + 1] buffer at per-row write
  offsets (rows advance at different speeds — the per-row cache-offset
  machinery in model.decoder_layer carries the divergence);
- rejected cache entries are never erased: the per-row offset simply
  moves back over them, the position-bounded mask hides them, and the
  next round's writes overwrite them (the same trick the engine's
  decode scan uses for its fixed-capacity cache);
- the round scan runs ``max_new`` times (worst case every round emits
  just 1 token); finished rows keep stepping with writes masked — the
  standard static-shape idiom.

Cost model: a round costs 1 target forward of T=k+1 (≈ the cost of a
T=1 decode step for HBM-bound models — weights dominate) plus k draft
forwards. With acceptance rate a, expected tokens/round ≈ (1-a^{k+1})/
(1-a), so a draft ~10x smaller at a ≈ 0.8 and k=4 cuts target forwards
~3x. No reference counterpart (the reference delegates decoding to
external vLLM, SURVEY.md §2 #8); design follows the public speculative
decoding literature (PAPERS.md).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.engine import (
    GenerationResult,
    chunked_prefill,
    prefill_chunk_for,
    make_caches,
    prepare_prompts,
)
from kubeinfer_tpu.inference.model import Params, forward
from kubeinfer_tpu.inference.stepper import sample_rows, spec_accept


def _draw(logits, temperature, top_k, top_p, rng, counter):
    """One per-row draw through the stepper's shared sampler: per-row
    warp knobs, per-row key folded with ``counter`` (the token's output
    index — the position-folded schedule the slot path uses). The
    repetition-penalty/seen operands are pinned disabled: this engine
    excludes penalized requests (module docstring), so the no-op
    operands keep sample_rows the single sampling implementation
    without threading dead [B, V] state through the round carry."""
    B = logits.shape[0]
    return sample_rows(
        logits, temperature, top_k, top_p,
        jnp.ones((B,), jnp.float32),
        jnp.zeros((B, logits.shape[-1]), bool),
        rng, counter,
    )


def _decode_mask(cache_len: int, offsets, q_width: int):
    """bool[B, q_width, cache_len]: row b's query at global position
    offsets[b]+i attends cache slots <= that position (stale slots
    beyond the valid frontier are excluded by the bound)."""
    q_pos = offsets[:, None] + jnp.arange(q_width)[None, :]  # [B, W]
    return jnp.arange(cache_len)[None, None, :] <= q_pos[:, :, None]


def _prefill_state(
    params, dparams, prompt, prompt_len, cfg, dcfg, max_new, cache_len,
    k, prefill_chunk, eos_id, temperature, top_k, top_p, rng_key,
):
    """Prefill target+draft and build the round-loop carry (round 0
    emits the target's first token, exactly like engine.py's ``first``).
    Shared by the bulk scan path (_spec_generate_jit) and the
    incremental group path (_spec_group_prefill) — an identical state0
    plus the shared _one_round is what makes the two paths
    bit-identical (tests pin it)."""
    B, T = prompt.shape
    dtype = params["norm"].dtype
    tcaches = make_caches(cfg, B, cache_len, dtype)
    dcaches = make_caches(dcfg, B, cache_len, dparams["norm"].dtype)

    tcaches, t_logits = chunked_prefill(
        params, prompt, prompt_len, cfg, tcaches, prefill_chunk
    )
    dcaches, _ = chunked_prefill(
        dparams, prompt, prompt_len, dcfg, dcaches, prefill_chunk
    )
    # one key per ROW, counters folded per output index: rows that
    # accept at different speeds keep drawing independent fresh noise
    # without any key bookkeeping in the round carry (the stepper's
    # slot schedule, transplanted to the dense solo engine)
    rows_rng = jax.random.split(rng_key, B)
    first = _draw(
        t_logits, temperature, top_k, top_p, rows_rng,
        jnp.zeros((B,), jnp.int32),
    )

    # every round may emit up to k+1 tokens past max_new - 1 priors
    written0 = jnp.zeros((B, max_new + k + 1), jnp.int32)
    written0 = written0.at[:, 0].set(first)
    counts0 = jnp.ones((B,), jnp.int32)
    done0 = (first == eos_id) & (eos_id >= 0)
    # `first` occupies the cache slot AT each row's prompt length; the
    # token before it is the prompt's last real token
    offsets0 = prompt_len
    prev0 = jnp.take_along_axis(
        prompt, jnp.clip(prompt_len - 1, 0, T - 1)[:, None], axis=1
    )[:, 0]
    return (
        tcaches, dcaches, prev0, first, offsets0, written0, counts0, done0,
        jnp.zeros((B,), jnp.int32), jnp.int32(0), rows_rng,
    )


def _one_round(
    params, dparams, cfg, dcfg, k, max_new, eos_id,
    temperature, top_k, top_p, carry,
):
    """One speculation round over the loop carry: k draft proposals, one
    target verify forward, acceptance, buffer write. Module-level so the
    bulk scan and the incremental group path run the SAME trace."""
    (tcaches, dcaches, prev, cur, offsets, written, counts, done,
     accepted, rounds, rows_rng) = carry
    B = prev.shape[0]
    cache_len = tcaches[0][0].shape[1]

    def decode_mask(offsets, q_width):
        return _decode_mask(cache_len, offsets, q_width)

    def draft_propose(dcaches, prev, cur, offsets):
        """k draft steps; proposal i+1 guesses the target draw for
        output index counts+i, so it samples with the SAME per-row key
        and counter that draw will fold — a draft matching the target's
        distribution then proposes the identical token (acceptance 1.0
        for a self-draft), and any weaker draft only lowers the rate.

        The FIRST step runs a 2-token window [prev, cur] (positions
        offsets-1, offsets): after a full-acceptance round the draft
        cache has a hole at offsets-1 — the bonus token was emitted
        without the draft ever processing its predecessor — and querying
        through that hole silently collapses acceptance in every later
        round (r2 review finding). Rewriting the slot is a no-op for
        rows without the hole (same token, same cached context, same
        kv) and repairs it for rows with one.
        """
        logits, dcaches = forward(
            dparams, jnp.stack([prev, cur], axis=1), dcfg,
            positions=jnp.stack([offsets - 1, offsets], axis=1),
            attn_mask=decode_mask(offsets - 1, 2),
            kv_caches=dcaches,
            cache_offset=offsets - 1,
        )
        d1 = _draw(logits[:, 1], temperature, top_k, top_p,
                   rows_rng, counts)

        def step(carry, j):
            dcaches, tok, off = carry
            logits, dcaches = forward(
                dparams, tok[:, None], dcfg,
                positions=off[:, None],
                attn_mask=decode_mask(off, 1),
                kv_caches=dcaches,
                cache_offset=off,
            )
            nxt = _draw(logits[:, 0], temperature, top_k, top_p,
                        rows_rng, counts + j)
            return (dcaches, nxt, off + 1), nxt

        (dcaches, _, _), rest = jax.lax.scan(
            step, (dcaches, d1, offsets + 1),
            jnp.arange(1, k, dtype=jnp.int32),
        )
        drafts = jnp.concatenate([d1[:, None], rest.swapaxes(0, 1)], axis=1)
        return dcaches, drafts

    dcaches, drafts = draft_propose(dcaches, prev, cur, offsets)
    window = jnp.concatenate([cur[:, None], drafts], axis=1)
    t_logits, tcaches = forward(
        params, window, cfg,
        positions=offsets[:, None] + jnp.arange(k + 1)[None, :],
        attn_mask=decode_mask(offsets, k + 1),
        kv_caches=tcaches,
        cache_offset=offsets,
    )

    # the target's own draws at every window position: t_logits[:, i]
    # conditions on window[0..i]; on the accepted prefix those context
    # tokens equal the emitted stream, so each emitted draw is exactly
    # what an unspeculated run would have drawn at that output index
    # (rejected positions' draws are computed but never emitted). The
    # k+1 draws are independent given the logits — no repetition
    # penalty state evolves here — so the loop unrolls statically
    # instead of scanning.
    target = jnp.stack(
        [
            _draw(t_logits[:, i], temperature, top_k, top_p,
                  rows_rng, counts + i)
            for i in range(k + 1)
        ],
        axis=1,
    )
    # emitted = target: accepted drafts equal the target draw at their
    # position by the match rule, so the target row already IS the
    # emitted row — n_emit below bounds what counts
    emitted = target
    m = spec_accept(drafts, target) - 1  # [B] accepted drafts, 0..k
    is_eos = (emitted == eos_id) & (eos_id >= 0)
    first_eos = jnp.where(
        is_eos.any(axis=1),
        jnp.argmax(is_eos, axis=1) + 1,
        k + 1,
    )
    n_emit = jnp.minimum(m + 1, first_eos)
    n_emit = jnp.where(done, 0, n_emit)
    hit_eos = is_eos.any(axis=1) & (first_eos <= m + 1)

    # write the static row at each row's count; slots past n_emit are
    # garbage that the NEXT round's write (which starts inside them)
    # overwrites, and the host slices to counts at the end. Done rows
    # write too (at their frozen count, i.e. beyond their final
    # length) — masking the write would cost a select over the whole
    # buffer for nothing.
    written = jax.vmap(
        lambda buf, row, c: jax.lax.dynamic_update_slice(buf, row, (c,))
    )(written, emitted, counts)

    counts = counts + n_emit
    # diagnostics: accepted draft tokens (the speedup) and rounds
    # with any active row — tests pin sustained acceptance on these
    accepted = accepted + jnp.maximum(n_emit - 1, 0)
    rounds = rounds + jnp.any(~done).astype(jnp.int32)
    done = done | hit_eos | (counts >= max_new)
    # next round continues from the last VALID token; prev is the
    # token one position behind it (the draft's repair window)
    last_idx = jnp.clip(n_emit - 1, 0, k)
    new_cur = jnp.take_along_axis(
        emitted, last_idx[:, None], axis=1
    )[:, 0]
    prev_idx = jnp.clip(n_emit - 2, 0, k)
    new_prev = jnp.where(
        n_emit >= 2,
        jnp.take_along_axis(emitted, prev_idx[:, None], axis=1)[:, 0],
        cur,
    )
    prev = jnp.where(n_emit > 0, new_prev, prev)
    cur = jnp.where(n_emit > 0, new_cur, cur)
    offsets = offsets + n_emit
    return (tcaches, dcaches, prev, cur, offsets, written, counts, done,
            accepted, rounds, rows_rng)


def _vector_warp(B, temperature, top_k, top_p):
    """Broadcast scalar-or-[B] warp knobs to per-row [B] vectors."""
    return (
        jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,)),
        jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,)),
        jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,)),
    )


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "dcfg", "max_new", "cache_len", "k",
                     "prefill_chunk"),
)
def _spec_generate_jit(
    params: Params,
    dparams: Params,
    prompt: jax.Array,  # i32[B, T_bucket] left-aligned, 0-padded
    prompt_len: jax.Array,  # i32[B]
    cfg: ModelConfig,
    dcfg: ModelConfig,
    max_new: int,
    cache_len: int,
    k: int,
    prefill_chunk: int,
    eos_id: jax.Array,  # i32 (negative = never stop)
    temperature: jax.Array | float = 0.0,
    top_k: jax.Array | int = 0,
    top_p: jax.Array | float = 1.0,
    rng_key: jax.Array | None = None,
):
    """Bulk path: prefill + all rounds in one scan (fastest for a solo
    generate). The incremental group path runs the same _prefill_state /
    _one_round pair one round per call (bit-identical outputs)."""
    B = prompt.shape[0]
    temperature, top_k, top_p = _vector_warp(B, temperature, top_k, top_p)
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)
    state0 = _prefill_state(
        params, dparams, prompt, prompt_len, cfg, dcfg, max_new,
        cache_len, k, prefill_chunk, eos_id, temperature,
        top_k, top_p, rng_key,
    )

    def round_step(carry, _):
        return _one_round(
            params, dparams, cfg, dcfg, k, max_new, eos_id,
            temperature, top_k, top_p, carry,
        ), ()

    if max_new > 1:
        state, _ = jax.lax.scan(round_step, state0, None, length=max_new - 1)
    else:
        state = state0
    written, counts, accepted, rounds = state[5], state[6], state[8], state[9]
    return written, jnp.minimum(counts, max_new), accepted, rounds


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "dcfg", "max_new", "cache_len", "k",
                     "prefill_chunk"),
)
def _spec_group_prefill(
    params, dparams, prompt, prompt_len, cfg, dcfg, max_new, cache_len,
    k, prefill_chunk, eos_id, temperature, top_k, top_p, rng_key,
):
    return _prefill_state(
        params, dparams, prompt, prompt_len, cfg, dcfg, max_new,
        cache_len, k, prefill_chunk, eos_id, temperature,
        top_k, top_p, rng_key,
    )


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "dcfg", "max_new", "k"),
    donate_argnums=(2,),
)
def _spec_group_round(
    params, dparams, carry, cfg, dcfg, max_new, k, eos_id,
    temperature, top_k, top_p,
):
    """One speculation round for a live group (carry donated: the KV
    caches are rewritten in place across rounds)."""
    return _one_round(
        params, dparams, cfg, dcfg, k, max_new, eos_id,
        temperature, top_k, top_p, carry,
    )


@dataclass
class SpeculativeEngine:
    """Greedy generation with draft-model speculation.

    ``generate`` matches Engine.generate's greedy output token-for-token
    (the acceptance rule guarantees it); ``k`` is the speculation depth.
    """

    params: Params
    cfg: ModelConfig
    draft_params: Params
    draft_cfg: ModelConfig
    k: int = 4
    max_cache_len: int = 0
    # diagnostics from the most recent generate(); None before any call
    last_stats: dict | None = None

    def __post_init__(self):
        if self.cfg.vocab_size != self.draft_cfg.vocab_size:
            raise ValueError(
                "draft and target must share a vocabulary "
                f"({self.draft_cfg.vocab_size} vs {self.cfg.vocab_size})"
            )
        if self.k < 1:
            # k=0 would trace a [B, 2] window against k+1=1-column masks
            # and die with a shape error inside jit on the first request
            raise ValueError(f"speculation depth k must be >= 1, got {self.k}")
        if not self.max_cache_len:
            self.max_cache_len = self.cfg.max_position_embeddings

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Whether a request fits this engine's cache INCLUDING the k+1
        speculation slack — callers (the server) fall back to the plain
        engine when it does not, instead of failing a request that the
        target model alone could serve."""
        return (
            prompt_len + max_new_tokens + self.k + 1 <= self.max_cache_len
        )

    def generate(
        self,
        prompts: list[list[int]],
        max_new_tokens: int = 32,
        eos_id: int = -1,
        temperature: float = 0.0,
        seed: int = 0,
        top_k: int = 0,
        top_p: float = 1.0,
    ) -> GenerationResult:
        if not prompts:
            return GenerationResult(
                np.zeros((0, 0), np.int32), np.zeros((0,), np.int32)
            )
        B = len(prompts)
        # slack: every round may write up to k+1 cache entries past the
        # frontier
        padded, lens, cache_len = prepare_prompts(
            prompts, max_new_tokens, self.max_cache_len, slack=self.k + 1
        )

        toks, counts, accepted, rounds = _spec_generate_jit(
            self.params, self.draft_params,
            jnp.asarray(padded), jnp.asarray(lens),
            self.cfg, self.draft_cfg,
            max_new_tokens, cache_len, self.k,
            prefill_chunk_for(B, int(padded.shape[1])),
            jnp.int32(eos_id),
            temperature=jnp.float32(temperature),
            top_k=jnp.int32(top_k),
            top_p=jnp.float32(top_p),
            rng_key=jax.random.PRNGKey(seed),
        )
        return self._assemble(toks, counts, accepted, rounds,
                              max_new_tokens, eos_id)

    def _assemble(self, written, counts, accepted, rounds, max_new,
                  eos_id) -> GenerationResult:
        """Shared output contract for the bulk and incremental paths:
        clamp counts to max_new, slice each row to its count, EOS-pad
        beyond it (engine.py's contract), record diagnostics. One copy —
        the bulk/incremental bit-identity the tests pin depends on both
        paths assembling identically."""
        # diagnostics for tests/telemetry: accepted draft tokens per row
        # and speculation rounds executed (the cost side of the trade)
        self.last_stats = {
            "accepted_drafts": np.asarray(accepted),
            "rounds": int(rounds),
        }
        toks = np.asarray(written)[:, :max_new]
        counts = np.minimum(np.asarray(counts), max_new)
        B = toks.shape[0]
        out = np.full((B, max_new), eos_id, np.int32)
        for b in range(B):
            out[b, : counts[b]] = toks[b, : counts[b]]
        return GenerationResult(out, counts)

    # -- incremental group API (r4 verdict item 5) ------------------------
    #
    # The bulk generate() blocks for the whole scan, which is right for a
    # solo request but wrong inside the continuous batcher: a draft group
    # must interleave with busy decode slots. start/step/finish split the
    # SAME computation at round granularity — _prefill_state and
    # _one_round are shared with the scan, so the incremental outputs are
    # bit-identical to generate()'s (tests pin it). One round costs k
    # draft forwards + one (k+1)-wide target forward, so slot requests
    # see a bounded ~2-step latency bubble per round, not a whole
    # generation.

    def start_group(
        self,
        prompts: list[list[int]],
        max_new_tokens: int = 32,
        eos_id: int = -1,
        temperatures: list[float] | float = 0.0,
        top_ks: list[int] | int = 0,
        top_ps: list[float] | float = 1.0,
        seed: int = 0,
    ) -> "SpecGroup":
        """Prefill a draft group. Warp knobs are per-row — greedy and
        sampled rows share one trace now that both run the same
        match-acceptance math (a row at temperature 0 just draws its
        argmax), though the batcher still drains homogeneous groups.
        Per-row keys derive from one group seed (the head request's):
        each row's marginal distribution is exactly the target's (every
        emitted token is the target's own draw), but token-level
        reproducibility is per-group, not per-member."""
        B = len(prompts)
        temperature, top_k, top_p = _vector_warp(
            B, np.asarray(temperatures, np.float32),
            np.asarray(top_ks, np.int32), np.asarray(top_ps, np.float32),
        )
        sampled = bool(np.any(np.asarray(temperatures) > 0))
        padded, lens, cache_len = prepare_prompts(
            prompts, max_new_tokens, self.max_cache_len, slack=self.k + 1
        )
        state = _spec_group_prefill(
            self.params, self.draft_params,
            jnp.asarray(padded), jnp.asarray(lens),
            self.cfg, self.draft_cfg,
            max_new_tokens, cache_len, self.k,
            prefill_chunk_for(B, int(padded.shape[1])),
            jnp.int32(eos_id), temperature, top_k, top_p,
            jax.random.PRNGKey(seed),
        )
        return SpecGroup(
            state=state, max_new=max_new_tokens, eos_id=eos_id,
            sampled=sampled, temperature=temperature, top_k=top_k,
            top_p=top_p,
        )

    def step_group(self, g: "SpecGroup") -> bool:
        """Advance one speculation round; True when every row is done
        (or the round budget — max_new-1, the scan length — is spent)."""
        if g.rounds_run >= g.max_new - 1:
            return True
        g.state = _spec_group_round(
            self.params, self.draft_params, g.state,
            self.cfg, self.draft_cfg, g.max_new, self.k,
            jnp.int32(g.eos_id), g.temperature, g.top_k, g.top_p,
        )
        g.rounds_run += 1
        # lint: allow[host-sync] decode exit: the all-slots-done flag drives the Python scheduling loop
        return bool(np.asarray(g.state[7]).all())

    def finish_group(self, g: "SpecGroup") -> GenerationResult:
        """Read the group's buffers through the shared assembly."""
        return self._assemble(
            g.state[5], g.state[6], g.state[8], g.state[9],
            g.max_new, g.eos_id,
        )


@dataclass
class SpecGroup:
    """Device state of a live incremental draft group (start_group)."""

    state: tuple
    max_new: int
    eos_id: int
    sampled: bool
    temperature: jax.Array  # f32[B]
    top_k: jax.Array  # i32[B]
    top_p: jax.Array  # f32[B]
    rounds_run: int = 0

    @property
    def accepted_drafts(self) -> int:
        """Total draft tokens the target accepted across this group's
        rows — read off the group's OWN carry, not the engine's shared
        last_stats (which any concurrent bulk generate() overwrites)."""
        return int(np.asarray(self.state[8]).sum())
