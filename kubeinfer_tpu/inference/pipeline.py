"""Pipeline parallelism: decoder stages across a ``pp`` mesh axis.

GPipe-style schedule under ``shard_map``: the L layers split into P
contiguous stages (device p holds only its stage's weights — the stacked
layer pytree shards over ``pp``, so an 80-layer model's params divide
across the axis). The batch splits into M microbatches; activations hop
stage-to-stage via ``ppermute`` (neighbor ICI transfer, never a global
gather). The classic (M + P - 1)-tick schedule fills and drains the
bubble; utilization is M/(M+P-1).

Embedding runs on stage 0 and the head on the last stage; the final
logits are broadcast back with a ``psum`` so every device returns the
same value (convenient for loss computation under pure SPMD callers).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from kubeinfer_tpu.utils.jaxcompat import pcast, shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.model import (
    Params,
    causal_mask,
    decoder_layer,
    layer_param_template,
    rms_norm,
    rope_tables,
)


def stack_stage_params(params: Params, n_stages: int) -> Params:
    """Regroup per-layer params into [n_stages, layers_per_stage, ...]
    stacked arrays (the leading axis shards over ``pp``)."""
    L = len(params["layers"])
    if L % n_stages:
        raise ValueError(f"{L} layers do not divide into {n_stages} stages")
    per = L // n_stages
    stages = []
    for s in range(n_stages):
        chunk = params["layers"][s * per : (s + 1) * per]
        stages.append(
            jax.tree.map(lambda *xs: jnp.stack(xs), *chunk)
        )
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)
    out = dict(params)
    out["layers"] = stacked  # pytree of [n_stages, per, ...]
    return out



@functools.cache
def _pp_fn(cfg: ModelConfig, mesh: Mesh, M: int, tied: bool):
    """Memoized jitted shard_map per (cfg, mesh, microbatches): building
    it per call would retrace and recompile every forward."""
    # spec tree derived from the layer's actual key structure (family-
    # dependent: dense vs moe mlp, qkv biases) — a hardcoded key list
    # here broke every non-llama family under pp
    layer_spec = jax.tree.map(
        lambda _: P("pp"), layer_param_template(cfg),
        is_leaf=lambda x: x is None,
    )
    other_keys = ["embed_tokens", "norm"] + ([] if tied else ["lm_head"])
    other_spec = {k: P() for k in other_keys}

    PP = mesh.shape["pp"]

    def body(layers_stage, other, toks):
        # layers_stage: this device's [1, per, ...] slice (squeeze below)
        B, T = toks.shape
        p = lax.axis_index("pp")
        mask = jnp.broadcast_to(
            causal_mask(T)[None], (B // M, T, T)
        )
        positions = jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (B // M, T)
        )
        cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
        per = jax.tree.leaves(layers_stage)[0].shape[1]

        def run_stage(x):
            def layer_step(x, i):
                layer = jax.tree.map(lambda a: a[0, i], layers_stage)
                x, _ = decoder_layer(layer, x, cos, sin, mask, cfg)
                return x, ()

            x, _ = lax.scan(layer_step, x, jnp.arange(per))
            return x

        def embed(mb):
            x = other["embed_tokens"][mb]
            if cfg.scale_embeddings:  # gemma residual-stream scaling
                x = x * jnp.asarray(
                    float(cfg.hidden_size) ** 0.5, x.dtype
                )
            return x

        mbs = toks.reshape(M, B // M, T)
        H = cfg.hidden_size
        perm_fwd = [(i, (i + 1) % PP) for i in range(PP)]

        # pcast to 'varying': carries start as invariant zeros but hold
        # device-varying values after the first tick (shard_map scan
        # manual-axes typing, as in ring_attention.py)
        buf = pcast(
            jnp.zeros((B // M, T, H), other["norm"].dtype),
            ("pp",), to="varying",
        )  # inbound activation from the previous stage
        # carry ACTIVATIONS, not logits: a vocab-sized carry would be
        # ~16-32x bigger for real models, and projecting per tick would
        # run the model's largest matmul PP*(M+PP-1) times instead of
        # once post-scan
        acts = pcast(
            jnp.zeros((M, B // M, T, H), other["norm"].dtype),
            ("pp",), to="varying",
        )

        def tick(carry, t):
            buf, acts = carry
            # stage 0 injects microbatch t (if still filling)
            x_in = jnp.where(
                (p == 0) & (t < M),
                embed(mbs[jnp.clip(t, 0, M - 1)]).astype(buf.dtype),
                buf,
            )
            x_out = run_stage(x_in)
            # last stage records microbatch (t - PP + 1) when valid
            emit_idx = t - (PP - 1)
            acts = jnp.where(
                (p == PP - 1) & (emit_idx >= 0),
                acts.at[jnp.clip(emit_idx, 0, M - 1)].set(x_out),
                acts,
            )
            buf = lax.ppermute(x_out, "pp", perm_fwd)
            return (buf, acts), ()

        (buf, acts), _ = lax.scan(
            tick, (buf, acts), jnp.arange(M + PP - 1)
        )
        # only the last stage holds real activations; broadcast, then
        # norm + head ONCE over the full batch
        acts = lax.psum(jnp.where(p == PP - 1, acts, 0.0), "pp")
        x = rms_norm(
            acts.reshape(B, T, H), other["norm"], cfg.rms_norm_eps,
            offset=cfg.rmsnorm_offset,
        )
        h = (
            other["embed_tokens"].T
            if cfg.tie_word_embeddings
            else other["lm_head"]
        )
        return (x @ h).astype(jnp.float32)

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(layer_spec, other_spec, P()),
            out_specs=P(),
        )
    )


def pipeline_forward(
    params: Params,
    tokens: jax.Array,  # i32[B, T]
    cfg: ModelConfig,
    mesh: Mesh,
    n_microbatches: int = 4,
) -> jax.Array:
    """Causal-LM logits with layers pipelined over the mesh's ``pp`` axis.

    ``B`` must divide by ``n_microbatches``. Numerically identical to the
    dense forward (parity-tested); only the schedule differs. Hot loops
    should call ``stack_stage_params`` once and invoke the memoized
    ``_pp_fn(cfg, mesh, M, tied)`` result directly — this convenience
    wrapper re-stacks the layer tree (a device copy) every call.
    """
    B, _ = tokens.shape
    if B % n_microbatches:
        raise ValueError(
            f"batch {B} must divide into {n_microbatches} microbatches"
        )
    stacked = stack_stage_params(params, mesh.shape["pp"])
    other = {k: v for k, v in stacked.items() if k != "layers"}
    fwd = _pp_fn(cfg, mesh, n_microbatches, cfg.tie_word_embeddings)
    return fwd(stacked["layers"], other, tokens)


def make_pp_mesh(pp: int) -> Mesh:
    from kubeinfer_tpu.inference.sharding import make_axis_mesh

    return make_axis_mesh("pp", pp)
