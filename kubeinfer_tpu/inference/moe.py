"""Mixture-of-experts block with expert parallelism over an ``ep`` axis.

The reference has no parallelism of its own at all (SURVEY.md §2); the
native engine's MoE tier adds the last member of the dp/tp/sp/pp/ep
family. Design: a top-k softmax router and E SwiGLU experts. Under
expert parallelism each device holds E/ep experts (the expert-stacked
weights shard on their leading axis), computes its local experts'
weighted contribution for the full token set, and a single ``psum``
combines — no token all-to-all, which at this scale costs more than it
saves (the all-to-all dispatch becomes worthwhile when E and token
counts are large enough that compute dominates the replicated-token
waste; the psum form is the correct-first baseline the scaling book
recommends starting from).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from kubeinfer_tpu.utils.jaxcompat import shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

Params = dict


def init_moe_params(
    key: jax.Array,
    hidden: int,
    ffn: int,
    n_experts: int,
    dtype=jnp.float32,
) -> Params:
    ks = jax.random.split(key, 4)

    def dense(k, shape):
        return (0.02 * jax.random.normal(k, shape, jnp.float32)).astype(dtype)

    return {
        "router": dense(ks[0], (hidden, n_experts)),
        # expert-stacked [E, ...]: the leading axis shards over ep
        "gate_proj": dense(ks[1], (n_experts, hidden, ffn)),
        "up_proj": dense(ks[2], (n_experts, hidden, ffn)),
        "down_proj": dense(ks[3], (n_experts, ffn, hidden)),
    }


def _router_weights(params: Params, x: jax.Array, top_k: int):
    """[B, T, E] routing weights: softmax over the top-k expert logits,
    zero elsewhere (standard switch/mixtral routing)."""
    logits = (
        x.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    )  # [B, T, E]
    E = logits.shape[-1]
    top_vals, _ = lax.top_k(logits, top_k)
    thresh = top_vals[..., -1:]
    masked = jnp.where(logits >= thresh, logits, -jnp.inf)
    return jax.nn.softmax(masked, axis=-1)  # zeros off the top-k


def moe_block(params: Params, x: jax.Array, top_k: int = 2) -> jax.Array:
    """Dense reference implementation: every expert sees every token."""
    w = _router_weights(params, x, top_k)  # [B, T, E]
    gate = jax.nn.silu(jnp.einsum("bth,ehf->betf", x, params["gate_proj"]))
    up = jnp.einsum("bth,ehf->betf", x, params["up_proj"])
    y = jnp.einsum("betf,efh->beth", gate * up, params["down_proj"])
    return jnp.einsum("beth,bte->bth", y, w.astype(x.dtype))



@functools.cache
def _ep_fn(mesh: Mesh, top_k: int):
    """Memoized jitted shard_map per (mesh, top_k) — building it inside
    moe_block_ep would defeat the jit cache and recompile every call."""

    def body(p_local, x_full):
        r = lax.axis_index("ep")
        E_local = p_local["gate_proj"].shape[0]
        # router weights need ALL experts' logits: router is replicated
        w = _router_weights(
            {"router": p_local["router"]}, x_full,
            top_k,
        )  # [B, T, E_total]
        w_local = lax.dynamic_slice_in_dim(
            w, r * E_local, E_local, axis=2
        )
        gate = jax.nn.silu(
            jnp.einsum("bth,ehf->betf", x_full, p_local["gate_proj"])
        )
        up = jnp.einsum("bth,ehf->betf", x_full, p_local["up_proj"])
        y = jnp.einsum("betf,efh->beth", gate * up, p_local["down_proj"])
        out = jnp.einsum("beth,bte->bth", y, w_local.astype(x_full.dtype))
        return lax.psum(out, "ep")

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(
                {
                    "router": P(),  # replicated: routing needs all logits
                    "gate_proj": P("ep"),
                    "up_proj": P("ep"),
                    "down_proj": P("ep"),
                },
                P(),
            ),
            out_specs=P(),
        )
    )


def moe_block_ep(
    params: Params, x: jax.Array, mesh: Mesh, top_k: int = 2
) -> jax.Array:
    """Expert-parallel form: experts shard over ``ep``, outputs psum.

    Bit-compatible with ``moe_block`` up to reduction order
    (parity-tested to fp tolerance).
    """
    return _ep_fn(mesh, top_k)(params, x)


def make_ep_mesh(ep: int) -> Mesh:
    from kubeinfer_tpu.inference.sharding import make_axis_mesh

    return make_axis_mesh("ep", ep)
