"""OpenAI-compatible HTTP server over the native engine.

``python -m kubeinfer_tpu.inference.server`` accepts the SAME CLI surface
the agent's runtime launcher builds for vLLM (runtime.py build_args —
--model/--host/--port/--tensor-parallel-size/--dtype/[--max-model-len]),
so switching a workload to the native TPU runtime is just
``RUNTIME_KIND=native`` (or ``runtime: native`` in the LLMService spec) —
lifecycle code is untouched.

Endpoints (the surface the reference's mock pins, testdata
vllm-mock/mock_server.py, plus real generation):

- ``GET  /health``            → OK
- ``GET  /v1/models``         → OpenAI-style model list
- ``POST /v1/completions``    → {model, prompt: str|[int], max_tokens,
                                temperature, seed} → completion

String prompts need tokenizer files next to the weights (loaded via
``transformers`` AutoTokenizer); token-id prompts always work (and are
what the tests and the e2e slice use). ``--random-init`` serves a
randomly initialized preset config — the demo/e2e mode that needs no
weights and no network, the role the reference's vllm-mock image plays,
except it really generates.

Reproducibility contract: a completion is a deterministic function of
(prompt, seed, sampling params) — independent of what else is in
flight. Greedy requests are trivially so; sampled requests hold it
because the batcher's draft groups only join sampled requests with
EQUAL seeds (batching.py _drain_spec_group — the group key stream is
seeded by the head request, so a different-seed join would silently
sample from the head's stream), and slot-path sampling keys are
per-slot, derived from each request's own seed.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading
import time
from http.server import ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from kubeinfer_tpu.analysis.racecheck import make_lock
from kubeinfer_tpu.metrics.registry import (
    Counter, Gauge, Histogram, Registry,
)
from kubeinfer_tpu.observability import tracing
from kubeinfer_tpu.observability.slo import SLOMonitor
from kubeinfer_tpu.utils.httpbase import BaseEndpointHandler, token_matches

log = logging.getLogger(__name__)

_TRACER = tracing.get_tracer("inference-server")


def _serving_metrics(registry: Registry):
    """Serving-side collectors (vLLM exposes the equivalents; the
    control plane's collector set lives in metrics/registry.py — these
    are per-inference-server and ride its own /metrics endpoint)."""
    return {
        "requests": Counter(
            "kubeinfer_inference_requests_total",
            "Completion requests by outcome and decode route",
            labels=("route", "outcome"), registry=registry,
        ),
        "prompt_tokens": Counter(
            "kubeinfer_inference_prompt_tokens_total",
            "Prompt tokens received", registry=registry,
        ),
        "completion_tokens": Counter(
            "kubeinfer_inference_completion_tokens_total",
            "Tokens generated", registry=registry,
        ),
        "latency": Histogram(
            "kubeinfer_inference_request_seconds",
            "End-to-end completion latency",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0),
            labels=("route",), registry=registry,
        ),
        # per-request latency breakdown (the vLLM request-metrics plane
        # equivalents): TTFT/queue-wait come from the batcher's own
        # request timeline when the continuous route served the request
        # (t_submit/t_admit/t_first, batching.py _Request); routes with
        # no internal timeline degrade to end-to-end figures — same
        # family, split by the route label
        "ttft": Histogram(
            "kubeinfer_inference_ttft_seconds",
            "Time to first generated token (queue wait + admission + "
            "prefill on the continuous route; end-to-end elsewhere)",
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                     10.0, 30.0),
            labels=("route",), registry=registry,
        ),
        "tpot": Histogram(
            "kubeinfer_inference_time_per_output_token_seconds",
            "Mean decode time per generated token after the first",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0),
            labels=("route",), registry=registry,
        ),
        "queue_wait": Histogram(
            "kubeinfer_inference_queue_wait_seconds",
            "Submit-to-admission wait in the continuous batcher",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                     30.0),
            labels=("route",), registry=registry,
        ),
        # speculation effectiveness (r4 verdict weak #3 follow-through:
        # "spec_served stays flat exactly when throughput matters" must
        # be OBSERVABLE, not just fixed) — refreshed from the batcher's
        # counters at scrape time
        "spec_served": Gauge(
            "kubeinfer_inference_spec_served_requests",
            "Requests served via speculative draft groups",
            registry=registry,
        ),
        "spec_accepted": Gauge(
            "kubeinfer_inference_spec_accepted_drafts",
            "Draft tokens accepted by the target across all groups",
            registry=registry,
        ),
        # paged speculative decoding (batching.py verify windows, gated
        # by --speculative-draft): the engine's monotonic ints convert
        # to Prometheus counters by delta at scrape time under the kv
        # lock, same discipline as the radix counters below; the ratio
        # gauge is cumulative accepted/proposed so dashboards read the
        # acceptance rate without a PromQL rate-quotient
        "spec_draft_tokens": Counter(
            "kubeinfer_spec_draft_tokens_total",
            "Draft tokens proposed by paged verify windows",
            registry=registry,
        ),
        "spec_accepted_tokens": Counter(
            "kubeinfer_spec_accepted_tokens_total",
            "Proposed draft tokens the target accepted at a window "
            "boundary",
            registry=registry,
        ),
        "spec_rollbacks": Counter(
            "kubeinfer_spec_rollbacks_total",
            "Verify windows that rejected at least one draft token "
            "for some row",
            registry=registry,
        ),
        "spec_acceptance_ratio": Gauge(
            "kubeinfer_spec_acceptance_ratio",
            "Cumulative accepted/proposed draft tokens (0 until the "
            "first window)",
            registry=registry,
        ),
        # paged-KV pool + radix prefix cache (batching.kv_cache_stats):
        # gauges snapshot pool occupancy; the cache counters are
        # Prometheus counters fed by delta at scrape time so restarts
        # of the batcher never make them go backwards mid-series
        "kv_blocks_in_use": Gauge(
            "kubeinfer_kv_blocks_in_use",
            "KV pool blocks referenced by live slots or the prefix cache",
            registry=registry,
        ),
        "kv_blocks_free": Gauge(
            "kubeinfer_kv_blocks_free",
            "KV pool blocks on the free list",
            registry=registry,
        ),
        # device layout of the continuous batcher (sharding.EngineLayout):
        # capacity dashboards need the tp degree next to the pool gauges —
        # a tp=4 replica's blocks_in_use counts LOGICAL blocks whose bytes
        # are split 4 ways, so per-device headroom math divides by tp
        "tp_degree": Gauge(
            "kubeinfer_engine_tp_degree",
            "Tensor-parallel degree of the serving engine's device "
            "layout (1 = unsharded)",
            registry=registry,
        ),
        "mesh_devices": Gauge(
            "kubeinfer_mesh_devices",
            "Devices in the serving mesh (1 when unsharded)",
            registry=registry,
        ),
        "kv_shard_blocks_in_use": Gauge(
            "kubeinfer_kv_shard_blocks_in_use",
            "KV pool blocks referenced per tensor-parallel shard; block "
            "indices are logical, so every shard references the same "
            "block set and holds n_kv/tp heads of each",
            labels=("shard",), registry=registry,
        ),
        "prefix_hits": Counter(
            "kubeinfer_prefix_cache_hits_total",
            "Admits that reused >= 1 cached prefix block",
            registry=registry,
        ),
        "prefix_misses": Counter(
            "kubeinfer_prefix_cache_misses_total",
            "Admits that prefilled from token 0",
            registry=registry,
        ),
        "prefix_evictions": Counter(
            "kubeinfer_prefix_cache_evictions_total",
            "Radix-cache nodes evicted (LRU) to free pool blocks",
            registry=registry,
        ),
        # step-level engine efficiency (batching.StepProfiler): goodput
        # separates "tokens the device produced for someone" from the
        # padded work static shapes force; occupancy/padding-waste say
        # WHY goodput moved (empty slots vs bucket padding). Gauges
        # snapshot the profiler's sliding-window summary at scrape time.
        "goodput": Gauge(
            "kubeinfer_engine_goodput_tokens_per_second",
            "Live (non-padding) tokens produced per second, sliding "
            "window over profiler steps",
            registry=registry,
        ),
        "occupancy": Gauge(
            "kubeinfer_engine_batch_occupancy",
            "Mean live-rows / n_slots over recent decode dispatches",
            registry=registry,
        ),
        "padding_waste": Gauge(
            "kubeinfer_engine_padding_waste_frac",
            "Padded / (live + padded) tokens over recent dispatches",
            registry=registry,
        ),
        "queue_depth": Gauge(
            "kubeinfer_engine_queue_depth",
            "Requests waiting for a slot (submit queue + holdover)",
            registry=registry,
        ),
        "step_duration": Histogram(
            "kubeinfer_engine_step_duration_seconds",
            "Device dispatch wall time by phase (prefill/decode/spec)",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 5.0, 30.0),
            labels=("phase",), registry=registry,
        ),
        "compiles": Counter(
            "kubeinfer_engine_compiles_total",
            "Device dispatches that hit a first-seen (phase, bucket) "
            "shape (jit compile proxy)",
            registry=registry,
        ),
        # preemptive scheduling + chunked prefill (batching.py): the
        # monotonic engine counters convert by delta at scrape time
        # like the radix counters above; the depth gauges snapshot the
        # scheduler's instantaneous backlog
        "preemptions": Counter(
            "kubeinfer_preemptions_total",
            "Decoding rows parked (blocks cached to the radix trie) to "
            "admit an SLO-pressured waiter",
            registry=registry,
        ),
        "resumes": Counter(
            "kubeinfer_preemption_resumes_total",
            "Parked rows readmitted (radix warm-resume)",
            registry=registry,
        ),
        "chunks": Counter(
            "kubeinfer_prefill_chunks_total",
            "Intermediate chunked-prefill dispatches (excludes the "
            "finalizing bucket dispatch)",
            registry=registry,
        ),
        "chunk_queue": Gauge(
            "kubeinfer_prefill_chunk_queue_depth",
            "Chunked prefills in flight (slot reserved, row not yet "
            "decoding)",
            registry=registry,
        ),
        "parked": Gauge(
            "kubeinfer_parked_requests",
            "Preempted requests awaiting readmission",
            registry=registry,
        ),
        # SLO burn rates (observability/slo.py): burn 1.0 = spending
        # budget exactly at the sustainable rate; the window label keeps
        # the short/long pair an alerting rule needs in one series
        "slo_burn": Gauge(
            "kubeinfer_slo_burn_rate",
            "Error-budget burn rate per objective and window",
            labels=("slo", "window"), registry=registry,
        ),
        "slo_budget": Gauge(
            "kubeinfer_slo_budget_remaining",
            "Signed remaining budget fraction over the longest window",
            labels=("slo",), registry=registry,
        ),
        # disaggregated prefill/decode (disagg/): the KV transfer plane
        # observed from BOTH ends — direction=export counts blocks/bytes
        # served at /kv/blocks, direction=import counts blocks/bytes
        # landed via ContinuousEngine.import_prefix; fallbacks are the
        # paths that degraded to local prefill (token-identical, so a
        # fallback is a latency event, never a correctness one)
        "kv_stream_blocks": Counter(
            "kubeinfer_kv_stream_blocks_total",
            "KV blocks streamed over the transfer plane",
            labels=("direction",), registry=registry,
        ),
        "kv_stream_bytes": Counter(
            "kubeinfer_kv_stream_bytes_total",
            "Wire bytes streamed over the KV transfer plane",
            labels=("direction",), registry=registry,
        ),
        "kv_stream_seconds": Histogram(
            "kubeinfer_kv_stream_seconds",
            "KV transfer-plane operation latency (export = serve the "
            "blob; import = fetch + verify + scatter)",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0),
            labels=("direction",), registry=registry,
        ),
        "disagg_fallbacks": Counter(
            "kubeinfer_disagg_fallbacks_total",
            "Disaggregated-prefill requests that fell back to local "
            "prefill, by reason",
            labels=("reason",), registry=registry,
        ),
        "kv_pool_bytes": Gauge(
            "kubeinfer_kv_pool_bytes",
            "Resident bytes of the paged KV pool (pages + quant scales "
            "+ bf16 tail buffers), summed across the mesh",
            registry=registry,
        ),
        "model_param_bytes": Gauge(
            "kubeinfer_model_param_bytes",
            "Resident bytes of the model parameters (int8 pages + f32 "
            "scale planes under weight_dtype=int8), summed across the "
            "mesh",
            registry=registry,
        ),
        "requests_shed": Counter(
            "kubeinfer_requests_shed_total",
            "Completion requests refused at the admission door, by "
            "reason (queue_depth_limit = graceful load shedding; the "
            "client got 503 + Retry-After, never a queue slot)",
            labels=("reason",), registry=registry,
        ),
        "kv_quant_blocks": Counter(
            "kubeinfer_kv_quant_blocks_total",
            "KV blocks quantized to int8 on commit (admit-time fills "
            "plus decode/verify boundary crossings; imports excluded)",
            registry=registry,
        ),
        # live-session migration (drain/evacuate/rebalance): sessions
        # handed off with a resume prefix, chunks streamed while decode
        # continued, and the export-cache evictions that tell an
        # operator a slow importer is losing blobs between chunks.
        # Fallbacks are the paths that degraded to (partial) re-prefill
        # — token-identical by the determinism contract, so every one
        # is a latency event, never a correctness one.
        "migrations": Counter(
            "kubeinfer_migrations_total",
            "Live sessions completed as migrated (drain handed them to "
            "the router with a resume prefix)",
            registry=registry,
        ),
        "migration_chunks": Counter(
            "kubeinfer_migration_chunks_total",
            "KV chunks streamed out by drain passes while decode "
            "continued on the source",
            registry=registry,
        ),
        "migration_fallbacks": Counter(
            "kubeinfer_migration_fallbacks_total",
            "Migration resume paths that degraded to (partial) local "
            "re-prefill, by reason",
            labels=("reason",), registry=registry,
        ),
        "kv_export_evictions": Counter(
            "kubeinfer_kv_export_evictions_total",
            "Export-cache blobs evicted (entry cap or bytes budget) "
            "before being pulled",
            registry=registry,
        ),
        "draining": Gauge(
            "kubeinfer_engine_draining_state",
            "1 while the engine refuses new admissions (drain in "
            "progress)",
            registry=registry,
        ),
    }


class InferenceServer:
    def __init__(self, engine, model_id: str, tokenizer=None,
                 host: str = "127.0.0.1", port: int = 8000,
                 continuous=None, speculative=None, sp=None,
                 tls_cert: str = "", tls_key: str = "",
                 token: str = "", slo=None,
                 kv_export_budget_mb: float = 0.0) -> None:
        self.engine = engine
        self.continuous = continuous  # ContinuousEngine | None
        self.speculative = speculative  # SpeculativeEngine | None
        self.sp = sp  # SPEngine | None (sequence-parallel long prompts)
        self.model_id = model_id
        self.tokenizer = tokenizer
        # bearer token guarding /debug/* only: traces and flight
        # recorder dumps carry prompt lengths and scheduling detail,
        # /metrics stays open like every scrape target. Empty = open
        # (tests, pod-network-only deployments) — same contract as the
        # store's debug endpoints (httpstore.py).
        self._token = token
        self.slo = slo if slo is not None else SLOMonitor()
        self.registry = Registry()
        self.metrics = _serving_metrics(self.registry)
        # disaggregated-prefill export staging (disagg/export.py):
        # prefill-only completions park their wire-encoded KV here,
        # keyed by deepest prefix fingerprint, until a decode replica
        # pulls it from /kv/blocks. Only meaningful with a continuous
        # engine (the paged pool is what gets exported).
        self.kv_exports = None
        if continuous is not None:
            from kubeinfer_tpu.disagg.export import KVExportCache

            # --kv-export-budget-mb: migration chunks are much larger
            # than prefill exports, so the cache is byte-bounded too
            # (0 = entry cap only, the pre-migration behavior)
            self.kv_exports = KVExportCache(
                max_bytes=(
                    int(kv_export_budget_mb * (1 << 20))
                    if kv_export_budget_mb > 0 else None
                ),
            )
            # live-session migration: the engine's drain pass streams
            # committed-KV chunks through this sink (scheduler thread,
            # off the engine lock); they land in the same export cache
            # /kv/blocks already serves, keyed by each chunk's own
            # deepest fingerprint — the target's chunked importer needs
            # no new endpoint
            continuous.migration_sink = self._export_migration_chunk
            # fleet identity: engine spans inherit this server's
            # model_id unless the engine was already named — model_id
            # is the name the router registers the replica under, so
            # fleetview's per-replica attribution lines up across the
            # server and engine halves of one hop
            if getattr(continuous, "replica_name", None) is None:
                continuous.replica_name = model_id
        # last-seen monotonic kv_cache_stats counters, for the
        # delta-to-Counter conversion at scrape time; guarded because
        # ThreadingHTTPServer can run concurrent /metrics scrapes
        self._kv_last: dict[str, int] = {}
        # profiler replay cursor: each step record feeds the duration
        # histogram exactly once across concurrent scrapes
        self._prof_seq = -1
        self._kv_lock = make_lock("server.InferenceServer._kv_lock")
        server = self

        class Handler(BaseEndpointHandler):
            def _authed(self) -> bool:
                if not server._token:
                    return True
                got = self.headers.get("Authorization", "")
                return token_matches(got, server._token)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path.startswith("/debug/") and not self._authed():
                    self.respond(401, "application/json",
                                 json.dumps({"error": "unauthorized"}))
                    return
                if path == "/health":
                    self.respond(200, "text/plain", "OK")
                elif path == "/metrics":
                    server._refresh_spec_metrics()
                    # unauthenticated by design: the inference server
                    # binds inside the pod network; the manager's
                    # token-guarded endpoint is the cluster-facing one
                    self.respond(
                        200, "text/plain; version=0.0.4",
                        server.registry.render(),
                    )
                elif path == "/v1/models":
                    self.respond(200, "application/json", json.dumps({
                        "object": "list",
                        "data": [{
                            "id": server.model_id,
                            "object": "model",
                            "owned_by": "kubeinfer-tpu",
                        }],
                    }))
                elif path == "/debug/spans":
                    # recorded spans as Chrome trace-event JSON —
                    # save the body and open it in Perfetto
                    # (docs/OBSERVABILITY.md); ?trace_id= narrows to
                    # one request's trace. Engine counter tracks
                    # (occupancy / queue depth / kv blocks) merge in as
                    # their own process group so the curves render next
                    # to the span timeline.
                    q = parse_qs(urlparse(self.path).query)
                    tid = (q.get("trace_id") or [None])[0]
                    doc = tracing.RECORDER.to_chrome_trace(tid)
                    server._merge_counter_tracks(doc)
                    self.respond(
                        200, "application/json", json.dumps(doc),
                    )
                elif path == "/cache/summary":
                    # unauthenticated like /metrics: fingerprints are
                    # one-way hashes of block keys — no prompt content
                    # is recoverable — and the fleet router polls this
                    # from inside the pod network
                    serving = (
                        server.continuous.stats_summary()
                        if server.continuous is not None else {}
                    )
                    self.respond(200, "application/json", json.dumps({
                        "model": server.model_id,
                        "serving": serving,
                    }))
                elif path == "/kv/blocks":
                    # disaggregated-prefill transfer plane: serve one
                    # exported prefix by content address (deepest
                    # rolling fingerprint). Unauthenticated like
                    # /cache/summary — the fleet's pod network — and
                    # self-verifying on the wire (sha256 in the header,
                    # wire.py), so a torn read never reaches a pool.
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        fp = int((q.get("fp") or [""])[0])
                    except ValueError:
                        self.respond(400, "application/json", json.dumps(
                            {"error": "fp must be an integer fingerprint"}
                        ))
                        return
                    blob = (
                        server.kv_exports.get(fp)
                        if server.kv_exports is not None else None
                    )
                    if blob is None:
                        # evicted from the export LRU (or never made):
                        # the importer falls back to local prefill
                        self.respond(404, "application/json", json.dumps(
                            {"error": "no export for fingerprint"}
                        ))
                        return
                    try:
                        hdr = json.loads(blob[:blob.find(b"\n")])
                        nblocks = int(hdr.get("blocks", 0))
                    except ValueError:
                        nblocks = 0
                    # count BEFORE the socket write: the importer's very
                    # next request may scrape /metrics, and the counters
                    # must already reflect the blob it just received
                    server.metrics["kv_stream_blocks"].inc(
                        "export", by=nblocks
                    )
                    server.metrics["kv_stream_bytes"].inc(
                        "export", by=len(blob)
                    )
                    t0 = time.perf_counter()
                    self.respond(200, "application/octet-stream", blob)
                    server.metrics["kv_stream_seconds"].observe(
                        "export", time.perf_counter() - t0
                    )
                elif path == "/debug/flightrecorder":
                    # ?since= is an exactly-once cursor (events with
                    # seq > since only), same contract as
                    # StepProfiler.snapshot: a long-run drainer passes
                    # its last-seen seq each poll instead of refetching
                    # (and re-counting) the whole ring
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        since = int((q.get("since") or ["-1"])[0])
                    except ValueError:
                        self.respond(400, "application/json", json.dumps(
                            {"error": "since must be an integer seq"}
                        ))
                        return
                    fl = (server.continuous.flight.to_dict(since)
                          if server.continuous is not None
                          else {"capacity": 0, "recorded": 0,
                                "events": []})
                    self.respond(200, "application/json", json.dumps(fl))
                elif path == "/debug/slo":
                    self.respond(
                        200, "application/json",
                        json.dumps(server.slo.snapshot()),
                    )
                else:
                    self.respond(404, "text/plain", "not found\n")

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                if path == "/admin/drain":
                    # guarded like /debug/*: draining is disruptive (a
                    # replica stops admitting), so it shares the bearer
                    # token; empty token = open, same contract
                    if not self._authed():
                        self.respond(401, "application/json",
                                     json.dumps({"error": "unauthorized"}))
                        return
                    try:
                        body = json.loads(raw or b"{}")
                    except ValueError:
                        body = {}
                    if not isinstance(body, dict):
                        body = {}
                    try:
                        resp = server.drain(
                            resume=bool(body.get("resume", False)),
                            timeout_s=float(body.get("timeout_s", 30.0)),
                        )
                    except ValueError as e:
                        self.respond(400, "application/json", json.dumps(
                            {"error": {"message": str(e),
                                       "type": "invalid_request_error"}}
                        ))
                        return
                    self.respond(200, "application/json",
                                 json.dumps(resp))
                    return
                if path != "/v1/completions":
                    self.respond(404, "text/plain", "not found\n")
                    return
                # server-side span joins the caller's trace when a
                # traceparent header arrived; otherwise this request
                # starts a fresh trace
                with _TRACER.span(
                    "http POST /v1/completions",
                    parent=self.trace_context(),
                ) as sp:
                    try:
                        try:
                            body = json.loads(raw or b"{}")
                        except ValueError:
                            # malformed JSON never reaches complete(); count
                            # it here or a flood of garbage 400s shows zero
                            # in requests_total
                            server.metrics["requests"].inc("invalid", "invalid")
                            raise
                        resp = server.complete(body)
                        sp.set(status=200)
                        self.respond(200, "application/json", json.dumps(resp))
                    except ValueError as e:
                        sp.set(status=400)
                        self.respond(400, "application/json", json.dumps(
                            {"error": {"message": str(e), "type": "invalid_request_error"}}
                        ))
                    except Exception as e:  # keep the serving thread alive
                        if server._is_overload_error(e):
                            # graceful load shedding: valid request, no
                            # queue room — 503 with a Retry-After hint
                            # so well-behaved clients back off instead
                            # of hammering the door
                            sp.set(status=503)
                            self.respond(
                                503, "application/json",
                                json.dumps({"error": {
                                    "message": str(e),
                                    "type": "overloaded",
                                }}),
                                headers={"Retry-After": str(max(
                                    1, int(getattr(
                                        e, "retry_after_s", 1.0))))},
                            )
                            return
                        if server._is_draining_error(e):
                            # the request is valid; THIS replica just
                            # won't take it — 503 with a typed body so
                            # the router marks the replica draining and
                            # routes elsewhere instead of relaying an
                            # error to the client
                            sp.set(status=503)
                            self.respond(503, "application/json", json.dumps(
                                {"error": {"message": str(e), "type": "draining"}}
                            ))
                            return
                        log.exception("completion failed")
                        sp.set(status=500)
                        self.respond(500, "application/json", json.dumps(
                            {"error": {"message": str(e), "type": "server_error"}}
                        ))

        from kubeinfer_tpu.utils.httpbase import wrap_server_tls

        self._httpd = wrap_server_tls(
            ThreadingHTTPServer((host, port), Handler), tls_cert, tls_key
        )
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # -- request handling --------------------------------------------------

    def _encode(self, prompt) -> list[int]:
        if isinstance(prompt, list):
            if not all(isinstance(t, int) for t in prompt):
                raise ValueError("prompt list must contain token ids (ints)")
            return prompt
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "string prompts require tokenizer files next to the "
                    "model weights; this server was started without them — "
                    "send token ids instead"
                )
            return self.tokenizer.encode(prompt)
        raise ValueError("prompt must be a string or a list of token ids")

    def _decode(self, ids: list[int]) -> str:
        if self.tokenizer is None:
            return " ".join(str(i) for i in ids)
        return self.tokenizer.decode(ids)

    def _refresh_spec_metrics(self) -> None:
        """Scrape-time refresh of the speculation gauges and the
        paged-KV collectors from the batcher's counters (they mutate in
        the scheduler thread; gauges snapshot rather than double-count,
        and the monotonic radix counters convert to Prometheus counters
        by delta under _kv_lock so concurrent scrapes never double-add).
        SLO gauges refresh even without a continuous engine — every
        route feeds _observe_breakdown, so the burn rates are
        meaningful for per-request/speculative-only servers too."""
        snap = self.slo.snapshot()
        for name, obj in snap["objectives"].items():
            for w, d in obj["windows"].items():
                self.metrics["slo_burn"].set(name, f"{w}s", d["burn_rate"])
            self.metrics["slo_budget"].set(name, obj["budget_remaining"])
        if self.continuous is None:
            return
        self.metrics["spec_served"].set(self.continuous.spec_served)
        self.metrics["spec_accepted"].set(self.continuous.spec_accepted)
        stats = self.continuous.kv_cache_stats()
        self.metrics["kv_blocks_in_use"].set(stats["blocks_in_use"])
        self.metrics["kv_blocks_free"].set(stats["blocks_free"])
        self.metrics["kv_pool_bytes"].set(stats["pool_bytes"])
        self.metrics["model_param_bytes"].set(
            self.continuous.model_param_bytes
        )
        layout = self.continuous.layout
        self.metrics["tp_degree"].set(layout.tp)
        self.metrics["mesh_devices"].set(layout.mesh_devices)
        # one series per shard, all reporting the same logical count:
        # the pool's bookkeeping is layout-agnostic (kv_blocks.py), so a
        # shard's referenced-block set IS the pool's — the per-shard
        # fan-out exists so dashboards aggregating by device see the
        # sharded pool instead of inferring it from tp_degree
        for shard in range(layout.tp):
            self.metrics["kv_shard_blocks_in_use"].set(
                str(shard), stats["blocks_in_use"]
            )
        summary = self.continuous.stats_summary()
        self.metrics["goodput"].set(summary["goodput_tokens_per_sec"])
        self.metrics["occupancy"].set(summary["batch_occupancy"])
        self.metrics["padding_waste"].set(summary["padding_waste_frac"])
        self.metrics["queue_depth"].set(summary["queue_depth"])
        self.metrics["draining"].set(
            1.0 if summary.get("draining") else 0.0
        )
        sched = self.continuous.scheduler_stats()
        self.metrics["chunk_queue"].set(sched["chunk_queue"])
        self.metrics["parked"].set(sched["parked"])
        with self._kv_lock:
            for key, name in (
                ("hits", "prefix_hits"),
                ("misses", "prefix_misses"),
                ("evictions", "prefix_evictions"),
                ("quant_blocks", "kv_quant_blocks"),
            ):
                delta = stats[key] - self._kv_last.get(key, 0)
                # unconditional inc: a zero delta still materializes
                # the sample, so the series exists (at 0) from the
                # first scrape rather than popping into existence on
                # its first event
                self.metrics[name].inc(by=delta)
                self._kv_last[key] = stats[key]
            # scheduler counters ride the same delta-to-Counter
            # conversion (the engine's ints are monotonic per process;
            # _kv_last keys are disjoint from the radix ones)
            for key, name in (
                ("preempted", "preemptions"),
                ("resumed", "resumes"),
                ("chunks", "chunks"),
                ("spec_draft_tokens", "spec_draft_tokens"),
                ("spec_accepted_tokens", "spec_accepted_tokens"),
                ("spec_rollbacks", "spec_rollbacks"),
                ("migrated", "migrations"),
                ("migration_chunks", "migration_chunks"),
            ):
                delta = sched[key] - self._kv_last.get(key, 0)
                self.metrics[name].inc(by=delta)
                self._kv_last[key] = sched[key]
            if self.kv_exports is not None:
                # export-cache evictions ride the same delta-to-Counter
                # conversion (the cache's int is monotonic per process)
                ev = self.kv_exports.stats()["evictions"]
                self.metrics["kv_export_evictions"].inc(
                    by=ev - self._kv_last.get("export_evictions", 0)
                )
                self._kv_last["export_evictions"] = ev
            # ratio from the cumulative ints, not the deltas: a scrape
            # landing between windows would otherwise read 0/0 and
            # flap the gauge to zero
            self.metrics["spec_acceptance_ratio"].set(
                sched["spec_accepted_tokens"]
                / max(sched["spec_draft_tokens"], 1)
            )
            # profiler replay under the same lock: the cursor advance
            # and the histogram observes must be atomic per scrape or a
            # concurrent scrape double-counts the same step records
            self.metrics["compiles"].inc(by=0)
            recs = self.continuous.profiler.snapshot(
                since_seq=self._prof_seq
            )
            for r in recs:
                self.metrics["step_duration"].observe(r.phase, r.dur_s)
                if r.compiled:
                    self.metrics["compiles"].inc()
            if recs:
                self._prof_seq = recs[-1].seq

    def _merge_counter_tracks(self, doc: dict) -> None:
        """Append the engine's counter tracks (batch occupancy, padded
        tokens, queue depth, kv blocks) to a Chrome trace doc as one
        extra process group, so Perfetto shows the efficiency curves
        under the span timeline they explain. No-op without a
        continuous engine."""
        if self.continuous is None:
            return
        events = doc.get("traceEvents", [])
        pid = max((e.get("pid", 0) for e in events), default=0) + 1
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": "engine-counters"},
        })
        events.extend(self.continuous.profiler.counter_events(pid))
        events.extend(self.continuous.flight.counter_events(pid))
        doc["traceEvents"] = events

    def complete(self, body: dict) -> dict:
        # mutable holder: _complete records the chosen route the moment
        # it picks one, so exceptions thrown DURING generation still
        # carry their route label (a local set via the return tuple
        # would be lost exactly when the per-route error breakdown
        # matters)
        route_box = {"route": "invalid"}
        t0 = time.perf_counter()
        # replica attr = model_id: every in-process server records into
        # the shared RECORDER, and fleetview attributes a merged
        # trace's hops to replicas by this attr (model_id is the name
        # the router registers the replica under in the fleet benches)
        with _TRACER.span("server.complete", replica=self.model_id) as span:
            try:
                resp = self._complete(body, route_box)
            except ValueError:
                self.metrics["requests"].inc(route_box["route"], "invalid")
                raise
            except Exception as e:
                if self._is_overload_error(e):
                    self.metrics["requests_shed"].inc("queue_depth_limit")
                    outcome = "shed"
                elif self._is_draining_error(e):
                    outcome = "draining"
                else:
                    outcome = "error"
                self.metrics["requests"].inc(route_box["route"], outcome)
                raise
            finally:
                span.set(route=route_box["route"])
        route = route_box["route"]
        dur = time.perf_counter() - t0
        self.metrics["requests"].inc(route, "ok")
        self.metrics["latency"].observe(route, dur)
        self.metrics["prompt_tokens"].inc(
            by=resp["usage"]["prompt_tokens"]
        )
        self.metrics["completion_tokens"].inc(
            by=resp["usage"]["completion_tokens"]
        )
        ttft, tpot = self._observe_breakdown(
            route, dur, resp["usage"]["completion_tokens"],
            route_box.get("timing"),
        )
        # non-OpenAI extension: the serving timeline as the SERVER saw
        # it. The fleet router/bench compare replicas by TTFT/TPOT, and
        # a client-side wall clock would fold proxy+network time into
        # the very signal being compared.
        resp["kubeinfer"] = {
            "route": route,
            "ttft_ms": round(ttft * 1e3, 3),
            "tpot_ms": round(tpot * 1e3, 3),
        }
        resp["kubeinfer"].update(route_box.get("ext") or {})
        return resp

    def _observe_breakdown(self, route: str, total_s: float, n_out: int,
                           req=None) -> tuple[float, float]:
        """Derived latency-breakdown histograms. The continuous route
        hands back its ``_Request`` (``timing`` in the route box) whose
        t_submit/t_admit/t_first/t_done were stamped by the scheduler
        itself; routes without an internal timeline degrade to
        end-to-end TTFT and mean-per-token TPOT — the route label keeps
        the populations separable on dashboards. Returns the observed
        ``(ttft, tpot)`` seconds so complete() can echo them to the
        client (the disagg bench compares decode-replica TPOT tails
        across fleet topologies from this echo)."""
        ttft = total_s
        decode_s = None
        if req is not None and req.t_submit:
            if req.t_admit:
                wait = max(0.0, req.t_admit - req.t_submit)
                self.metrics["queue_wait"].observe(route, wait)
                self.slo.observe("queue_wait", wait)
            end = req.t_done or req.t_submit + total_s
            if req.t_first:
                ttft = max(0.0, req.t_first - req.t_submit)
                decode_s = max(0.0, end - req.t_first)
            else:  # draft-group path: no per-token timeline
                ttft = max(0.0, end - req.t_submit)
        self.metrics["ttft"].observe(route, ttft)
        self.slo.observe("ttft", ttft)
        if decode_s is not None and n_out > 1:
            tpot = decode_s / (n_out - 1)
        else:
            tpot = total_s / max(1, n_out)
        self.metrics["tpot"].observe(route, tpot)
        self.slo.observe("tpot", tpot)
        return ttft, tpot

    def _maybe_import_prefix(self, ids: list[int], base_url: str) -> None:
        """Pull this prompt's exported KV prefix from ``base_url`` and
        land it in the local pool + radix cache. Best-effort: every
        failure increments a fallback reason and the request proceeds
        with a local (token-identical) prefill. Runs lock-free on the
        serving HTTP thread — the network fetch here is exactly the
        blocking surface the admit path must never hold a lock across,
        so it happens before routing, and the scatter itself is staged
        to the scheduler thread (batching.import_prefix)."""
        from kubeinfer_tpu.disagg.client import import_remote_prefix
        from kubeinfer_tpu.inference.kv_blocks import prefix_fingerprints

        eng = self.continuous
        fps = prefix_fingerprints(ids, eng.block_size)
        if not fps:
            return  # sub-block prompt: nothing a prefill replica can ship
        advertised = set(
            eng.cache_summary().get("fingerprints", [])
        )
        if fps[-1] in advertised:
            return  # already warm locally (earlier import or admit)
        t0 = time.perf_counter()
        # the ledger's "stream" phase: the span brackets the network
        # fetch + verify + staged scatter, parented under the active
        # server.complete span so it joins the request's trace
        with _TRACER.span("server.kv_import", kind="prefix",
                          replica=self.model_id) as sp:
            imported, reason, wire_bytes = import_remote_prefix(
                eng, ids, base_url,
            )
            sp.set(blocks=imported,
                   **({"fallback": reason} if reason else {}))
        if imported > 0:
            self.metrics["kv_stream_blocks"].inc("import", by=imported)
            self.metrics["kv_stream_bytes"].inc("import", by=wire_bytes)
            self.metrics["kv_stream_seconds"].observe(
                "import", time.perf_counter() - t0
            )
        else:
            self.metrics["disagg_fallbacks"].inc(reason or "unknown")

    def _is_draining_error(self, e: BaseException) -> bool:
        """Lazy-typed check: batching pulls jax, and this module must
        stay importable in weightless tools — the class only exists to
        be raised once a continuous engine does, so the import here
        never runs before batching is loaded anyway."""
        if self.continuous is None:
            return False
        from kubeinfer_tpu.inference.batching import EngineDrainingError

        return isinstance(e, EngineDrainingError)

    def _is_overload_error(self, e: BaseException) -> bool:
        """Shed-at-the-door twin of _is_draining_error (same lazy-typed
        import rationale); distinct because the HTTP answer differs —
        overload carries Retry-After, drain does not recover."""
        if self.continuous is None:
            return False
        from kubeinfer_tpu.inference.batching import EngineOverloadedError

        return isinstance(e, EngineOverloadedError)

    def _export_migration_chunk(self, chunk: dict) -> None:
        """Engine migration sink (scheduler thread, OFF the engine
        lock): wire-encode one streamed chunk and park it in the export
        cache keyed by the chunk's own deepest fingerprint — exactly
        where ``/kv/blocks`` serves from, so the target's chunked
        importer (disagg.client.import_remote_chain) needs no new
        endpoint. Chunk 0 encodes as plain v1/v2 (start_block=0); later
        chunks ride wire v3. Raising here is fine: the engine treats a
        sink failure as 'hand the session off with what already
        streamed'."""
        from kubeinfer_tpu.disagg.wire import encode_payload

        blob = encode_payload(
            chunk["pages_k"], chunk["pages_v"],
            chunk["fingerprints"], chunk["block_size"],
            scales_k=chunk.get("scales_k"),
            scales_v=chunk.get("scales_v"),
            kv_dtype=chunk.get("kv_dtype", "bf16"),
            start_block=chunk["start_block"],
        )
        # export blocks/bytes are counted when /kv/blocks serves the
        # blob (count-before-respond there); counting the put too would
        # double-book the direction=export series
        self.kv_exports.put(int(chunk["fingerprints"][-1]), blob)

    def drain(self, resume: bool = False,
              timeout_s: float = 30.0) -> dict:
        """``POST /admin/drain``: stop admitting, migrate-or-complete
        every live session, report. Three callers share this one
        mechanism: scale-down (the reconciler drains before deleting
        the pod), fault evacuation (SLO-burn-triggered), and hot-replica
        rebalancing (``resume=True`` — hand the sessions off, then
        rejoin the fleet). Blocks up to ``timeout_s``; a false
        ``drained`` means sessions are still live (the caller retries
        or escalates to a hard kill, which the fallback path absorbs
        token-identically)."""
        if self.continuous is None:
            raise ValueError("drain requires the continuous batcher")
        eng = self.continuous
        before = eng.migrated_total
        eng.drain()
        drained = eng.wait_drained(timeout_s)
        sched = eng.scheduler_stats()
        out = {
            "drained": bool(drained),
            "draining": True,
            "migrated": int(eng.migrated_total - before),
            "migration_chunks_total": int(sched["migration_chunks"]),
            "migration_blocks_total": int(sched["migration_blocks"]),
            "exports": (
                self.kv_exports.stats()
                if self.kv_exports is not None else {}
            ),
        }
        if resume and drained:
            eng.undrain()
            out["draining"] = False
        return out

    def _maybe_import_chain(self, tokens: list[int],
                            base_url: str) -> None:
        """Chunked warm-import of a migrated session's KV chain from
        the SOURCE replica before the resume admit. Best-effort like
        ``_maybe_import_prefix``, but failures count under the
        migration fallback counter — a partial import is still a win
        (the resume re-prefills only past the last verified chunk), so
        blocks/bytes are recorded even when a reason is."""
        from kubeinfer_tpu.disagg.client import import_remote_chain
        from kubeinfer_tpu.inference.kv_blocks import prefix_fingerprints

        eng = self.continuous
        fps = prefix_fingerprints(tokens, eng.block_size)
        if not fps:
            return
        advertised = set(
            eng.cache_summary().get("fingerprints", [])
        )
        if fps[-1] in advertised:
            return  # whole chain already warm (bounce-back resume)
        t0 = time.perf_counter()
        # same stream-phase span as _maybe_import_prefix: one name for
        # both import shapes so ledger joins need a single rule
        with _TRACER.span("server.kv_import", kind="chain",
                          replica=self.model_id) as sp:
            imported, reason, wire_bytes = import_remote_chain(
                eng, tokens, base_url,
                chunk_blocks=getattr(eng, "migration_chunk_blocks", 4),
            )
            sp.set(blocks=imported,
                   **({"fallback": reason} if reason else {}))
        if imported > 0:
            self.metrics["kv_stream_blocks"].inc("import", by=imported)
            self.metrics["kv_stream_bytes"].inc("import", by=wire_bytes)
            self.metrics["kv_stream_seconds"].observe(
                "import", time.perf_counter() - t0
            )
        if reason is not None:
            self.metrics["migration_fallbacks"].inc(reason)

    def _complete(self, body: dict, route_box: dict) -> dict:
        prompt = body.get("prompt")
        if prompt is None:
            raise ValueError("'prompt' is required")
        ids = self._encode(prompt)
        max_tokens = int(body.get("max_tokens", 16))
        if not (0 <= max_tokens <= 4096):
            raise ValueError(
                "max_tokens must be in [0, 4096] (0 = prefill-only)"
            )
        temperature = float(body.get("temperature", 0.0))
        top_k = int(body.get("top_k", 0))
        top_p = float(body.get("top_p", 1.0))
        rep_penalty = float(body.get("repetition_penalty", 1.0))
        if top_k < 0:
            raise ValueError("top_k must be >= 0 (0 disables)")
        if not (0.0 < top_p <= 1.0):
            raise ValueError("top_p must be in (0, 1]")
        if rep_penalty <= 0.0:
            raise ValueError("repetition_penalty must be > 0")
        seed = int(body.get("seed", 0))
        eos_id = -1
        if self.tokenizer is not None and self.tokenizer.eos_token_id is not None:
            eos_id = int(self.tokenizer.eos_token_id)

        # live-session migration resume (router-injected): a source
        # replica drained mid-generation and handed back its tokens-so-
        # far (and optionally where to pull the streamed KV chain from)
        resume = body.get("kubeinfer_resume")
        resume_tokens: list[int] = []
        if resume is not None:
            if not isinstance(resume, dict):
                raise ValueError("kubeinfer_resume must be an object")
            rt = resume.get("tokens") or []
            if not (
                isinstance(rt, list)
                and all(isinstance(t, int) for t in rt)
            ):
                raise ValueError(
                    "kubeinfer_resume.tokens must be token ids (ints)"
                )
            resume_tokens = [int(t) for t in rt]

        # disaggregated decode side: the router annotates the forwarded
        # body with the prefill replica that just produced this prompt's
        # KV; pull it into the local pool BEFORE routing so the
        # continuous admit below sees a warm radix cache. Runs on this
        # HTTP thread with no engine locks held (the scatter is staged
        # to the scheduler thread) — the new blocking surface the lint
        # would flag lives in _maybe_import_prefix, off-lock by design.
        kv_source = body.get("kubeinfer_kv_source")
        if (
            isinstance(kv_source, str) and kv_source
            and max_tokens > 0
            and self.continuous is not None
            and self.continuous.fits(len(ids), max_tokens)
        ):
            self._maybe_import_prefix(ids, kv_source)

        if max_tokens == 0:
            # prefill-only mode (disaggregated prefill role): run the
            # prompt through the continuous batcher's normal admit path
            # — the SAME code that serves interleaved prefills, so the
            # exported pages are bit-identical to what a local prefill
            # would have produced — and park the wire-encoded KV in the
            # export cache for a decode replica to pull. This branch
            # outranks every other route: sp/speculative/engine have no
            # exportable paged pool.
            if not (
                self.continuous is not None
                and self.continuous.fits(len(ids), 0)
            ):
                raise ValueError(
                    "max_tokens=0 (prefill-only) requires the continuous "
                    "batcher and a prompt that fits its cache"
                )
            route_box["route"] = "prefill"
            req = self.continuous.serve(
                ids, max_new_tokens=0, eos_id=eos_id,
                temperature=temperature, seed=seed,
                top_k=top_k, top_p=top_p,
                repetition_penalty=rep_penalty,
                export_kv=True,
            )
            gen: list[int] = []
            route_box["timing"] = req
            if req.kv_export is not None and self.kv_exports is not None:
                from kubeinfer_tpu.disagg.wire import (
                    WireError, encode_payload,
                )

                exp = req.kv_export
                try:
                    blob = encode_payload(
                        exp["pages_k"], exp["pages_v"],
                        exp["fingerprints"], exp["block_size"],
                        scales_k=exp.get("scales_k"),
                        scales_v=exp.get("scales_v"),
                        kv_dtype=exp.get("kv_dtype", "bf16"),
                    )
                except WireError:
                    # capture raced an empty/partial prefill (e.g. the
                    # prompt had no full block); the importer will fall
                    # back to local prefill — latency, not correctness
                    log.exception("kv export encode failed; skipping")
                else:
                    fp = exp["fingerprints"][-1]
                    self.kv_exports.put(fp, blob)
                    route_box["ext"] = {"kv_export": {
                        "fingerprint": int(fp),
                        "blocks": len(exp["fingerprints"]),
                        "bytes": len(blob),
                    }}
        elif resume_tokens:
            # resume MUST ride the continuous batcher: only its
            # position-folded key schedule reproduces the source's
            # sampling stream mid-generation (park/readmit invariant);
            # the sp/speculative/per-request engines would re-draw
            if not (
                self.continuous is not None
                and self.continuous.fits(len(ids), max_tokens)
            ):
                raise ValueError(
                    "kubeinfer_resume requires the continuous batcher "
                    "and a prompt that fits its cache"
                )
            route_box["route"] = "resume"
            if len(resume_tokens) >= max_tokens or (
                eos_id >= 0 and resume_tokens[-1] == eos_id
            ):
                # degenerate tail: the source finished the generation
                # before the hand-off completed — answer directly, no
                # zero-budget admit
                gen = resume_tokens[:max_tokens]
            else:
                src = resume.get("kv_source")
                if isinstance(src, str) and src:
                    # committed chain only — full blocks of the
                    # effective prompt MINUS the last token (the
                    # source's committed-blocks rule: the newest
                    # token's KV never streamed)
                    self._maybe_import_chain(
                        (ids + resume_tokens)[:-1], src,
                    )
                req = self.continuous.serve(
                    ids, max_new_tokens=max_tokens, eos_id=eos_id,
                    temperature=temperature, seed=seed,
                    top_k=top_k, top_p=top_p,
                    repetition_penalty=rep_penalty,
                    resume_tokens=resume_tokens,
                )
                gen = req.out_tokens
                route_box["timing"] = req
                if req.migrated is not None:
                    # drained AGAIN mid-resume (rolling rebalance):
                    # the router chains another hop off this ext
                    route_box["ext"] = {"migrated": dict(req.migrated)}
        elif self.sp is not None and self.sp.fits(len(ids), max_tokens):
            # long prompts shard their prefill over the mesh's sp axis
            # (ring attention; sp_engine.py) and decode from the
            # handed-off KV — the route that makes >single-chip-prefill
            # contexts servable. Short prompts fall through: the
            # collective traffic isn't worth it below --sp-min-prompt.
            route_box["route"] = "sp"
            out = self.sp.generate(
                [ids], max_new_tokens=max_tokens, eos_id=eos_id,
                temperature=temperature, seed=seed,
                top_k=top_k, top_p=top_p,
                repetition_penalty=rep_penalty,
            )
            gen = out.tokens[0, : out.lengths[0]].tolist()
        elif (
            self.speculative is not None
            # repetition penalty reshapes the target distribution per
            # step using generated-token state the speculative verifier
            # does not track; such requests take the normal paths
            and rep_penalty == 1.0
            and self.speculative.fits(len(ids), max_tokens)
            # when a batcher exists and the request fits it, the batcher
            # OWNS draft-eligible traffic: its incremental groups batch
            # concurrent eligible requests and interleave with busy
            # slots (r4 verdict item 5), strictly better than this
            # serialized per-request bulk path — which remains the
            # route when there is no batcher, or for requests only the
            # draft cache can hold
            and not (
                self.continuous is not None
                and self.continuous.speculative is not None
                and self.continuous.fits(len(ids), max_tokens)
            )
        ):
            # a configured draft model routes requests through
            # speculative decoding: greedy requests via argmax
            # acceptance (token-identical to vanilla greedy), sampled
            # requests via the rejection-sampling correction (exactly
            # the target's sampling distribution). Requests within the
            # target's context but beyond the k+1 speculation slack
            # fall through rather than fail.
            route_box["route"] = "speculative"
            out = self.speculative.generate(
                [ids], max_new_tokens=max_tokens, eos_id=eos_id,
                temperature=temperature, seed=seed,
                top_k=top_k, top_p=top_p,
            )
            gen = out.tokens[0, : out.lengths[0]].tolist()
        elif (
            self.continuous is not None
            and self.continuous.fits(len(ids), max_tokens)
        ):
            # requests ride the shared continuous-batching slots (greedy
            # and sampled alike — slots carry per-request temperature and
            # PRNG state): concurrent clients decode together instead of
            # serializing. Requests beyond slot width (long context) fall
            # through to the per-request engine, which serves the model's
            # full context.
            route_box["route"] = "continuous"
            req = self.continuous.serve(
                ids, max_new_tokens=max_tokens, eos_id=eos_id,
                temperature=temperature, seed=seed,
                top_k=top_k, top_p=top_p,
                repetition_penalty=rep_penalty,
            )
            gen = req.out_tokens
            # hand the scheduler-stamped timeline to complete() for the
            # TTFT/TPOT/queue-wait histograms
            route_box["timing"] = req
            if req.migrated is not None:
                # the engine drained under this request: out_tokens is
                # a PREFIX of the answer; the ext tells the router to
                # re-route with these as the resume prefix (and pull
                # the streamed chain from this replica's /kv/blocks)
                route_box["ext"] = {"migrated": dict(req.migrated)}
        else:
            route_box["route"] = "engine"
            out = self.engine.generate(
                [ids], max_new_tokens=max_tokens, eos_id=eos_id,
                temperature=temperature, seed=seed,
                top_k=top_k, top_p=top_p,
                repetition_penalty=rep_penalty,
            )
            gen = out.tokens[0, : out.lengths[0]].tolist()
        # "stop" iff the sequence actually terminated on EOS — including
        # EOS landing exactly on the max_tokens-th token (a length-based
        # test would mislabel that and invite clients to auto-continue a
        # finished sequence)
        stopped = eos_id >= 0 and bool(gen) and gen[-1] == eos_id
        finish = "stop" if stopped else "length"
        if (route_box.get("ext") or {}).get("migrated") is not None:
            # partial generation by design — neither EOS nor budget;
            # the router treats this as "continue elsewhere", a client
            # seeing it raw knows the tokens are a prefix
            finish = "migrated"
        # continuous-engine routes carry the engine's flight-recorder
        # request id (_Request.rid) in the completion id, so a client
        # report cross-references straight into a /debug/flightrecorder
        # dump's per-request chain (detail key `req`, see
        # analysis/protocol.py); batch-generate routes have no rid
        rid = getattr(route_box.get("timing"), "rid", None)
        return {
            "id": "cmpl-kubeinfer" if rid is None
            else f"cmpl-kubeinfer-{rid}",
            "object": "text_completion",
            "model": self.model_id,
            "choices": [{
                "index": 0,
                "text": self._decode(gen),
                "tokens": gen,
                "finish_reason": finish,
            }],
            "usage": {
                "prompt_tokens": len(ids),
                "completion_tokens": len(gen),
                "total_tokens": len(ids) + len(gen),
            },
        }

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "InferenceServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"inference-server-{self.port}",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        # shutdown() handshakes with serve_forever and BLOCKS FOREVER if
        # the serve loop never ran — callers that used complete()
        # directly (tests, the multichip dryrun) still get a clean close
        if self._thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()


def _load_tokenizer(model_dir: str):
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(model_dir)
    except Exception as e:
        log.warning("no tokenizer loaded from %s (%s); id-only mode", model_dir, e)
        return None


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="kubeinfer-inference-server")
    # flag surface = runtime.py build_args (vllm.go:93-112 parity)
    p.add_argument("--model", required=True,
                   help="model dir (HF snapshot) or preset name with "
                        "--random-init")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--sequence-parallel-size", type=int, default=1,
                   help="shard long-prompt prefill over this many mesh "
                        "devices via ring attention (sp_engine.py); "
                        "requests below --sp-min-prompt keep the normal "
                        "routes")
    p.add_argument("--sp-min-prompt", type=int, default=1024,
                   help="minimum prompt length (tokens) routed through "
                        "the sequence-parallel engine")
    p.add_argument("--gpu-memory-utilization", type=float, default=0.9)
    p.add_argument("--dtype", default="auto",
                   choices=["auto", "bfloat16", "float32"])
    p.add_argument("--max-model-len", type=int, default=0)
    p.add_argument("--random-init", action="store_true",
                   help="serve a randomly initialized --model preset "
                        "(demo/e2e mode; no weights needed)")
    p.add_argument("--batch-slots", type=int, default=8,
                   help="continuous-batching decode slots shared by "
                        "concurrent requests, greedy and sampled alike "
                        "(0 disables; over-slot-width requests use the "
                        "per-request engine)")
    p.add_argument("--prefill-chunk-blocks", type=int, default=4,
                   help="split each prefill into chunks of this many KV "
                        "blocks interleaved with decode steps, so a long "
                        "cold prompt never stalls the decode batch for "
                        "more than one chunk (0 = whole-suffix prefill)")
    p.add_argument("--migration-chunk-blocks", type=int, default=4,
                   help="KV blocks streamed per drain pass during live-"
                        "session migration; decode windows run between "
                        "chunks, so the stream chases the decode head "
                        "instead of stalling it")
    p.add_argument("--kv-export-budget-mb", type=float, default=0.0,
                   help="byte budget for the KV export cache (prefill "
                        "exports + migration chunks); 0 = entry cap "
                        "only. Evictions past the budget count under "
                        "kubeinfer_kv_export_evictions_total")
    p.add_argument("--kv-dtype", default="bf16",
                   choices=("bf16", "int8"),
                   help="paged KV pool dtype: int8 quantizes blocks on "
                        "commit (per-block-per-head scales, dequant in "
                        "the attention kernel) for ~2x the resident "
                        "slots at equal HBM; disagg peers must match")
    p.add_argument("--weight-dtype", default="bf16",
                   choices=("bf16", "int8"),
                   help="model weight precision: int8 quantizes the "
                        "projection matmul weights at LOAD time "
                        "(per-tile absmax scales, dequant fused into "
                        "the matmul) for ~2x model capacity at equal "
                        "HBM; embeddings, norms, and lm_head stay in "
                        "--dtype. Composes with --tensor-parallel-size "
                        "(scale planes shard with their weights)")
    p.add_argument("--queue-depth-limit", type=int, default=0,
                   help="shed completion submits with 503 + Retry-After "
                        "once waiting work (queue + holdover + parked) "
                        "reaches this depth, counted under "
                        "kubeinfer_requests_shed_total (0 = unbounded "
                        "queueing, the pre-shedding behavior)")
    p.add_argument("--preemption-slo", default="",
                   metavar="THRESHOLD_S[:BURN_LIMIT]",
                   help="park the youngest decoding row (KV cached to "
                        "the radix trie, token-identical warm resume) "
                        "when a waiter exceeds THRESHOLD_S and the "
                        "queue-wait burn rate reaches BURN_LIMIT "
                        "(default 1.0); empty disables preemption")
    p.add_argument("--draft-model", default="",
                   help="draft model dir (HF snapshot) or preset name "
                        "(with --random-init) enabling speculative "
                        "decoding for greedy requests; must share the "
                        "target's vocabulary")
    p.add_argument("--speculation-depth", type=int, default=4,
                   help="draft tokens proposed per verification round")
    p.add_argument("--speculative-draft", action="store_true",
                   help="run the --draft-model inside the continuous "
                        "batcher's paged batch: K-query verify windows "
                        "with accept/rollback at the window boundary "
                        "(supersedes the dense draft-group side-car for "
                        "slot-served requests; greedy and sampled alike)")
    p.add_argument("--prewarm-spec", default="",
                   help="comma-separated draft-group sizes to compile "
                        "before serving (e.g. '1,2,4'); without it the "
                        "first group of each size compiles on the "
                        "scheduler thread, stalling in-flight requests "
                        "(batching.py ContinuousEngine docstring)")
    p.add_argument("--tls-cert-file", default="",
                   help="serve completions over TLS (PEM cert; key via "
                        "--tls-key-file)")
    p.add_argument("--tls-key-file", default="")
    p.add_argument("--debug-token-file", default="",
                   help="file holding the bearer token required on "
                        "/debug/* (spans, flight recorder, SLO); empty "
                        "leaves them open")
    p.add_argument("--flight-capacity", type=int, default=512,
                   help="flight-recorder ring size (scheduler "
                        "decisions kept for /debug/flightrecorder); "
                        "long load runs raise it so post-mortems and "
                        "the ?since= cursor don't lose events between "
                        "polls")
    p.add_argument("--span-sample-every", type=int, default=1,
                   help="record spans for 1 in N traces (head "
                        "sampling, whole traces kept or dropped "
                        "together; 1 = record all). Sampled-out "
                        "requests still count in every metric — only "
                        "span recording is gated")
    p.add_argument("--slo", action="append", default=[],
                   metavar="NAME:THRESHOLD_S:OBJECTIVE",
                   help="SLO objective, repeatable (e.g. ttft:0.5:0.99 "
                        "= 99%% of requests see first token in 500ms); "
                        "names: ttft, tpot, queue_wait. Default: loose "
                        "built-ins (observability/slo.py)")
    args = p.parse_args(argv)
    # lint: allow[log-discipline] main() is the process entrypoint and owns root logging config
    logging.basicConfig(level=logging.INFO)
    if args.span_sample_every != 1:
        # process-global on purpose: the keep/drop verdict must agree
        # across every tracer in this process or ledgers shear mid-hop
        tracing.set_span_sampling(args.span_sample_every)

    import jax
    import jax.numpy as jnp

    from kubeinfer_tpu.inference.config import PRESETS
    from kubeinfer_tpu.inference.engine import Engine
    from kubeinfer_tpu.inference.model import init_params

    dtype = {"auto": jnp.bfloat16, "bfloat16": jnp.bfloat16,
             "float32": jnp.float32}[args.dtype]
    tokenizer = None
    if args.random_init:
        # --model may be a preset name or (when the lifecycle layer passes
        # a cache dir, e.g. the mock-download e2e flow) any path: fall
        # back to the CI-sized preset.
        cfg = PRESETS.get(args.model)
        if cfg is None:
            log.info("--random-init: %r is not a preset; using 'tiny'",
                     args.model)
            cfg = PRESETS["tiny"]
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=dtype,
                             weight_dtype=args.weight_dtype)
    else:
        from kubeinfer_tpu.inference.weights import load_pretrained

        params, cfg = load_pretrained(args.model, dtype=dtype,
                                      weight_dtype=args.weight_dtype)
        tokenizer = _load_tokenizer(args.model)
    if args.weight_dtype == "int8" and args.sequence_parallel_size > 1:
        # the SP engine shard_maps with manual param_specs and has no
        # quantized-leaf path; refusing beats silently serving a
        # broken long-prompt route
        raise SystemExit(
            "--weight-dtype int8 does not compose with "
            "--sequence-parallel-size > 1 yet"
        )
    if args.max_model_len > 0:
        max_cache = args.max_model_len
    else:
        max_cache = cfg.max_position_embeddings

    mesh = None
    if args.tensor_parallel_size > 1 or args.sequence_parallel_size > 1:
        # place params on a tp x sp mesh; GSPMD partitions the jitted
        # forward over tp, and the SP engine shard_maps prefill over sp
        from kubeinfer_tpu.inference.sharding import (
            make_inference_mesh, shard_params,
        )

        mesh = make_inference_mesh(
            tp=args.tensor_parallel_size,
            sp=args.sequence_parallel_size, dp=1,
        )
        if args.tensor_parallel_size > 1:
            params = shard_params(params, mesh, cfg)

    sp_engine = None
    if args.sequence_parallel_size > 1:
        from kubeinfer_tpu.inference.sp_engine import SPEngine

        sp_engine = SPEngine(
            params, cfg, mesh, max_cache_len=max_cache,
            min_prompt=args.sp_min_prompt,
        )

    engine = Engine(params, cfg, max_cache_len=max_cache)
    if args.speculative_draft and not args.draft_model:
        raise SystemExit(
            "--speculative-draft requires --draft-model (the paged "
            "verify windows run the same draft weights)"
        )
    speculative = None
    dparams = dcfg = None
    if args.draft_model:
        from kubeinfer_tpu.inference.speculative import SpeculativeEngine

        if args.random_init:
            dcfg = PRESETS.get(args.draft_model)
            if dcfg is None:
                raise SystemExit(
                    f"--draft-model {args.draft_model!r} is not a preset "
                    "(with --random-init the draft must name one)"
                )
            dparams = init_params(dcfg, jax.random.PRNGKey(1), dtype=dtype)
        else:
            from kubeinfer_tpu.inference.weights import load_pretrained

            dparams, dcfg = load_pretrained(args.draft_model, dtype=dtype)
        if args.tensor_parallel_size > 1:
            # the draft shards onto the same tp mesh as the target —
            # left unsharded, GSPMD would replicate its weights on every
            # device (tp x the intended draft HBM footprint)
            from kubeinfer_tpu.inference.sharding import shard_params

            dparams = shard_params(dparams, mesh, dcfg)
        speculative = SpeculativeEngine(
            params, cfg, dparams, dcfg, k=args.speculation_depth,
            max_cache_len=max_cache,
        )
    continuous = None
    if args.batch_slots > 0:
        from kubeinfer_tpu.inference.batching import (
            ContinuousEngine, PreemptionPolicy,
        )

        preemption = None
        if args.preemption_slo:
            preemption = PreemptionPolicy.parse(args.preemption_slo)
        layout = None
        if args.tensor_parallel_size > 1:
            # the real --tensor-parallel path (the reference forwards
            # the flag to external vLLM, vllm.go:57-61; we own the
            # partition): reuse the (dp, tp, sp) mesh built above so
            # the batcher, the per-request engine, and the draft all
            # place onto the same devices
            from kubeinfer_tpu.inference.sharding import EngineLayout

            layout = EngineLayout(tp=args.tensor_parallel_size, mesh=mesh)
        continuous = ContinuousEngine(
            params, cfg, n_slots=args.batch_slots,
            cache_len=min(max_cache, 4096),
            speculative=speculative,
            prefill_chunk_blocks=args.prefill_chunk_blocks,
            preemption=preemption,
            layout=layout,
            spec_draft=(
                (dparams, dcfg) if args.speculative_draft else None
            ),
            spec_k=args.speculation_depth,
            kv_dtype=args.kv_dtype,
            weight_dtype=args.weight_dtype,
            queue_depth_limit=args.queue_depth_limit,
            migration_chunk_blocks=args.migration_chunk_blocks,
            flight_capacity=args.flight_capacity,
        )
        if args.prewarm_spec and speculative is not None:
            sizes = tuple(
                int(s) for s in args.prewarm_spec.split(",") if s.strip()
            )
            t0 = time.monotonic()
            n = continuous.prewarm_spec(group_sizes=sizes)
            log.info("prewarmed %d draft-group shapes in %.1fs",
                     n, time.monotonic() - t0)
        continuous.start()
    debug_token = ""
    if args.debug_token_file:
        with open(args.debug_token_file, encoding="utf-8") as f:
            debug_token = f.read().strip()
    slo = None
    if args.slo:
        from kubeinfer_tpu.observability.slo import SLOObjective

        slo = SLOMonitor(
            objectives=tuple(SLOObjective.parse(s) for s in args.slo)
        )
    srv = InferenceServer(
        engine, model_id=args.model, tokenizer=tokenizer,
        host=args.host, port=args.port, continuous=continuous,
        speculative=speculative, sp=sp_engine,
        tls_cert=args.tls_cert_file, tls_key=args.tls_key_file,
        token=debug_token, slo=slo,
        kv_export_budget_mb=args.kv_export_budget_mb,
    ).start()
    log.info("native inference server on %s:%d (model %s)",
             args.host, srv.port, args.model)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    while not stop.is_set():
        stop.wait(0.5)
    srv.stop()
    if continuous is not None:
        continuous.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
