"""Ring attention: causal attention over a sequence-sharded axis.

Long-context scaling (SURVEY.md §2 checklist "Sequence/Context parallel":
absent in the reference; first-class here): the sequence axis is sharded
over mesh devices, each holding a [B, T/P, ...] block of Q, K, V. K/V
blocks rotate around the ring via ``ppermute`` (ICI neighbor exchange —
bandwidth-optimal, no all-gather materializing the full sequence), while
each device folds one block per step into its local attention state using
the online-softmax recurrence (running max m, normalizer l, accumulator
o — the same algebra as FlashAttention's outer loop):

    m' = max(m, rowmax(S));  a = exp(m - m');  b = exp(S - m')
    l' = a*l + rowsum(b);    o' = a*o + b @ V

After P steps every Q block has attended to every K/V block; o/l is the
exact softmax attention. Causality folds into a per-step block mask from
GLOBAL positions (device r holds positions [r*T_loc, (r+1)*T_loc)), so
no [T, T] global mask ever exists.

Compute/communication overlap is XLA's job (the ppermute is independent
of the block compute); the recurrence keeps f32 state regardless of
input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from kubeinfer_tpu.utils.jaxcompat import axis_size, pcast


def _block_attention(
    q: jax.Array,  # [B, Tq, n_kv, G, D] grouped query block
    k: jax.Array,  # [B, Tk, n_kv, D]
    v: jax.Array,  # [B, Tk, n_kv, D]
    mask: jax.Array,  # bool[Tq, Tk] True = attend
    m: jax.Array,  # f32[B, n_kv, G, Tq] running rowmax
    l: jax.Array,  # f32[B, n_kv, G, Tq] running normalizer
    o: jax.Array,  # f32[B, Tq, n_kv, G, D] running accumulator
):
    D = q.shape[-1]
    s = jnp.einsum(
        "btkgd,bskd->bkgts", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(D))
    s = jnp.where(mask[None, None, None, :, :], s, -1e30)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # renormalize old state; -1e30 rows (nothing attendable yet) stay 0
    # because exp(-1e30 - m_new) underflows to 0 exactly
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = alpha * l + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 3, 1, 2)[..., None] + jnp.einsum(
        "bkgts,bskd->btkgd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,  # [B, T_loc, n_heads, D] local query block
    k: jax.Array,  # [B, T_loc, n_kv, D] local key block
    v: jax.Array,  # [B, T_loc, n_kv, D]
    *,
    axis_name: str,
    causal: bool = True,
    extra_vary: tuple[str, ...] = (),
) -> jax.Array:
    """Exact causal attention with K/V rotating around ``axis_name``.

    Must run inside shard_map with the sequence axis sharded over
    ``axis_name``. Returns the local attention output block
    [B, T_loc, n_heads, D]. ``extra_vary`` names additional manual mesh
    axes the INPUT blocks vary over (e.g. ``("tp",)`` when the head axis
    is tensor-parallel-sharded) — the scan's accumulator carries must be
    declared varying over exactly the same axes as the per-step values
    merged into them, or shard_map's manual-axes type check rejects the
    carry.
    """
    P = axis_size(axis_name)
    r = lax.axis_index(axis_name)
    B, T_loc, n_heads, D = q.shape
    n_kv = k.shape[2]
    G = n_heads // n_kv
    qg = q.reshape(B, T_loc, n_kv, G, D)

    q_pos = r * T_loc + jnp.arange(T_loc)  # global positions of this block
    perm = [(i, (i + 1) % P) for i in range(P)]  # ring: send right

    # pcast to 'varying': the accumulators start as device-invariant
    # constants but the scan writes device-varying values into them;
    # shard_map's manual-axes type check requires the carry declared
    # varying up front.
    def vary(x):
        return pcast(x, (axis_name,) + extra_vary, to="varying")

    m = vary(jnp.full((B, n_kv, G, T_loc), -jnp.inf, jnp.float32))
    l = vary(jnp.zeros((B, n_kv, G, T_loc), jnp.float32))
    o = vary(jnp.zeros((B, T_loc, n_kv, G, D), jnp.float32))

    def step(carry, i):
        k_blk, v_blk, m, l, o = carry
        # block i arrived from device (r - i) mod P: its global offset
        src = (r - i) % P
        k_pos = src * T_loc + jnp.arange(T_loc)
        mask = (
            q_pos[:, None] >= k_pos[None, :]
            if causal
            else jnp.ones((T_loc, T_loc), bool)
        )
        m, l, o = _block_attention(qg, k_blk, v_blk, mask, m, l, o)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), ()

    (k, v, m, l, o), _ = lax.scan(
        step, (k, v, m, l, o), jnp.arange(P), length=P
    )
    # rows with no attendable position (never in causal mode) keep l=0;
    # guard the division anyway so non-causal edge uses stay finite
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 3, 1, 2)[..., None]
    return out.reshape(B, T_loc, n_heads, D).astype(q.dtype)
