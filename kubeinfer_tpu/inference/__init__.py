"""TPU-native inference runtime.

The reference manages inference by exec-ing an external GPU process
(vLLM — internal/agent/vllm/vllm.go) and owns none of the model compute.
This package is the TPU-native alternative the framework offers alongside
that pass-through: a decoder-only transformer (llama-family) implemented
directly in JAX, sharded over a ``jax.sharding.Mesh`` (tensor parallel
over heads/ffn, data parallel over batch, ring-attention sequence
parallel for long context), with a static-shape KV-cache decode engine
and an OpenAI-compatible HTTP server the agent's runtime launcher can
spawn exactly like it spawns vLLM.
"""

from kubeinfer_tpu.inference.config import ModelConfig, PRESETS
from kubeinfer_tpu.inference.model import forward, init_params

__all__ = ["ModelConfig", "PRESETS", "forward", "init_params"]
