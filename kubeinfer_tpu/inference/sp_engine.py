"""Sequence-parallel serving engine: ring-attention prefill + KV handoff.

The r2 framework had ring attention (ring_attention.py) and a sequence-
parallel forward (sharding.forward_sequence_parallel) but no path from
the SERVING stack into them — long prompts always took the single-device
chunked prefill (VERDICT r2 weak #2). This module closes that: the
prompt's sequence axis is sharded over the mesh's ``sp`` axis, each
device runs the decoder over its local block with ring attention (K/V
rotating over ICI, never materializing the full sequence on one chip,
and never materializing anything [T, T]-sized), and the per-shard KV —
written through the standard cache plumbing with GLOBAL RoPE positions —
is gathered into an ordinary decode cache. Decode then runs the exact
``engine.decode_scan`` every other route uses, so sampling semantics
(temperature/top-k/top-p/repetition penalty, EOS handling) are identical
by construction.

Reference parity note: the reference delegates long context entirely to
vLLM via --max-model-len (internal/agent/vllm/vllm.go:25-26,104-106);
sequence parallelism has no reference counterpart (SURVEY.md §2) — this
is TPU-first new capability, surfaced through the same CLI the runtime
launcher builds (server.py --sequence-parallel-size).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from kubeinfer_tpu.utils.jaxcompat import shard_map
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeinfer_tpu.inference.config import ModelConfig
from kubeinfer_tpu.inference.engine import (
    GenerationResult,
    prepare_prompts,
)
from kubeinfer_tpu.inference.stepper import decode_scan
from kubeinfer_tpu.inference.model import Params, forward
from kubeinfer_tpu.inference.ring_attention import ring_attention


def sp_prefill(
    params: Params,
    prompt: jax.Array,  # i32[B, T], T divisible by the sp axis
    prompt_len: jax.Array,  # i32[B]
    cfg: ModelConfig,
    mesh: Mesh,
):
    """Sequence-parallel prefill: returns (kv_caches [B, T, ...] per
    layer, next_logits f32[B, V] at each row's last real position).

    Each shard writes its local K/V (global RoPE positions) through the
    model's standard cache path — the local cache width equals the local
    block width, so the cache contents the ring consumes ARE the local
    block — and the shard_map out_spec concatenates the shards back into
    position order. Padding rows are left-aligned, so causal masking
    alone keeps real queries from attending to pad K/V; pad positions'
    garbage KV is overwritten by decode before it ever becomes visible
    (the same contract chunked_prefill relies on).

    On a mesh with a tp axis > 1 the ring body runs in MANUAL tensor
    parallelism (r3 verdict item 5): weights enter the shard_map already
    Megatron-sharded (sharding.param_specs — heads/tp per device, F/tp
    mlp lanes), the decoder emits the two row-parallel psums itself
    (model.decoder_layer tp_axis), and the KV cache comes back sharded
    over BOTH sp (positions) and tp (kv heads). Per-device weight HBM on
    the sp route is full/tp — the r3 all-gather warning is gone, not
    just documented.
    """
    B, T = prompt.shape
    sp = mesh.shape["sp"]
    tp = mesh.shape.get("tp", 1)
    if T % sp:
        raise ValueError(f"prompt bucket {T} must divide by sp={sp}")
    if cfg.num_attention_heads % tp or cfg.num_key_value_heads % tp:
        raise ValueError(
            f"tp={tp} must divide attention heads "
            f"({cfg.num_attention_heads}) and kv heads "
            f"({cfg.num_key_value_heads})"
        )
    T_loc = T // sp
    n_kv_loc, D = cfg.num_key_value_heads // tp, cfg.head_dim
    dtype = params["norm"].dtype
    tp_axis = "tp" if tp > 1 else None
    # tied embeddings keep full-vocab logits on every device (the embed
    # table is replicated); a separate lm_head is vocab-sharded over tp
    vocab_sharded = tp > 1 and not cfg.tie_word_embeddings

    def body(p, t_local, plen):
        r = lax.axis_index("sp")
        positions = jnp.broadcast_to(
            r * T_loc + jnp.arange(T_loc, dtype=jnp.int32)[None, :],
            t_local.shape,
        )
        local_caches = [
            (
                jnp.zeros((B, T_loc, n_kv_loc, D), dtype),
                jnp.zeros((B, T_loc, n_kv_loc, D), dtype),
            )
            for _ in range(cfg.num_hidden_layers)
        ]

        def ring_fn(q, k, v, mask):
            # causality comes from global positions inside the ring; the
            # local mask below exists only to satisfy forward()'s
            # cache-mode signature. The ring rotates over sp only — each
            # device rings its OWN tp head shard (hence extra_vary).
            del mask
            return ring_attention(
                q, k, v, axis_name="sp",
                extra_vary=("tp",) if tp > 1 else (),
            )

        local_mask = jnp.ones((B, T_loc, T_loc), bool)
        logits, caches = forward(
            p, t_local, cfg, positions=positions, attn_mask=local_mask,
            kv_caches=local_caches, cache_offset=0, attn_fn=ring_fn,
            tp_axis=tp_axis, tp_size=tp,
        )
        # Next-token logits live on whichever shard holds the row's last
        # real position; psum replicates them without gathering the full
        # [B, T_loc, V] logits across shards.
        last = jnp.clip(plen - 1, 0, T - 1)
        loc = last - r * T_loc
        in_shard = (loc >= 0) & (loc < T_loc)
        idx = jnp.clip(loc, 0, T_loc - 1)
        sel = jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0]
        next_logits = lax.psum(jnp.where(in_shard[:, None], sel, 0.0), "sp")
        return next_logits, caches

    if tp > 1:
        from kubeinfer_tpu.inference.sharding import param_specs

        pspecs = param_specs(cfg)
        if "lm_head" not in params:
            pspecs = dict(pspecs)
            pspecs.pop("lm_head")
    else:
        pspecs = jax.tree.map(lambda _: P(), params)
    cache_spec = [
        (
            P(None, "sp", tp_axis, None),
            P(None, "sp", tp_axis, None),
        )
        for _ in range(cfg.num_hidden_layers)
    ]
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(pspecs, P(None, "sp"), P()),
        out_specs=(
            P(None, "tp") if vocab_sharded else P(),
            cache_spec,
        ),
    )
    next_logits, caches = fn(params, prompt, prompt_len)
    return caches, next_logits


class SPEngine:
    """Long-prompt generation front-end over a sequence-parallel mesh.

    ``fits`` gates routing (server.py): prompts below ``min_prompt``
    aren't worth the collective traffic and take the normal routes.
    """

    def __init__(
        self,
        params: Params,
        cfg: ModelConfig,
        mesh: Mesh,
        max_cache_len: int = 0,
        min_prompt: int = 1024,
    ) -> None:
        if "sp" not in mesh.shape or mesh.shape["sp"] < 2:
            raise ValueError("SPEngine needs a mesh with an sp axis >= 2")
        self.params = params
        self.cfg = cfg
        self.mesh = mesh
        self.sp = mesh.shape["sp"]
        self.max_cache_len = max_cache_len or cfg.max_position_embeddings
        self.min_prompt = min_prompt

        @functools.partial(
            jax.jit, static_argnames=("max_new", "cache_len")
        )
        def _gen(params, prompt, prompt_len, max_new, cache_len,
                 eos_id, temperature, top_k, top_p, rep_penalty, rng_key):
            caches_t, next_logits = sp_prefill(
                params, prompt, prompt_len, self.cfg, self.mesh
            )
            B = prompt.shape[0]

            def expand(c):  # [B, T, n_kv, D] -> decode capacity
                buf = jnp.zeros(
                    (B, cache_len) + c.shape[2:], c.dtype
                )
                return lax.dynamic_update_slice(buf, c, (0, 0, 0, 0))

            caches = [(expand(k), expand(v)) for k, v in caches_t]
            return decode_scan(
                params, self.cfg, caches, next_logits, prompt, prompt_len,
                max_new, cache_len, eos_id, temperature, top_k, top_p,
                rep_penalty, rng_key,
            )

        self._gen = _gen

    def fits(self, prompt_len: int, max_new: int) -> bool:
        return (
            prompt_len >= self.min_prompt
            and prompt_len + max_new <= self.max_cache_len
        )

    def generate(
        self,
        prompts: list[list[int]],
        max_new_tokens: int = 32,
        eos_id: int = -1,
        temperature: float = 0.0,
        seed: int = 0,
        top_k: int = 0,
        top_p: float = 1.0,
        repetition_penalty: float = 1.0,
    ) -> GenerationResult:
        if not prompts:
            return GenerationResult(
                np.zeros((0, 0), np.int32), np.zeros((0,), np.int32)
            )
        B = len(prompts)
        padded, lens, cache_len = prepare_prompts(
            prompts, max_new_tokens, self.max_cache_len
        )
        # the sequence shards must be equal-sized: widen the bucket to a
        # multiple of sp (buckets are powers of two, so this only fires
        # for sp values that aren't)
        T = padded.shape[1]
        if T % self.sp:
            T2 = -(-T // self.sp) * self.sp
            padded = np.pad(padded, ((0, 0), (0, T2 - T)))
            cache_len = max(cache_len, T2)

        # one dispatch for the whole (possibly length-ragged) batch:
        # decode_scan carries per-row cache offsets, same as
        # Engine.generate
        toks, glens = self._gen(
            self.params,
            jnp.asarray(padded),
            jnp.asarray(lens),
            max_new_tokens,
            cache_len,
            jnp.int32(eos_id),
            jnp.float32(temperature),
            jnp.int32(top_k),
            jnp.float32(top_p),
            jnp.float32(repetition_penalty),
            jax.random.PRNGKey(seed),
        )
        # lint: allow[host-sync] serving boundary: one readback per batch
        toks_out = np.asarray(toks)
        lens_out = np.asarray(glens)  # lint: allow[host-sync] same readback as the line above
        return GenerationResult(toks_out, lens_out)
