"""Host-side paged-KV bookkeeping: block pool refcounts + radix prefix
cache over token ids.

All decisions here happen on the host BETWEEN device steps — the jit'd
admit/decode steps only ever see the static-shape i32 block tables this
module hands them (the repo's no-data-dependent-control-flow-under-jit
invariant). The device never allocates or frees; it scatters into blocks
the host already committed.

Design source: vLLM's PagedAttention block manager (Kwon et al. 2023)
for the pool, SGLang's RadixAttention (Zheng et al. 2024) for the
longest-prefix trie. No reference counterpart — the reference delegates
the whole serving cache to the vLLM subprocess (vllm.go:93-112), so the
paging policy is ours to own.

Reference counting contract:
- Block 0 is the reserved NULL block: never allocated, never freed.
  Hosts pad dead table entries with it and retired slots' decode
  scatters land in it (nondeterministic junk, read by nobody).
- A slot admit holds one reference per block in its table (fresh blocks
  arrive from alloc() with refcount 1; reused prefix blocks get a bump
  from RadixCache.match). Retire drops them all.
- The trie holds its own reference per cached node's block, so prefix
  blocks survive slot retirement until LRU eviction needs the space.

Lock order (outermost first): ContinuousEngine._lock ->
RadixCache._lock -> BlockPool._lock. The trie calls into the pool under
its own lock; nothing here calls back out.

Device-layout audit (tensor-parallel serving): every block id in this
module is LOGICAL — an index into the pool array's replicated leading
``num_blocks`` axis. Under a sharded EngineLayout the pool tensor
shards along its ``n_kv`` axis (each device holds its own heads' slice
of every block); the leading axis is whole on every device, so the
same i32 tables, refcounts, fingerprints, and LRU decisions drive
every shard identically and nothing in this file may ever branch on
the layout. Anything that would make block ids device-relative (e.g.
per-shard free lists) breaks the radix cache's cross-slot sharing and
the preemption park/resume contract in one stroke.
"""

from __future__ import annotations

from typing import Iterable, Sequence

from kubeinfer_tpu.analysis.racecheck import guard, make_lock

NULL_BLOCK = 0

# -- path fingerprints --------------------------------------------------
#
# The fleet router (kubeinfer_tpu/router/) scores replicas by longest
# advertised prefix match without ever shipping token ids across the
# control plane: each trie node carries a rolling hash of the block-key
# path from the root, and RadixCache.summary() exports a capped set of
# those fingerprints. The request side recomputes the same chain over
# its own prompt (prefix_fingerprints) and the deepest fingerprint
# present in a replica's advertised set IS the match depth. Both sides
# must use the identical chain function, which is why it lives here and
# the router imports it — two implementations would silently drift.
#
# The hash is FNV-1a folded per token and chained per block, masked to
# 63 bits so fingerprints survive JSON round-trips (store heartbeats)
# as plain positive ints. Collisions only misroute a request to a
# replica that turns out cold — a performance blip, never a
# correctness issue — so 63 bits is plenty. Deliberately NOT Python's
# hash(): that is salted per process and two replicas would never agree.

_FP_SEED = 0xCBF29CE484222325 & ((1 << 63) - 1)  # FNV-1a offset basis
_FP_PRIME = 0x100000001B3
_FP_MASK = (1 << 63) - 1

# Heartbeat payload cap: a trie can grow to thousands of nodes, and the
# summary rides inside every NodeState heartbeat (agent -> store write,
# typically 1/s per node). 512 fingerprints is ~10 KiB of JSON — small
# next to the rest of NodeState, yet deep enough to advertise hundreds
# of distinct prefix families. Truncation keeps the LRU-newest (hottest)
# paths, so what gets dropped is exactly what the cache would evict
# first anyway; a truncated summary only understates match depth.
SUMMARY_FINGERPRINT_BUDGET = 512


def extend_fingerprint(fp: int, key: Sequence[int]) -> int:
    """Chain one block of token ids onto a path fingerprint."""
    h = fp
    for t in key:
        h = ((h ^ (int(t) & _FP_MASK)) * _FP_PRIME) & _FP_MASK
    return h


def prefix_fingerprints(tokens: Sequence[int], block_size: int) -> list[int]:
    """Fingerprint of every full-block prefix of ``tokens``,
    shallowest first — element i covers tokens[0 : (i+1)*block_size].
    The partial tail block is never fingerprinted, mirroring the trie's
    full-blocks-only keying (the tail is copy-on-write, never shared)."""
    if block_size <= 0:
        raise ValueError(f"block_size must be > 0, got {block_size}")
    out: list[int] = []
    fp = _FP_SEED
    for i in range(0, len(tokens) - block_size + 1, block_size):
        fp = extend_fingerprint(fp, tokens[i:i + block_size])
        out.append(fp)
    return out


class BlockPool:
    """Fixed-size pool of KV blocks with host-side refcounts.

    Pure bookkeeping — the actual [num_blocks, block_size, n_kv, D]
    device tensors live in the engine's SlotState; indices handed out
    here are what the block tables (and the Pallas index_map) resolve.
    Indices are logical per the module's device-layout audit: one pool,
    whatever the tensor's sharding.
    """

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"BlockPool needs >= 2 blocks (one is the reserved null "
                f"block); got {num_blocks}"
            )
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._lock = make_lock("kv_blocks.BlockPool._lock")
        # LIFO free list: recently-freed blocks are re-issued first,
        # which keeps the working set of physical blocks small (warmer
        # in whatever cache hierarchy the backend has).
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = [0] * self.num_blocks
        guard(self)

    def alloc(self, n: int) -> list[int]:
        """Take n blocks at refcount 1. Raises when the pool cannot
        supply them — callers gate on free_blocks (or
        RadixCache.ensure_free) first, so hitting this is a logic bug,
        not backpressure."""
        with self._lock:
            if n > len(self._free):
                raise RuntimeError(
                    f"BlockPool exhausted: need {n}, have "
                    f"{len(self._free)} free of {self.num_blocks}"
                )
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
            return out

    def ref(self, blocks: Iterable[int]) -> None:
        with self._lock:
            for b in blocks:
                if self._ref[b] <= 0:
                    raise RuntimeError(f"ref of free block {b}")
                self._ref[b] += 1

    def unref(self, blocks: Iterable[int]) -> int:
        """Drop one reference per block; blocks reaching 0 return to
        the free list. Returns how many were freed."""
        freed = 0
        with self._lock:
            for b in blocks:
                if b == NULL_BLOCK:
                    raise RuntimeError("unref of the null block")
                if self._ref[b] <= 0:
                    raise RuntimeError(f"unref of free block {b}")
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    self._free.append(b)
                    freed += 1
        return freed

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref[block]

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        # excludes the null block: it is neither free nor usable
        with self._lock:
            return self.num_blocks - 1 - len(self._free)


class _Node:
    """One trie edge = one full block of tokens. The node holds the
    pool block storing that span's KV (trie's own +1 reference)."""

    __slots__ = ("children", "parent", "key", "block", "stamp", "fp")

    def __init__(self, parent: "_Node | None", key: tuple | None,
                 block: int) -> None:
        self.children: dict[tuple, _Node] = {}
        self.parent = parent
        self.key = key
        self.block = block
        self.stamp = 0
        # path fingerprint root->here, extended incrementally so insert
        # stays O(block_size) per new node instead of re-hashing the
        # whole path
        self.fp = (
            _FP_SEED if parent is None
            else extend_fingerprint(parent.fp, key)
        )


class RadixCache:
    """Longest-prefix KV reuse over full blocks.

    Keys are ``block_size``-token tuples, so a lookup walks at most
    len(prompt) // block_size edges and the matched depth is always a
    whole number of blocks — the partial tail block is never shared
    (copy-on-write by construction: the admit path recomputes the tail
    into a fresh block instead of appending to a shared one).

    Eviction is LRU over leaves whose block nobody else references
    (pool refcount == 1, i.e. only the trie's own hold) — an interior
    node can only be evicted after its children, which preserves the
    invariant that every cached path is fully materialized.
    """

    def __init__(self, pool: BlockPool) -> None:
        self._pool = pool
        self._lock = make_lock("kv_blocks.RadixCache._lock")
        self._root = _Node(None, None, NULL_BLOCK)
        self._clock = 0  # monotonic LRU stamp; touched on every match
        self._nodes = 0
        # summary version: bumps whenever the advertised fingerprint set
        # can have changed (insert created nodes / eviction removed one)
        # so routers can diff summaries by a single int compare
        self._version = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        guard(self)

    def _keys(self, tokens: Sequence[int]) -> list[tuple]:
        bs = self._pool.block_size
        return [
            tuple(tokens[i: i + bs])
            for i in range(0, len(tokens) - bs + 1, bs)
        ]

    def match(self, tokens: Sequence[int]) -> list[int]:
        """Longest-prefix match in full blocks. Returns the matched
        block ids in sequence order, each with one reference taken for
        the caller (so eviction cannot free them between this call and
        the admit that consumes them). The caller must unref any it
        decides not to use."""
        with self._lock:
            self._clock += 1
            out: list[int] = []
            node = self._root
            for key in self._keys(tokens):
                child = node.children.get(key)
                if child is None:
                    break
                child.stamp = self._clock
                out.append(child.block)
                node = child
            self._pool.ref(out)
            return out

    def match_with_fingerprints(
        self, tokens: Sequence[int],
    ) -> list[tuple[int, int]]:
        """``match()`` plus each matched node's path fingerprint:
        ``[(block, fp), ...]`` shallowest first, where ``fp`` covers
        tokens[0 : (i+1)*block_size] — the exact chain
        ``prefix_fingerprints`` would recompute. The KV export side
        content-addresses blocks with these (disagg/export.py) without
        re-hashing the prompt. Same reference contract as ``match()``:
        one caller-owned reference per returned block, unref what you
        don't consume."""
        with self._lock:
            self._clock += 1
            out: list[tuple[int, int]] = []
            node = self._root
            for key in self._keys(tokens):
                child = node.children.get(key)
                if child is None:
                    break
                child.stamp = self._clock
                out.append((child.block, child.fp))
                node = child
            self._pool.ref([b for b, _ in out])
            return out

    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> int:
        """Cache the full blocks of ``tokens``: blocks[i] holds tokens
        [i*bs, (i+1)*bs). Existing nodes keep their block (the caller's
        table already names them — match() handed them out); each NEW
        node takes the trie's own reference on the caller's block.
        Returns how many new nodes were created."""
        created = 0
        with self._lock:
            self._clock += 1
            node = self._root
            for key, block in zip(self._keys(tokens), blocks):
                child = node.children.get(key)
                if child is None:
                    child = _Node(node, key, block)
                    node.children[key] = child
                    self._pool.ref([block])
                    self._nodes += 1
                    created += 1
                child.stamp = self._clock
                node = child
            if created:
                self._version += 1
        return created

    def note_result(self, reused_blocks: int) -> None:
        """Record one admit's outcome for the hit/miss counters: a hit
        is an admit that actually reused >= 1 block (after the engine's
        capacity clamp), not merely one that matched."""
        with self._lock:
            if reused_blocks > 0:
                self.hits += 1
            else:
                self.misses += 1

    def _evict_one(self) -> bool:
        # LRU scan over evictable leaves. O(nodes), fine at serving
        # scale (thousands of nodes); called only when the pool is
        # actually short.
        victim: _Node | None = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if (
                node is not self._root
                and not node.children
                and self._pool.refcount(node.block) == 1
                and (victim is None or node.stamp < victim.stamp)
            ):
                victim = node
        if victim is None:
            return False
        assert victim.parent is not None
        del victim.parent.children[victim.key]
        self._pool.unref([victim.block])
        self._nodes -= 1
        self.evictions += 1
        self._version += 1
        return True

    def evictable_blocks(self) -> int:
        """Upper bound on blocks eviction could reclaim right now:
        trie nodes whose block only the trie holds (pool refcount 1).
        It is an overestimate when a refcount-1 interior node sits above
        a pinned child (that subtree path cannot be fully torn down),
        but an overestimate only delays the fail-fast below to the first
        stuck ``_evict_one`` — it never rejects a servable request."""
        with self._lock:
            return self._evictable_locked()

    def _evictable_locked(self) -> int:
        count = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is not self._root and self._pool.refcount(node.block) == 1:
                count += 1
        return count

    def ensure_free(self, n: int) -> bool:
        """Evict LRU-first until the pool has n free blocks. False when
        eviction cannot get there (everything live is pinned by slots or
        parked rows) — the engine treats that as admission backpressure.

        Fails fast BEFORE evicting anything when free + evictable can
        never reach n: under preemption pressure a hopeless request used
        to strip the entire reusable cache on its way to False, turning
        one backpressured admit into a cold-start penalty for every
        later warm admit. The loop itself always terminates — each
        successful ``_evict_one`` frees exactly one block."""
        with self._lock:
            if n > self._pool.free_blocks + self._evictable_locked():
                return False
            while self._pool.free_blocks < n:
                if not self._evict_one():
                    return False
            return True

    def stats(self) -> dict:
        """Counters plus trie shape. ``nodes``/``leaves`` and
        ``cached_tokens`` (= nodes x block_size, every edge is exactly
        one full block) are the capacity denominators a summary
        consumer needs to judge how much of the trie its capped
        fingerprint set actually covers."""
        with self._lock:
            leaves = 0
            stack = list(self._root.children.values())
            while stack:
                node = stack.pop()
                if node.children:
                    stack.extend(node.children.values())
                else:
                    leaves += 1
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "nodes": self._nodes,
                "leaves": leaves,
                "cached_tokens": self._nodes * self._pool.block_size,
            }

    def summary(self, budget: int = SUMMARY_FINGERPRINT_BUDGET) -> dict:
        """Compact advertisement of what this cache holds, for the
        fleet router: every cached path's fingerprint (hottest first,
        capped at ``budget``), the block size the request side must use
        to recompute matching fingerprints, and a version that bumps on
        any insert/evict so consumers can skip unchanged summaries.

        Truncation order is deterministic: LRU stamp descending (the
        paths the cache would keep longest advertise first), fingerprint
        as the tie-break so equal-stamp nodes — e.g. a whole path
        inserted by one admit — never reorder between two exports of
        the same trie. ``total_nodes`` lets a consumer see HOW MUCH was
        dropped, not just whether (``truncated``)."""
        with self._lock:
            entries: list[tuple[int, int]] = []
            stack = list(self._root.children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                entries.append((node.stamp, node.fp))
            entries.sort(key=lambda e: (-e[0], e[1]))
            return {
                "version": self._version,
                "block_size": self._pool.block_size,
                "total_nodes": len(entries),
                "truncated": len(entries) > budget,
                "fingerprints": [fp for _, fp in entries[:budget]],
            }


# -- int8 block quantization --------------------------------------------
#
# The kv_dtype="int8" pool stores committed blocks as int8 values plus
# ONE f32 scale per (block, kv head): symmetric absmax over the block's
# (block_size, head_dim) span, scale = absmax / 127 (the standard int8
# affine-free rule; vLLM's kv-cache-dtype=int8 is the design source).
# Per-head granularity is the coarsest that survives GQA: K and V
# magnitudes differ per head by orders of magnitude post-RoPE, while
# within a head one block's spread is tame — per-(block, head) scales
# cost 4 bytes against block_size * head_dim int8 bytes of pages
# (<0.05% at 128x64), so coarser granularity visibly hurts the
# tolerance suite for no measurable capacity win.
#
# jnp-on-purpose, lazily imported: these run INSIDE the engine's jitted
# commit/admit steps. The lazy import keeps this module import-light
# for the router, which pulls prefix_fingerprints into a process that
# may never touch a device.


def quantize_blocks(x):
    """[..., block_size, n_kv, D] float pages -> (int8 pages,
    f32[..., n_kv] scales). Symmetric round-to-nearest; an all-zero
    block (the null block, unwritten pool space) gets scale 1.0 so
    dequantization is exactly 0 rather than 0/0."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-3, -1))  # [..., n_kv]
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    inv = 1.0 / scale[..., None, :, None]
    q = jnp.clip(jnp.round(xf * inv), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_blocks(q, scale, dtype=None):
    """Inverse of :func:`quantize_blocks`: int8 pages [..., bs, n_kv, D]
    x f32 scales [..., n_kv] -> float pages (``dtype`` or f32). The
    multiply order (int8 -> f32, then * scale) is the contract the
    in-kernel dequant mirrors (flash_attention._dequant_tile) — parity
    between a gathered-and-dequantized view and the kernel's in-place
    read depends on both doing bitwise the same math."""
    import jax.numpy as jnp

    out = q.astype(jnp.float32) * scale[..., None, :, None]
    return out if dtype is None else out.astype(dtype)
