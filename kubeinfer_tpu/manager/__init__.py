"""The manager process: control-plane store + reconcile loop + endpoints.

Parity target: reference cmd/manager/main.go:56-204 — flag parsing, metrics
server with auth filtering (:126-138), health/ready probes (:190-197),
leader election option (:162-163), controller startup (:181-184), blocking
run (:200).

Deliberate differences:

- The reference manager is a *client* of the Kubernetes API server; this
  manager **hosts** the control plane itself (``StoreServer``) because the
  framework is standalone. ``--store-connect`` instead joins an external
  store (another manager's, or a test harness'), which is when
  ``--leader-elect`` matters — exactly the reference's HA topology.
- Metrics auth is a static bearer token (``--auth-token-file``); the
  reference's authn/authz delegates to the cluster
  (filters.WithAuthenticationAndAuthorization). Same posture: probes open,
  everything else tokened.
"""

from __future__ import annotations

import logging
import os
import secrets
import socket
import threading
from dataclasses import dataclass, field
from http.server import ThreadingHTTPServer
from typing import Callable

from kubeinfer_tpu import metrics
from kubeinfer_tpu.controller.reconciler import Controller
from kubeinfer_tpu.controlplane.httpstore import (
    RemoteStore,
    StoreServer,
    load_token,
)
from kubeinfer_tpu.controlplane.store import Store
from kubeinfer_tpu.coordination.lease import LeaseManager
from kubeinfer_tpu.utils.clock import Clock, RealClock
from kubeinfer_tpu.analysis.racecheck import make_rlock
from kubeinfer_tpu.utils.httpbase import (
    BaseEndpointHandler,
    token_matches,
    wrap_server_tls,
)

__all__ = ["Manager", "ManagerConfig", "EndpointServer", "load_token"]

log = logging.getLogger(__name__)

MANAGER_LEASE = "kubeinfer-manager"  # leader-election lease name


class EndpointServer:
    """Tiny HTTP endpoint mux for probes and /metrics.

    Routes map path → callable returning (status, content_type, body).
    Paths in ``open_paths`` skip auth (probes must be reachable by the
    platform's health checker without credentials — main.go:190-197).
    """

    def __init__(self, host: str, port: int,
                 routes: dict[str, Callable[[], tuple[int, str, str]]],
                 token: str = "", open_paths: tuple[str, ...] = (),
                 tls_cert: str = "", tls_key: str = "") -> None:
        class Handler(BaseEndpointHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                handler = routes.get(path)
                if handler is None:
                    self.respond(404, "text/plain", "not found\n")
                    return
                if token and path not in open_paths:
                    got = self.headers.get("Authorization", "")
                    if not token_matches(got, token):
                        self.respond(401, "text/plain", "unauthorized\n")
                        return
                try:
                    self.respond(*handler())
                except Exception as e:
                    log.exception("endpoint %s failed", path)
                    self.respond(500, "text/plain", f"error: {e}\n")

        self._httpd = wrap_server_tls(
            ThreadingHTTPServer((host, port), Handler), tls_cert, tls_key
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"endpoints-{port}",
        )

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "EndpointServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


@dataclass
class ManagerConfig:
    """Flag surface (cmd/manager/main.go:65-86 analogue)."""

    store_bind_host: str = "127.0.0.1"
    store_bind_port: int = 18080
    metrics_bind_host: str = "127.0.0.1"
    metrics_bind_port: int = 18081  # ref --metrics-bind-address
    health_bind_host: str = "127.0.0.1"
    health_bind_port: int = 18082  # ref --health-probe-bind-address
    store_connect: str = ""  # join external store instead of hosting
    # durable state directory (journal + snapshots; store.py) — the etcd
    # role. Empty = in-memory (tests, ephemeral demos). Combined with
    # --store-connect it means REPLICA standby: tail the primary's
    # journal into this directory and promote (bind --store-bind-address
    # and serve the replica) when the primary dies — takeover WITH
    # state, the reference's replicated-etcd posture (replica.py).
    data_dir: str = ""
    # sustained primary-unreachable time before a replica standby
    # attempts promotion
    replica_failover_s: float = 5.0
    auth_token: str = ""
    tick_interval_s: float = 1.0
    node_ttl_s: float = 30.0
    leader_elect: bool = False  # ref --leader-elect
    # TLS for every served endpoint (store, metrics, health) and the
    # CA bundle for joining an https store — the reference's secured-
    # metrics posture (main.go:96-103,126-138) with the trust delegated
    # to these files instead of the cluster
    tls_cert_file: str = ""
    tls_key_file: str = ""
    store_ca_file: str = ""
    namespace: str = "default"
    identity: str = ""  # leader-election holder id (default: derived)
    # (duration_s, renew_s, retry_s) override for tests/demos;
    # None = reference timings (election.go:41-43)
    lease_timings: tuple[float, float, float] | None = None
    extra: dict = field(default_factory=dict)


class Manager:
    """Composable manager: store (hosted or joined), controller, endpoints."""

    def __init__(self, cfg: ManagerConfig, clock: Clock | None = None) -> None:
        self.cfg = cfg
        self._clock = clock or RealClock()
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._is_leader = threading.Event()
        self._threads: list[threading.Thread] = []
        # Serializes replica promotion against stop(): promotion runs on
        # the replica thread and publishes store/store_server/_local_store,
        # which stop() tears down — without mutual exclusion a stop racing
        # a promotion can leak a freshly bound StoreServer (socket held
        # forever) or close a store mid-publication. Reentrant because
        # promotion calls _start_election, which takes the lock itself so
        # the leader-elect boot path is equally guarded.
        self._promote_mu = make_rlock("manager.Manager._promote_mu")

        self._replica = None
        if cfg.store_connect:
            self.store_server = None
            self.store = RemoteStore(
                cfg.store_connect, token=cfg.auth_token,
                ca_file=cfg.store_ca_file,
            )
            if cfg.data_dir:
                from kubeinfer_tpu.controlplane.replica import StoreReplica

                # request timeout derives from the grace: an in-flight
                # call is the blackhole-failure detector, so it must not
                # outlast the promotion deadline it feeds
                self._replica = StoreReplica(
                    RemoteStore(
                        cfg.store_connect, token=cfg.auth_token,
                        ca_file=cfg.store_ca_file,
                        request_timeout_s=max(
                            2.0, min(10.0, cfg.replica_failover_s)
                        ),
                    ),
                    data_dir=cfg.data_dir,
                    failover_grace_s=cfg.replica_failover_s,
                )
        else:
            self._local_store = Store(data_dir=cfg.data_dir or None)
            self.store_server = self._host_store_server(self._local_store)
            # The in-process controller bypasses HTTP (same truth, no hop).
            self.store = self._local_store

        self.controller = self._make_controller()
        self._lease: LeaseManager | None = None

        health_routes = {
            "/healthz": lambda: (200, "text/plain", "ok\n"),
            "/readyz": self._readyz,
        }
        if self._replica is not None:
            # standby observability: a replica is never /readyz (it does
            # not reconcile) but operators and the e2e need to know when
            # its journal tail is live before trusting a failover
            health_routes["/replicaz"] = lambda: (
                # promoted replicas stop tailing (they ARE the primary
                # now) — keep reporting 200 or the route reads as a
                # replica that lost its journal tail
                (200, "text/plain", "promoted\n")
                if self._replica.promoted.is_set()
                else (200, "text/plain", "synced\n")
                if self._replica.synced
                else (503, "text/plain", "syncing\n")
            )
        self.health_server = EndpointServer(
            cfg.health_bind_host, cfg.health_bind_port,
            routes=health_routes,
            tls_cert=cfg.tls_cert_file, tls_key=cfg.tls_key_file,
        )
        self.metrics_server = EndpointServer(
            cfg.metrics_bind_host, cfg.metrics_bind_port,
            routes={
                "/metrics": lambda: (
                    200, "text/plain; version=0.0.4",
                    metrics.REGISTRY.render(),
                ),
                "/healthz": lambda: (200, "text/plain", "ok\n"),
            },
            token=cfg.auth_token,
            open_paths=("/healthz",),
            tls_cert=cfg.tls_cert_file, tls_key=cfg.tls_key_file,
        )

    def _host_store_server(self, store: Store) -> StoreServer:
        """The hosted-store wiring, shared by boot-time primaries and
        replica promotion — one home so a promoted replica can never
        serve a differently-configured store than a boot primary."""
        from kubeinfer_tpu.scheduler.backends import solve_service_handler

        cfg = self.cfg
        return StoreServer(
            store, cfg.store_bind_host, cfg.store_bind_port,
            token=cfg.auth_token,
            # POST /solve: the scheduler as an RPC for external
            # controllers (SURVEY §7 step 3 boundary)
            solve_handler=solve_service_handler,
            tls_cert=cfg.tls_cert_file, tls_key=cfg.tls_key_file,
        )

    def _make_controller(self) -> Controller:
        return Controller(
            self.store, clock=self._clock, node_ttl_s=self.cfg.node_ttl_s
        )

    # -- probes -----------------------------------------------------------

    def _readyz(self) -> tuple[int, str, str]:
        if self._ready.is_set():
            return 200, "text/plain", "ok\n"
        return 503, "text/plain", "not ready\n"

    @property
    def store_address(self) -> str:
        if self.store_server is not None:
            return self.store_server.address
        return self.cfg.store_connect

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Manager":
        if self.store_server is not None:
            self.store_server.start()
            log.info("store listening on %s", self.store_server.address)
        self.health_server.start()
        self.metrics_server.start()
        log.info(
            "probes on :%d, metrics on :%d%s",
            self.health_server.port, self.metrics_server.port,
            " (token auth)" if self.cfg.auth_token else " (NO AUTH — dev mode)",
        )
        if not self.cfg.auth_token:
            log.warning(
                "metrics/store endpoints are UNAUTHENTICATED; pass "
                "--auth-token-file for the reference's secured posture"
            )

        if self._replica is not None:
            # Warm standby: replicate only. Election + reconcile start at
            # promotion — pre-promotion this process must never reconcile
            # (the primary leads by construction), and running the
            # election against the remote store would just leave a lease
            # manager pointed at a store that is about to die.
            self._replica.start(self._promote_replica)
            log.info(
                "replica standby: following %s into %s",
                self.cfg.store_connect, self.cfg.data_dir,
            )
            return self

        if self.cfg.leader_elect:
            self._start_election()
        else:
            self._is_leader.set()
            self._start_controller()
        return self

    def _start_election(self) -> None:
        # HA parity (main.go:162-163): reconcile only while holding the
        # manager lease; standby managers take over on expiry.
        timing_kw = {}
        if self.cfg.lease_timings is not None:
            d, rn, rt = self.cfg.lease_timings
            timing_kw = dict(
                duration_s=d, renew_interval_s=rn, retry_interval_s=rt
            )
        # Default identity must be unique across HOSTS AND PROCESSES
        # (two managers agreeing on an identity = both lead =
        # split-brain); hostname+pid+random nonce guarantees it the
        # way the reference's pod name does.
        identity = self.cfg.identity or (
            f"manager-{socket.gethostname()}-{os.getpid()}-"
            f"{secrets.token_hex(4)}"
        )
        # _lease is published under _promote_mu everywhere it is written
        # (_promote_replica swaps it during failover); the boot path must
        # hold the same lock or a stop() racing startup can observe a
        # half-published lease (found by analysis lock-discipline).
        with self._promote_mu:
            if self._stop.is_set():
                return
            self._lease = LeaseManager(
                self.store, self.cfg.namespace, MANAGER_LEASE,
                identity=identity, clock=self._clock, **timing_kw,
            )
            self._lease.start(self._on_elected, self._on_lost)

    def _promote_replica(self) -> bool:
        """Serve the replica on the store frontend address (called from
        the replica thread on sustained primary failure). The BIND is
        the promotion arbitration — the VIP role: losing it to a
        sibling standby returns False and the replica resumes
        following. On success the manager becomes a full primary:
        hosted store, election (the dead leader's replicated lease must
        TTL-expire before this manager wins — CAS continuity makes that
        steal sound), reconcile.

        Runs entirely under ``_promote_mu`` so stop() can't interleave
        with the bind/publish sequence. ``_stop`` is checked both before
        AND after the bind: stop() sets the flag without the lock (it
        must — taking it first would deadlock against this very method
        via the replica-thread join), so the flag can flip while we hold
        the mutex. A dying manager must release the frontend it just
        won, not half-promote."""
        with self._promote_mu:
            if self._stop.is_set():
                return False
            try:
                server = self._host_store_server(self._replica.store)
            except OSError as e:
                log.warning("promotion bind lost (%s); resuming follow", e)
                return False
            if self._stop.is_set():
                # bound but never started: abort(), not shutdown() —
                # shutdown would block on a serve_forever that never ran
                server.abort()
                return False
            prev_store = self.store
            # a never-promoted replica has no _local_store attribute at
            # all (only the self-hosting __init__ branch sets it); None
            # is a safe restore value because stop() gates the close on
            # store_server, which rollback also clears
            prev_local = getattr(self, "_local_store", None)
            prev_controller = self.controller
            started = False
            try:
                self.store_server = server.start()
                started = True
                self._local_store = self._replica.store
                self.store = self._local_store
                self.controller = self._make_controller()
                if self.cfg.leader_elect:
                    self._start_election()
                else:
                    self._is_leader.set()
                    self._start_controller()
            except Exception:
                log.exception(
                    "promotion failed after bind; releasing the frontend"
                )
                if started:
                    server.shutdown()
                else:
                    server.abort()
                # full rollback to follower state: every attribute the
                # try block may have published must revert, or a later
                # promotion attempt (and any reader meanwhile) sees a
                # half-promoted manager pointed at the local store with
                # a controller built against it
                self.store_server = None
                self.store = prev_store
                self._local_store = prev_local
                self.controller = prev_controller
                self._is_leader.clear()
                if self._lease is not None:
                    self._lease.stop()
                    self._lease = None
                return False
            log.warning(
                "promoted: serving replicated store on %s (rv continuity "
                "from the dead primary)", server.address,
            )
            return True

    def _on_elected(self) -> None:
        log.info("manager elected leader")
        self._is_leader.set()
        self._start_controller()

    def _on_lost(self) -> None:
        log.info("manager lost leadership; pausing reconcile")
        self._is_leader.clear()

    def _start_controller(self) -> None:
        t = threading.Thread(
            target=self._controller_loop, daemon=True, name="controller"
        )
        self._threads.append(t)
        t.start()

    def _controller_loop(self) -> None:
        # First tick marks readiness (the controller can serve its caches).
        try:
            self.controller.reconcile_once()
        except Exception:
            log.exception("initial reconcile failed")
        self._ready.set()

        stop_or_demoted = threading.Event()

        def relay():
            while not self._stop.is_set() and self._is_leader.is_set():
                if self._stop.wait(0.2):
                    break
            stop_or_demoted.set()

        relay_t = threading.Thread(target=relay, daemon=True)
        relay_t.start()
        self.controller.run(stop_or_demoted, self.cfg.tick_interval_s)

    def run_forever(self, stop: threading.Event | None = None) -> None:
        """Block until ``stop`` (or self.stop()) — mgr.Start parity."""
        ext = stop or threading.Event()
        while not self._stop.is_set() and not ext.is_set():
            ext.wait(0.5)
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        # Handshake with an in-flight promotion: after this acquire,
        # either the promotion published its server/lease/threads (torn
        # down below) or it observed _stop and unwound itself. _stop MUST
        # be set before acquiring and the lock released before the joins
        # below — _replica.stop() joins the replica thread, which may be
        # inside _promote_replica waiting for this same lock.
        with self._promote_mu:
            pass
        self._is_leader.clear()
        if self._lease is not None:
            self._lease.stop()
        if self._replica is not None:
            # a promoted replica's store is closed below via the hosted
            # store path; an unpromoted one closes its own journal
            self._replica.stop()
        for t in self._threads:
            t.join(timeout=10)
        self.health_server.shutdown()
        self.metrics_server.shutdown()
        if self.store_server is not None:
            self.store_server.shutdown()
            # hosted store: flush+close the durability journal (no-op
            # for in-memory stores)
            self._local_store.close()
