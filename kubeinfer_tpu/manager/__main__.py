"""``python -m kubeinfer_tpu.manager`` — the manager binary.

Flag surface mirrors reference cmd/manager/main.go:65-86:
``--metrics-bind-address`` / ``--health-probe-bind-address`` /
``--leader-elect`` keep their names; ``--store-bind-address`` replaces the
kubeconfig (this manager *hosts* the control plane; see manager package
docstring); ``--auth-token-file`` is the static-token analogue of the
reference's authn/authz filters (main.go:126-138).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from kubeinfer_tpu.manager import Manager, ManagerConfig, load_token


def _split_hostport(s: str, default_host: str = "127.0.0.1") -> tuple[str, int]:
    if ":" not in s:
        return default_host, int(s)
    host, _, port = s.rpartition(":")
    return (host or default_host), int(port)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubeinfer-manager",
        description="kubeinfer_tpu control-plane manager",
    )
    p.add_argument("--store-bind-address", default="127.0.0.1:18080",
                   help="host:port the control-plane store listens on")
    p.add_argument("--store-connect", default="",
                   help="join an external store URL instead of hosting one "
                        "(HA standby topology; enables --leader-elect)")
    p.add_argument("--data-dir", default="",
                   help="directory for durable control-plane state "
                        "(journal + snapshots); empty = in-memory only. "
                        "The etcd role: services, workloads, nodes and "
                        "leases survive a manager restart. With "
                        "--store-connect this makes the manager a "
                        "REPLICA standby: it streams the primary's "
                        "journal here and promotes with full state when "
                        "the primary dies")
    p.add_argument("--replica-failover-s", type=float, default=5.0,
                   help="replica standby: seconds the primary must stay "
                        "unreachable before attempting promotion")
    p.add_argument("--lease-timings", default="",
                   help="manager election lease override as "
                        "'duration,renew,retry' seconds (tests/demos; "
                        "default: reference timings 15/10/2)")
    p.add_argument("--metrics-bind-address", default="127.0.0.1:18081",
                   help="host:port for the /metrics endpoint")
    p.add_argument("--health-probe-bind-address", default="127.0.0.1:18082",
                   help="host:port for /healthz and /readyz")
    p.add_argument("--auth-token-file", default="",
                   help="file holding the bearer token guarding store+metrics")
    p.add_argument("--tick-interval", type=float, default=1.0,
                   help="reconcile fallback tick period, seconds")
    p.add_argument("--node-ttl", type=float, default=30.0,
                   help="node heartbeat TTL before a node is unschedulable")
    p.add_argument("--leader-elect", action="store_true",
                   help="enable manager leader election (for HA managers "
                        "sharing one store)")
    p.add_argument("--identity", default="",
                   help="leader-election holder identity (default: "
                        "hostname-pid-nonce)")
    p.add_argument("--namespace", default="default")
    p.add_argument("--tls-cert-file", default="",
                   help="serve the store/metrics/health endpoints over "
                        "TLS with this certificate (PEM; key via "
                        "--tls-key-file) — the reference's secured-"
                        "endpoint posture (main.go:96-103,126-138)")
    p.add_argument("--tls-key-file", default="",
                   help="private key for --tls-cert-file (PEM)")
    p.add_argument("--store-ca-file", default="",
                   help="CA bundle verifying an https --store-connect")
    p.add_argument("--log-level", default="info",
                   choices=["debug", "info", "warning", "error"])
    return p


def _parse_lease_timings(s: str) -> tuple[float, float, float] | None:
    if not s:
        return None
    parts = s.split(",")
    try:
        if len(parts) != 3:
            raise ValueError
        return tuple(float(x) for x in parts)
    except ValueError:
        raise SystemExit(
            "--lease-timings must be 'duration,renew,retry' seconds"
        ) from None


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper()),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    log = logging.getLogger("manager")

    store_host, store_port = _split_hostport(args.store_bind_address)
    metrics_host, metrics_port = _split_hostport(args.metrics_bind_address)
    health_host, health_port = _split_hostport(args.health_probe_bind_address)
    token = load_token(args.auth_token_file) if args.auth_token_file else ""

    cfg = ManagerConfig(
        store_bind_host=store_host, store_bind_port=store_port,
        metrics_bind_host=metrics_host, metrics_bind_port=metrics_port,
        health_bind_host=health_host, health_bind_port=health_port,
        store_connect=args.store_connect,
        data_dir=args.data_dir,
        replica_failover_s=args.replica_failover_s,
        lease_timings=_parse_lease_timings(args.lease_timings),
        auth_token=token,
        tick_interval_s=args.tick_interval,
        node_ttl_s=args.node_ttl,
        leader_elect=args.leader_elect,
        identity=args.identity,
        namespace=args.namespace,
        tls_cert_file=args.tls_cert_file,
        tls_key_file=args.tls_key_file,
        store_ca_file=args.store_ca_file,
    )

    # Join the multi-host runtime when the fleet env is present (no-op
    # single-process): must happen before any jax usage so the solver's
    # mesh spans all hosts. See kubeinfer_tpu/distributed.py topology.
    from kubeinfer_tpu import distributed

    distributed.initialize()

    stop = threading.Event()

    def on_signal(signum, frame):
        log.info("signal %d: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)

    mgr = Manager(cfg).start()
    log.info("manager started (store %s)", mgr.store_address)
    try:
        mgr.run_forever(stop)
    finally:
        mgr.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
