"""Deterministic fault injection: named points compiled into network edges.

Chaos testing that depends on real packet loss is unreproducible; this
harness makes failure a first-class, *seeded* input instead. Each
network edge calls ``fire("<point>", key=...)`` (and payload edges call
``mangle``) unconditionally — the disarmed fast path is one attribute
read, so production cost is nil — and an armed ``FaultSpec`` decides
per pass, from a seeded RNG, whether to inject.

Fault points (the catalogue docs/ARCHITECTURE.md documents):

- ``store.request``   RemoteStore._req, key ``"METHOD /path"``
- ``agent.heartbeat`` NodeAgent.heartbeat, key = node name
- ``lease.renew``     LeaseManager.try_acquire_or_renew, key = identity
- ``transfer.fetch``  transfer list/download, key = relative path
- ``runtime.health``  RuntimeServer.wait_healthy poll, key = health URL

Modes:

- ``error``:     raise (``kind``: reset | refused | timeout | http_503 /
                 http_500 / http_429 — any ``http_<code>``)
- ``latency``:   sleep ``delay_s`` then proceed
- ``blackhole``: sleep ``delay_s`` then raise TimeoutError — a hung
                 connection whose client-side timeout eventually fires,
                 without actually holding a socket open for the test
- ``corrupt``:   mangle the payload bytes (``mangle()`` edges only)

Arming: programmatic (``REGISTRY.arm(FaultSpec(...))``, tests) or via
``KUBEINFER_FAULTS="point:mode[:k=v[,k=v...]];..."`` +
``KUBEINFER_FAULT_SEED`` in the environment (manual chaos drills; parsed
lazily on first fire so importing this module never costs env parsing).
Same seed + same call sequence → same fault sequence → same outcome;
``REGISTRY.log`` records every firing for determinism assertions.
"""

from __future__ import annotations

import email.message
import io
import os
import random
import threading
import time
import urllib.error
from dataclasses import dataclass, field

from kubeinfer_tpu.metrics.registry import fault_injections_total
from kubeinfer_tpu.analysis.racecheck import fuzz_yield, make_lock
from kubeinfer_tpu.observability import tracing

__all__ = ["FaultSpec", "FaultRegistry", "REGISTRY", "fire", "mangle"]


@dataclass
class FaultSpec:
    """One armed fault. ``match`` (substring of the call-site ``key``)
    narrows a point to specific traffic — e.g. only ``/watch`` long
    polls, only one lease identity. ``after`` skips the first N matching
    passes; ``count`` caps total firings (-1 = unlimited); ``rate``
    fires probabilistically from the registry's seeded RNG."""

    point: str
    mode: str  # error | latency | blackhole | corrupt
    kind: str = "reset"  # error mode: reset|refused|timeout|http_<code>
    match: str = ""
    rate: float = 1.0
    count: int = -1
    after: int = 0
    delay_s: float = 0.05
    # internal counters (per-spec, so independent specs don't interact)
    passes: int = field(default=0, repr=False)
    fired: int = field(default=0, repr=False)


def _make_error(kind: str) -> BaseException:
    if kind == "reset":
        return ConnectionResetError("injected: connection reset")
    if kind == "refused":
        return ConnectionRefusedError("injected: connection refused")
    if kind == "timeout":
        return TimeoutError("injected: timed out")
    if kind.startswith("http_"):
        code = int(kind.split("_", 1)[1])
        return urllib.error.HTTPError(
            "http://injected.invalid/", code, "injected fault",
            email.message.Message(), io.BytesIO(b"{}"),
        )
    raise ValueError(f"unknown fault kind {kind!r}")


class FaultRegistry:
    """Process-global fault state. Tests arm/disarm around scenarios;
    ``seed()`` resets the RNG *and* per-spec counters so a re-armed
    scenario replays bit-identically."""

    def __init__(self) -> None:
        self._mu = make_lock("faultpoints.FaultState._mu")
        self._specs: list[FaultSpec] = []
        self._rng = random.Random(0)
        self._env_checked = False
        self.log: list[tuple[str, str, str]] = []  # (point, mode, key)

    # -- arming -----------------------------------------------------------

    def arm(self, *specs: FaultSpec) -> None:
        with self._mu:
            self._specs.extend(specs)

    def disarm(self, point: str | None = None) -> None:
        with self._mu:
            if point is None:
                self._specs = []
            else:
                self._specs = [s for s in self._specs if s.point != point]

    def seed(self, n: int) -> None:
        with self._mu:
            self._rng.seed(n)
            self.log.clear()
            for s in self._specs:
                s.passes = 0
                s.fired = 0

    def _maybe_load_env(self) -> None:
        if self._env_checked:
            return
        self._env_checked = True
        raw = os.environ.get("KUBEINFER_FAULTS", "")
        if not raw:
            return
        self._rng.seed(int(os.environ.get("KUBEINFER_FAULT_SEED", "0")))
        for part in raw.split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            spec = FaultSpec(point=bits[0], mode=bits[1])
            if len(bits) > 2:
                for kv in bits[2].split(","):
                    k, _, v = kv.partition("=")
                    if k in ("rate", "delay_s"):
                        setattr(spec, k, float(v))
                    elif k in ("count", "after"):
                        setattr(spec, k, int(v))
                    else:
                        setattr(spec, k, v)
            self._specs.append(spec)

    # -- firing -----------------------------------------------------------

    def _select(self, point: str, key: str, modes: tuple[str, ...]):
        # caller holds _mu
        for s in self._specs:
            if s.point != point or s.mode not in modes:
                continue
            if s.match and s.match not in key:
                continue
            s.passes += 1
            if s.passes <= s.after:
                continue
            if s.count >= 0 and s.fired >= s.count:
                continue
            if s.rate < 1.0 and self._rng.random() >= s.rate:
                continue
            s.fired += 1
            self.log.append((point, s.mode, key))
            fault_injections_total.inc(point, s.mode)
            return s
        return None

    def fire(self, point: str, key: str = "") -> None:
        """Action faults (error/latency/blackhole) at a control edge."""
        # every control edge is an interleaving opportunity for the
        # schedule fuzzer, armed or not — no-op outside a fuzz run
        fuzz_yield(f"fault:{point}")
        if not self._specs and self._env_checked:
            return
        with self._mu:
            self._maybe_load_env()
            s = self._select(point, key, ("error", "latency", "blackhole"))
        if s is None:
            return
        # activation event on whatever span is live at the edge (the
        # store client span, a heartbeat span, ...) — chaos-run traces
        # show WHERE each injection landed; outside the lock like the
        # sleeps below
        tracing.add_event("fault", point=point, mode=s.mode, key=key)
        # sleep OUTSIDE the lock: concurrent edges must not serialize on
        # an injected latency
        if s.mode == "latency":
            time.sleep(s.delay_s)
            return
        if s.mode == "blackhole":
            time.sleep(s.delay_s)
            raise TimeoutError(f"injected blackhole at {point}")
        raise _make_error(s.kind)

    def mangle(self, point: str, data: bytes, key: str = "") -> bytes:
        """Corrupt-payload faults at a data edge; returns ``data``
        (possibly truncated/flipped — deterministic under the seed)."""
        if not self._specs and self._env_checked:
            return data
        with self._mu:
            self._maybe_load_env()
            s = self._select(point, key, ("corrupt",))
            if s is None or not data:
                return data
            # truncate at a seeded offset and flip the last byte: breaks
            # JSON/Content-Length framing without ever being a no-op
            cut = self._rng.randrange(len(data)) if len(data) > 1 else 1
            out = bytearray(data[:max(1, cut)])
            out[-1] ^= 0xFF
        tracing.add_event("fault", point=point, mode="corrupt", key=key)
        return bytes(out)


REGISTRY = FaultRegistry()


def fire(point: str, key: str = "") -> None:
    REGISTRY.fire(point, key)


def mangle(point: str, data: bytes, key: str = "") -> bytes:
    return REGISTRY.mangle(point, data, key)
