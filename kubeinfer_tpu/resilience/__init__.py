"""Unified resilience layer: retry policy, circuit breaker, classification.

The reference operator is explicitly fragile under partial failure —
follower.go:117-149 has no retry/resume (SURVEY.md §2 #9), and every
other network edge (heartbeats, lease renewal, watch tailing) simply
propagates the first transport error. This package is the one home for
failure handling so every edge degrades the same way:

- ``RetryPolicy``: bounded attempts with exponential backoff and FULL
  jitter (delay ~ U(0, min(cap, base·2^attempt)) — the AWS-recommended
  variant: under a correlated outage, uniform jitter spreads the retry
  herd where equal-delay backoff synchronizes it), an overall deadline,
  and pluggable retryable-error classification. Per-attempt timeouts
  stay with the transport call (urlopen's ``timeout=``); the policy owns
  the *overall* budget.
- ``CircuitBreaker``: consecutive-failure trip → open (calls fail fast
  with ``BreakerOpenError``) → half-open probe after a cooldown → close
  on success. Fail-fast matters at the node-agent edge: during a store
  outage a tick must cost microseconds, not a full retry schedule, or
  heartbeat staleness accounting itself lags.
- Classifiers: ``transient_http`` (safe for idempotent requests),
  ``connect_failure`` (safe for ANY request — the request provably never
  reached the server), ``is_transport_error`` (breaker accounting: did
  the EDGE fail, regardless of whether this caller may retry).

Retry counts, exhaustions, and breaker transitions are exported through
``metrics.registry`` so degradation is observable, not silent. The
deterministic fault-injection harness that exercises all of this lives
in ``resilience.faultpoints``.

Every consumer passes an ``edge`` label naming the network edge
("store", "lease", "transfer.sync", ...) — docs/ARCHITECTURE.md's
"Failure handling" section is the catalogue.
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import threading
import time
import urllib.error
from dataclasses import dataclass, field
from typing import Any, Callable

from kubeinfer_tpu.analysis.racecheck import guard, make_lock
from kubeinfer_tpu.metrics.registry import (
    breaker_state,
    breaker_transitions_total,
    retries_exhausted_total,
    retry_attempts_total,
)
from kubeinfer_tpu.observability import tracing

__all__ = [
    "BreakerOpenError",
    "CircuitBreaker",
    "RetryPolicy",
    "connect_failure",
    "is_transport_error",
    "transient_http",
]


class BreakerOpenError(ConnectionError):
    """Raised instead of attempting a call while a breaker is open.

    Subclasses ConnectionError (→ OSError) so every existing transient-
    error handler (``except OSError`` in watch loops, agent ticks,
    replica tailing) treats a fast-failed call exactly like the
    connection failure it stands in for.
    """


def _http_code(exc: BaseException) -> int | None:
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code
    return None


# 5xx the server may recover from, plus 429 throttling. 501/505 are
# permanent contract mismatches — retrying cannot help.
_RETRYABLE_HTTP = frozenset({429, 500, 502, 503, 504})


def transient_http(exc: BaseException) -> bool:
    """Retryable for IDEMPOTENT requests (GET/LIST/watch-page).

    Connection-level OSErrors (reset/refused/timeout), protocol-level
    HTTP client errors (short reads, bad status lines), URLErrors
    wrapping either, retryable HTTP status codes, and corrupt JSON
    payloads (a torn response body is a transport failure even though
    json surfaces it as ValueError).
    """
    code = _http_code(exc)
    if code is not None:
        return code in _RETRYABLE_HTTP
    return isinstance(
        exc,
        (OSError, http.client.HTTPException, urllib.error.URLError,
         json.JSONDecodeError),
    )


def connect_failure(exc: BaseException) -> bool:
    """Retryable for NON-idempotent requests (PUT/POST/DELETE): only
    failures that prove the request never reached the server — refused
    connections and name-resolution failures. A reset or timeout after
    connect may have landed the write; those callers rely on
    resourceVersion CAS (a replayed PUT surfaces ConflictError, which
    every store caller already handles as "re-read and retry")."""
    if isinstance(exc, (ConnectionRefusedError, socket.gaierror)):
        return True
    if isinstance(exc, urllib.error.URLError) and not isinstance(
        exc, urllib.error.HTTPError
    ):
        return isinstance(
            exc.reason, (ConnectionRefusedError, socket.gaierror)
        )
    return False


def is_transport_error(exc: BaseException) -> bool:
    """Breaker accounting: did the EDGE fail (vs. the server answering
    with a domain error)? Wider than any retry classifier — a 503 on a
    PUT is not retryable for that caller, but it still counts against
    the edge's health."""
    return transient_http(exc)


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + full jitter, overall deadline, classification.

    ``classify`` decides retry eligibility; non-matching exceptions pass
    through on the first attempt (fail fast on real bugs and domain
    errors). ``deadline_s`` caps the TOTAL time spent including sleeps:
    a retry schedule must never outlive the caller's own failure
    detector (e.g. the replica promotion grace). 0 disables the cap.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    deadline_s: float = 30.0
    classify: Callable[[BaseException], bool] = transient_http

    def backoff(self, attempt: int, rng: random.Random) -> float:
        """Delay after the ``attempt``-th failure (0-based), full jitter."""
        cap = min(self.max_delay_s, self.base_delay_s * (2.0 ** attempt))
        return rng.random() * cap

    def call(
        self,
        fn: Callable[[], Any],
        *,
        edge: str = "",
        breaker: "CircuitBreaker | None" = None,
        rng: random.Random | None = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ) -> Any:
        """Run ``fn`` under this policy. ``rng``/``sleep``/``clock`` are
        injectable so backoff schedules are unit-testable (and so chaos
        scenarios replay identically under a seeded rng)."""
        rng = rng if rng is not None else random
        start = clock()
        attempt = 0
        while True:
            if breaker is not None and not breaker.allow():
                raise BreakerOpenError(
                    f"{edge or 'edge'}: circuit open; failing fast"
                )
            try:
                result = fn()
            except BaseException as exc:  # noqa: BLE001 — reclassified below
                if breaker is not None:
                    if is_transport_error(exc):
                        breaker.record_failure()
                    else:
                        # the server answered (404/409/...): the edge is
                        # healthy even though this call failed
                        breaker.record_success()
                if not self.classify(exc):
                    raise
                attempt += 1
                delay = self.backoff(attempt - 1, rng)
                out_of_budget = (
                    attempt >= self.max_attempts
                    or (
                        self.deadline_s > 0
                        and clock() + delay - start > self.deadline_s
                    )
                )
                if out_of_budget:
                    if edge:
                        retries_exhausted_total.inc(edge)
                    # span events mirror the counters so a chaos run's
                    # trace explains WHICH request burned its budget
                    # (no-ops outside an active span)
                    tracing.add_event(
                        "retries-exhausted", edge=edge, attempts=attempt,
                        error=type(exc).__name__,
                    )
                    raise
                if edge:
                    retry_attempts_total.inc(edge)
                tracing.add_event(
                    "retry", edge=edge, attempt=attempt,
                    error=type(exc).__name__, delay_s=round(delay, 4),
                )
                sleep(delay)
            else:
                if breaker is not None:
                    breaker.record_success()
                return result


# Breaker state encoding for the kubeinfer_breaker_state gauge.
_STATE_CODE = {"closed": 0, "open": 1, "half-open": 2}


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing.

    closed → (``failure_threshold`` consecutive transport failures) →
    open → (``reset_timeout_s`` elapsed) → half-open, which admits ONE
    probe call: success closes, failure re-opens (and restarts the
    cooldown). Thread-safe; one instance guards one edge.
    """

    def __init__(
        self,
        edge: str = "",
        failure_threshold: int = 5,
        reset_timeout_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.edge = edge
        self._threshold = max(1, failure_threshold)
        self._reset = reset_timeout_s
        self._clock = clock
        self._mu = make_lock(f"resilience.CircuitBreaker[{edge}]._mu")
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        guard(self)

    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    def _transition(self, to: str) -> None:
        # caller holds _mu
        if self._state == to:
            return
        self._state = to
        if self.edge:
            breaker_transitions_total.inc(self.edge, to)
            breaker_state.set(self.edge, _STATE_CODE[to])

    def peek(self) -> bool:
        """Would ``allow()`` plausibly admit a call right now?
        Read-only: neither transitions open -> half-open nor consumes
        the half-open probe slot. Candidacy filters (the fleet router's
        scoring loop) use this — calling ``allow()`` from a filter
        would burn the single probe on a replica the filter may not
        even choose, and an unconsumed probe wedges the breaker
        half-open forever."""
        with self._mu:
            if self._state == "closed":
                return True
            if self._state == "open":
                return self._clock() - self._opened_at >= self._reset
            return not self._probing

    def allow(self) -> bool:
        with self._mu:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at >= self._reset:
                    self._transition("half-open")
                    self._probing = True
                    return True
                return False
            # half-open: exactly one in-flight probe decides the state;
            # everyone else keeps failing fast until it reports
            if not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._mu:
            self._failures = 0
            self._probing = False
            self._transition("closed")

    def record_failure(self) -> None:
        with self._mu:
            self._failures += 1
            self._probing = False
            if self._state == "half-open" or self._failures >= self._threshold:
                self._opened_at = self._clock()
                self._transition("open")
