"""Cross-cutting utilities: clock abstraction, logging, env config."""

from kubeinfer_tpu.utils.clock import Clock, RealClock, SimulatedClock

__all__ = ["Clock", "RealClock", "SimulatedClock"]
