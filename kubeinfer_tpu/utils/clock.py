"""Clock abstraction: real time for production, simulated time for tests.

The reference has no testable time source — its lease expiry logic calls
time.Now() directly (internal/agent/coordinator/election.go:144-155) and
consequently has zero tests for election/failover (SURVEY.md §4). Every
time-dependent component here (election, reconciler ticks, heartbeats)
takes a ``Clock`` so distributed-correctness tests can drive lease expiry,
split-brain, and failover deterministically (SURVEY.md §7 hard part 6).
"""

from __future__ import annotations

import threading
import time as _time
from kubeinfer_tpu.analysis.racecheck import make_condition


class Clock:
    """Interface: now() seconds, sleep(), and condition-wait support."""

    def now(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def wait(self, event: threading.Event, timeout: float) -> bool:
        """Wait for ``event`` up to ``timeout`` (simulated clocks advance)."""
        raise NotImplementedError


class RealClock(Clock):
    def now(self) -> float:
        return _time.time()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)

    def wait(self, event: threading.Event, timeout: float) -> bool:
        return event.wait(timeout)


class SimulatedClock(Clock):
    """Deterministic manual clock.

    ``sleep`` blocks the calling thread until another thread ``advance``s the
    clock past the wake deadline — so N threads sleeping on a SimulatedClock
    interleave exactly as their deadlines order them, regardless of host
    scheduling. This is what makes 15s-lease-TTL failover tests run in
    milliseconds.
    """

    def __init__(self, start: float = 0.0):
        self._now = start
        self._cond = make_condition("clock.SimulatedClock._cond")

    def now(self) -> float:
        with self._cond:
            return self._now

    def sleep(self, seconds: float) -> None:
        with self._cond:
            deadline = self._now + seconds
            while self._now < deadline:
                self._cond.wait(timeout=1.0)

    def wait(self, event: threading.Event, timeout: float) -> bool:
        # Block on the same condition variable advance() notifies, so waiters
        # wake immediately on clock advancement; a 50ms real-time fallback
        # poll catches event.set() from threads that don't touch the clock.
        with self._cond:
            deadline = self._now + timeout
            while not event.is_set() and self._now < deadline:
                self._cond.wait(timeout=0.05)
        return event.is_set()

    def advance(self, seconds: float) -> None:
        """Advance simulated time, waking any sleepers whose deadline passed."""
        with self._cond:
            self._now += seconds
            self._cond.notify_all()

    def advance_in_steps(self, seconds: float, step: float = 0.5) -> None:
        """Advance in small steps, yielding the GIL so sleeper threads run
        their loop bodies between steps (models real interleaving)."""
        remaining = seconds
        while remaining > 1e-9:
            s = min(step, remaining)
            self.advance(s)
            remaining -= s
            _time.sleep(0.001)
