"""Shared plumbing for the framework's hand-rolled HTTP endpoints.

Both the control-plane store server and the manager's probe/metrics
endpoints speak HTTP/1.1 with static-bearer-token auth; this module is the
single home for the auth comparison and response writing so a hardening
fix lands everywhere at once.
"""

from __future__ import annotations

import hmac
import logging
import ssl
from http.server import BaseHTTPRequestHandler

from kubeinfer_tpu.observability import tracing

log = logging.getLogger(__name__)


def traceparent_header() -> str | None:
    """W3C ``traceparent`` value for the calling thread's active span,
    or None outside any span. Single injection point for every HTTP
    client in the package (store client, model transfer) so the header
    format lives in one place."""
    ctx = tracing.current_context()
    return ctx.traceparent() if ctx is not None else None


def inject_traceparent(headers: dict) -> dict:
    """Add the current ``traceparent`` (if any) to a mutable header
    dict; returns it for call-site chaining."""
    tp = traceparent_header()
    if tp is not None:
        headers["traceparent"] = tp
    return headers


def wrap_server_tls(httpd, tls_cert: str, tls_key: str = ""):
    """Wrap a bound HTTP server's listening socket in TLS.

    The reference hardens its served endpoints with TLS options and
    delegates trust to the cluster (cmd/manager/main.go:96-103,126-138);
    here the same posture is an ``ssl.SSLContext`` wrap so bearer tokens
    never travel in clear (r2 verdict missing #1). No-op when
    ``tls_cert`` is empty. ``PROTOCOL_TLS_SERVER`` negotiates TLS 1.2+
    only; HTTP/2 concerns don't apply (http.server is HTTP/1.1).
    """
    if not tls_cert:
        return httpd
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(tls_cert, tls_key or None)
    # do_handshake_on_connect=False: with an eager handshake the accept
    # LOOP (one thread) performs it synchronously, so a single client
    # that connects and stalls blocks every other connection. Deferred,
    # the handshake happens on first read — inside the per-connection
    # handler thread, bounded by BaseEndpointHandler.timeout.
    httpd.socket = ctx.wrap_socket(
        httpd.socket, server_side=True, do_handshake_on_connect=False
    )
    return httpd


def client_ssl_context(ca_file: str = "") -> ssl.SSLContext | None:
    """Client-side verification context: ``ca_file`` pins the serving
    cert's CA (self-signed deployments pin the cert itself). Returns
    None when no CA bundle is given — callers pass it straight to
    urllib/http.client, which then use default verification for https
    URLs."""
    if not ca_file:
        return None
    return ssl.create_default_context(cafile=ca_file)


def token_matches(header_value: str, token: str) -> bool:
    """Constant-time bearer-token check.

    Bytes comparison: ``hmac.compare_digest`` raises TypeError on
    non-ASCII *str* inputs, which would kill the connection thread without
    a response; encoding first makes any unicode header merely unequal.
    """
    return hmac.compare_digest(
        header_value.encode("utf-8", "surrogateescape"),
        f"Bearer {token}".encode("utf-8"),
    )


class BaseEndpointHandler(BaseHTTPRequestHandler):
    """HTTP/1.1 handler base: logging redirect + framed responses."""

    protocol_version = "HTTP/1.1"
    # per-connection socket timeout (socketserver applies it before the
    # handler runs): bounds a stalled TLS handshake or a dribbling
    # request so it costs one handler thread for at most this long,
    # never the accept loop (see wrap_server_tls)
    timeout = 60

    def log_message(self, fmt, *args):  # route to logging, not stderr
        log.debug("http: " + fmt, *args)

    def trace_context(self) -> "tracing.SpanContext | None":
        """Extract the inbound W3C trace context, if the client sent
        one; the single extraction point mirroring
        :func:`traceparent_header` on the client side."""
        return tracing.parse_traceparent(self.headers.get("traceparent"))

    def respond(self, code: int, ctype: str, payload: bytes | str,
                headers: dict[str, str] | None = None) -> None:
        data = payload.encode() if isinstance(payload, str) else payload
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        # extra response headers (e.g. Retry-After on a load-shed 503)
        # go between the fixed pair and end_headers, where http.server
        # requires them
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def drop_body(self) -> None:
        """Consume the request body before an early error response —
        unread bytes desync HTTP/1.1 keep-alive (the client's next
        request line would be parsed out of the stale body)."""
        n = int(self.headers.get("Content-Length", 0))
        if n:
            self.rfile.read(n)
