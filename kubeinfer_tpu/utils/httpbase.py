"""Shared plumbing for the framework's hand-rolled HTTP endpoints.

Both the control-plane store server and the manager's probe/metrics
endpoints speak HTTP/1.1 with static-bearer-token auth; this module is the
single home for the auth comparison and response writing so a hardening
fix lands everywhere at once.
"""

from __future__ import annotations

import hmac
import logging
from http.server import BaseHTTPRequestHandler

log = logging.getLogger(__name__)


def token_matches(header_value: str, token: str) -> bool:
    """Constant-time bearer-token check.

    Bytes comparison: ``hmac.compare_digest`` raises TypeError on
    non-ASCII *str* inputs, which would kill the connection thread without
    a response; encoding first makes any unicode header merely unequal.
    """
    return hmac.compare_digest(
        header_value.encode("utf-8", "surrogateescape"),
        f"Bearer {token}".encode("utf-8"),
    )


class BaseEndpointHandler(BaseHTTPRequestHandler):
    """HTTP/1.1 handler base: logging redirect + framed responses."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route to logging, not stderr
        log.debug("http: " + fmt, *args)

    def respond(self, code: int, ctype: str, payload: bytes | str) -> None:
        data = payload.encode() if isinstance(payload, str) else payload
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def drop_body(self) -> None:
        """Consume the request body before an early error response —
        unread bytes desync HTTP/1.1 keep-alive (the client's next
        request line would be parsed out of the stale body)."""
        n = int(self.headers.get("Content-Length", 0))
        if n:
            self.rfile.read(n)
