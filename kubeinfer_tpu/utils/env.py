"""Environment helpers shared by subprocess-spawning code."""

from __future__ import annotations

import os

# Path components identifying this dev box's axon sitecustomize (its
# interpreter-startup jax import dials an experimental remote-TPU relay
# and can wedge child processes for minutes). Component match, not
# substring: unrelated paths merely containing "axon" must survive.
_AXON_COMPONENTS = (".axon_site", "axon")


def scrub_axon_pythonpath(pythonpath: str | None = None) -> str:
    """PYTHONPATH with any axon sitecustomize entries removed.

    One copy of the match rule — bench.py's CPU-fallback re-exec and the
    test suite's subprocess fixtures must agree on it.
    """
    if pythonpath is None:
        pythonpath = os.environ.get("PYTHONPATH", "")
    return os.pathsep.join(
        p for p in pythonpath.split(os.pathsep)
        if p and not any(seg in _AXON_COMPONENTS for seg in p.split(os.sep))
    )
