"""Version-compat aliases for jax API promotions/renames.

jax promoted ``shard_map`` from ``jax.experimental.shard_map`` to the
top-level namespace; images on either side of the promotion must run
the same source (the pallas ``TPUCompilerParams`` -> ``CompilerParams``
rename is handled locally in solver/pallas_kernels.py the same way).
Alias once here so call sites stay uniform — the analyzer's jit-entry
detection matches the bare ``shard_map`` name as well as the dotted
form (analysis/jitlint.py), so linting is unaffected.
"""

from __future__ import annotations

import functools

import jax
from jax import lax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, **kwargs):
        # pre-promotion jax also predates lax.pcast, so bodies that
        # declare varying-ness via pcast (ring attention's scan carries)
        # trip the old replication-type checker — its own error message
        # prescribes check_rep=False. Newer jax keeps full checking.
        kwargs.setdefault("check_rep", False)
        return _shard_map(f, **kwargs)

if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:
    def axis_size(axis_name) -> int:
        # the pre-axis_size idiom: psum of a Python constant
        # constant-folds against the static mesh, so the result is a
        # plain int usable for Python loop bounds inside shard_map
        return lax.psum(1, axis_name)

if hasattr(lax, "pcast"):
    pcast = lax.pcast
else:
    def pcast(x, axis_name, *, to):
        # pre-varying-axes jax has no manual-axes type system for
        # shard_map bodies, so there is nothing to cast — the values are
        # already (implicitly) varying and identity is exact
        return x

__all__ = ["shard_map", "axis_size", "pcast"]
