"""Multi-host distributed runtime: ``jax.distributed`` init + global mesh.

The reference's only inter-node channels are the K8s API server and plain
HTTP (SURVEY.md §2 "Collective comm backend: absent"). The TPU-native
equivalent (SURVEY.md §5) is XLA collectives over ICI within a slice and
DCN across slices; this module owns the process-group bootstrap and the
DCN-aware mesh construction the sharded solver (solver/sharded.py) runs
on.

Topology: the solver's ``jobs`` axis is the data-parallel axis, so it maps
across hosts (DCN) — the per-round cross-shard traffic is a handful of [J]
vectors (10k jobs ≈ 160KB), far below DCN bandwidth, while the [N, J] cost
field never leaves a device. The ``nodes`` axis stays within a host (ICI)
where its min-reductions are cheap. This is the "shard the big axis where
the traffic is small" rule from the scaling-book recipe.

Bootstrap env contract (set by the deployment layer; all optional — absent
means single-process):

  KUBEINFER_COORDINATOR   "host:port" of process 0 (jax.distributed
                          coordinator service)
  KUBEINFER_PROCESS_ID    this process's rank, 0-based
  KUBEINFER_NUM_PROCESSES total process count
  KUBEINFER_LOCAL_DEVICE_IDS  optional comma list restricting local devices

``initialize()`` is idempotent and a no-op without the env, so every
entrypoint can call it unconditionally (manager does at startup).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

log = logging.getLogger(__name__)

_initialized = False


@dataclass(frozen=True)
class DistributedConfig:
    coordinator_address: str
    process_id: int
    num_processes: int
    local_device_ids: tuple[int, ...] | None = None


def config_from_env(env=None) -> DistributedConfig | None:
    """Parse the bootstrap env; None = single-process (no env set).

    Raises ValueError when the env is partially set — a half-configured
    fleet must fail loudly at startup, not deadlock in initialize().
    """
    env = os.environ if env is None else env
    addr = env.get("KUBEINFER_COORDINATOR", "")
    pid = env.get("KUBEINFER_PROCESS_ID", "")
    nproc = env.get("KUBEINFER_NUM_PROCESSES", "")
    if not addr and not pid and not nproc:
        return None
    if not (addr and pid and nproc):
        raise ValueError(
            "partial distributed env: KUBEINFER_COORDINATOR, "
            "KUBEINFER_PROCESS_ID and KUBEINFER_NUM_PROCESSES must all be "
            f"set (got coordinator={addr!r}, id={pid!r}, n={nproc!r})"
        )
    process_id = int(pid)
    num_processes = int(nproc)
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"process id {process_id} outside [0, {num_processes})"
        )
    ids = env.get("KUBEINFER_LOCAL_DEVICE_IDS", "")
    local = tuple(int(x) for x in ids.split(",") if x) if ids else None
    return DistributedConfig(addr, process_id, num_processes, local)


def initialize(cfg: DistributedConfig | None = None, env=None) -> bool:
    """Join the jax.distributed process group (no-op single-process).

    Returns True when running multi-process. Safe to call more than once.
    """
    global _initialized
    if cfg is None:
        cfg = config_from_env(env)
    if cfg is None or cfg.num_processes <= 1:
        return False
    if _initialized:
        return True

    import jax

    kwargs = {}
    if cfg.local_device_ids is not None:
        kwargs["local_device_ids"] = list(cfg.local_device_ids)
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
        **kwargs,
    )
    _initialized = True
    log.info(
        "joined distributed runtime: process %d/%d via %s (%d global devices)",
        cfg.process_id, cfg.num_processes, cfg.coordinator_address,
        jax.device_count(),
    )
    return True


def global_mesh(node_axis: int = 1):
    """(jobs, nodes) mesh over ALL global devices, DCN-aware.

    Single-host: delegates to solver.sharded.make_mesh (contiguous
    devices). Multi-host: hosts stack along the ``jobs`` axis (each host
    contributes its local devices as job-parallel shards), so cross-host
    traffic is the small [J]-vector gathers and ICI keeps the node-axis
    reductions. ``node_axis`` must divide the per-host device count —
    a nodes shard spanning DCN would put the [N, J] field's reduction on
    the slow path, which this constructor refuses to build.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from kubeinfer_tpu.solver.sharded import make_mesh

    if jax.process_count() <= 1:
        return make_mesh(node_axis=node_axis)

    devices = jax.devices()
    per_host = len(devices) // jax.process_count()
    if node_axis > per_host or per_host % node_axis:
        raise ValueError(
            f"node_axis {node_axis} must divide the per-host device count "
            f"{per_host}: a nodes shard must never span DCN"
        )
    # Order devices host-major so the jobs axis tiles hosts contiguously.
    by_host: dict[int, list] = {}
    for d in devices:
        by_host.setdefault(d.process_index, []).append(d)
    ordered = [d for pid in sorted(by_host) for d in by_host[pid]]
    job_axis = len(ordered) // node_axis
    dev_array = np.asarray(ordered).reshape(job_axis, node_axis)
    return Mesh(dev_array, axis_names=("jobs", "nodes"))
