"""Prometheus-style metrics (parity: reference pkg/metrics/metrics.go).

The reference registers six collectors but only wires two
(metrics.go:27-147; SURVEY.md §2 #10 "the other four collectors/helpers are
dead wiring"). Here every collector is recorded by the component that owns
it, plus the new solver metrics the north star requires (per-solve latency,
placement quality).
"""

from kubeinfer_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
    agent_degraded_ticks_total,
    agent_store_stale_seconds,
    auction_fallback_total,
    breaker_state,
    breaker_transitions_total,
    coordinator_elections_total,
    evacuations_total,
    fault_injections_total,
    llmservice_ready_replicas,
    llmservice_total,
    model_download_duration_seconds,
    reconcile_duration_seconds,
    reconcile_total,
    retries_exhausted_total,
    retry_attempts_total,
    solve_duration_seconds,
    solve_placement_ratio,
    solve_problem_size,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "agent_degraded_ticks_total",
    "agent_store_stale_seconds",
    "auction_fallback_total",
    "breaker_state",
    "breaker_transitions_total",
    "coordinator_elections_total",
    "evacuations_total",
    "fault_injections_total",
    "llmservice_ready_replicas",
    "llmservice_total",
    "model_download_duration_seconds",
    "reconcile_duration_seconds",
    "reconcile_total",
    "retries_exhausted_total",
    "retry_attempts_total",
    "solve_duration_seconds",
    "solve_placement_ratio",
    "solve_problem_size",
]
