"""Minimal Prometheus-compatible collectors + text exposition.

Collector set parity with reference pkg/metrics/metrics.go:27-146 (names
keep the kubeinfer_ prefix so reference dashboards port over), plus the
solver observability the north star adds (solve latency / placement
quality / problem size — SURVEY.md §7 capability targets).

Exposition follows the Prometheus text format (what the reference's secured
/metrics endpoint serves); `Registry.render()` is servable as-is.
"""

from __future__ import annotations

from typing import Sequence

from kubeinfer_tpu.analysis.racecheck import make_lock


class Registry:
    def __init__(self) -> None:
        self._lock = make_lock("metrics.Registry._lock")
        self._collectors: list["_Collector"] = []

    def register(self, c: "_Collector") -> None:
        with self._lock:
            # Prometheus servers reject duplicate metric families; catching
            # the collision at registration time (instead of at scrape time,
            # or never — the old behavior silently rendered both) turns a
            # copy-paste collector name into an immediate, attributable error.
            for existing in self._collectors:
                if existing.name == c.name:
                    raise ValueError(
                        f"collector {c.name!r} already registered"
                    )
            self._collectors.append(c)

    def render(self) -> str:
        """Prometheus text exposition of every registered collector."""
        with self._lock:
            collectors = list(self._collectors)
        return "".join(c.render() for c in collectors)

    def reset(self) -> None:
        """Zero all collectors (test isolation)."""
        with self._lock:
            for c in self._collectors:
                c._reset()


REGISTRY = Registry()


def _escape(v: str) -> str:
    # Prometheus label-value escaping: backslash, double-quote, newline.
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{_escape(v)}"' for n, v in zip(names, values))
    return "{" + pairs + "}"


def _fmt_val(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


class _Collector:
    def __init__(
        self,
        name: str,
        help_: str,
        labels: Sequence[str] = (),
        registry: Registry | None = REGISTRY,
    ):
        self.name = name
        self.help = help_
        self.label_names = tuple(labels)
        self._lock = make_lock(f"metrics.{name}._lock")
        if registry is not None:
            registry.register(self)

    def _check(self, label_values: Sequence[str]) -> tuple[str, ...]:
        vals = tuple(str(v) for v in label_values)
        if len(vals) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {vals}"
            )
        return vals

    def _reset(self) -> None:
        raise NotImplementedError

    def render(self) -> str:
        raise NotImplementedError


class Counter(_Collector):
    TYPE = "counter"

    def __init__(self, name, help_, labels=(), registry=REGISTRY):
        super().__init__(name, help_, labels, registry)
        self._values: dict[tuple[str, ...], float] = {}

    def inc(self, *label_values: str, by: float = 1.0) -> None:
        key = self._check(label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(self._check(label_values), 0.0)

    def _reset(self) -> None:
        with self._lock:
            self._values.clear()

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}\n# TYPE {self.name} {self.TYPE}\n"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                out.append(
                    f"{self.name}{_fmt_labels(self.label_names, key)} {_fmt_val(v)}\n"
                )
        return "".join(out)


class Gauge(Counter):
    TYPE = "gauge"

    def set(self, *label_values_then_value) -> None:
        *label_values, value = label_values_then_value
        key = self._check(label_values)
        with self._lock:
            self._values[key] = float(value)

    def delete(self, *label_values: str) -> None:
        """Drop a label series (reference DeleteLLMServiceMetrics analogue)."""
        with self._lock:
            self._values.pop(self._check(label_values), None)


class Histogram(_Collector):
    TYPE = "histogram"

    def __init__(self, name, help_, buckets: Sequence[float], labels=(), registry=REGISTRY):
        super().__init__(name, help_, labels, registry)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}
        self._totals: dict[tuple[str, ...], int] = {}

    def observe(self, *label_values_then_value) -> None:
        *label_values, value = label_values_then_value
        value = float(value)
        key = self._check(label_values)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            # cumulative le semantics: every bucket with bound >= value
            for k in range(len(self.buckets)):
                if value <= self.buckets[k]:
                    counts[k] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, *label_values: str) -> int:
        with self._lock:
            return self._totals.get(self._check(label_values), 0)

    def sum(self, *label_values: str) -> float:
        with self._lock:
            return self._sums.get(self._check(label_values), 0.0)

    def _reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}\n# TYPE {self.name} {self.TYPE}\n"]
        with self._lock:
            for key in sorted(self._totals):
                labels = list(zip(self.label_names, key))
                for bound, c in zip(self.buckets, self._counts[key]):
                    le = _fmt_labels(
                        [n for n, _ in labels] + ["le"],
                        [v for _, v in labels] + [_fmt_val(bound)],
                    )
                    out.append(f"{self.name}_bucket{le} {c}\n")
                inf = _fmt_labels(
                    [n for n, _ in labels] + ["le"],
                    [v for _, v in labels] + ["+Inf"],
                )
                out.append(f"{self.name}_bucket{inf} {self._totals[key]}\n")
                lbl = _fmt_labels(self.label_names, key)
                out.append(f"{self.name}_sum{lbl} {_fmt_val(self._sums[key])}\n")
                out.append(f"{self.name}_count{lbl} {self._totals[key]}\n")
        return "".join(out)


# --- reference collector set (metrics.go:27-146) ---------------------------

llmservice_total = Gauge(
    "kubeinfer_llmservice_total",
    "Number of LLMService resources",  # metrics.go:28-33
)
llmservice_ready_replicas = Gauge(
    "kubeinfer_llmservice_ready_replicas",
    "Ready replicas per LLMService",  # metrics.go:47-53
    labels=("namespace", "name"),
)
coordinator_elections_total = Counter(
    "kubeinfer_coordinator_elections_total",
    "Coordinator elections per lease",  # metrics.go:65-71
    labels=("namespace", "lease"),
)
model_download_duration_seconds = Histogram(
    "kubeinfer_model_download_duration_seconds",
    "Model download duration",  # metrics.go:95-102: 10s*2^k, k=0..9
    buckets=[10.0 * 2**k for k in range(10)],
    labels=("source",),  # hub | coordinator
)
reconcile_total = Counter(
    "kubeinfer_reconcile_total",
    "Reconcile outcomes",  # metrics.go:120-126
    labels=("controller", "result"),
)
evacuations_total = Counter(
    "kubeinfer_evacuations_total",
    "SLO-burn evacuations triggered by the reconciler, by node and "
    "outcome (drained = the drainer confirmed; failed = it raised or "
    "declined — the node stays a candidate next tick)",
    labels=("node", "outcome"),
)
reconcile_duration_seconds = Histogram(
    "kubeinfer_reconcile_duration_seconds",
    "Reconcile duration",  # metrics.go:140-146 (DefBuckets)
    buckets=[0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10],
    labels=("controller",),
)

# --- solver observability (new; north-star requirement) --------------------

solve_duration_seconds = Histogram(
    "kubeinfer_solve_duration_seconds",
    "End-to-end scheduler solve latency (encode + device + readback)",
    buckets=[0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5],
    labels=("policy",),
)
solve_placement_ratio = Gauge(
    "kubeinfer_solve_placement_ratio",
    "Fraction of batched replicas placed in the last solve",
    labels=("policy",),
)
solve_problem_size = Gauge(
    "kubeinfer_solve_problem_size",
    "Last solve problem axes",
    labels=("policy", "axis"),  # axis: jobs | nodes
)
auction_fallback_total = Counter(
    "kubeinfer_auction_fallback_total",
    "jax-auction requests rerouted to jax-greedy because the problem is "
    "not a one-replica-per-node instance (auction would silently "
    "under-place)",
)

# --- resilience observability (resilience/, ISSUE 1) ------------------------
# `edge` names a network edge from docs/ARCHITECTURE.md's failure-handling
# catalogue (store, lease, transfer.sync, ...); `point` names a fault
# point (resilience/faultpoints.py). Degradation must be visible on
# /metrics, never silent.

retry_attempts_total = Counter(
    "kubeinfer_retry_attempts_total",
    "Retried attempts per network edge (beyond each call's first try)",
    labels=("edge",),
)
retries_exhausted_total = Counter(
    "kubeinfer_retries_exhausted_total",
    "Calls that failed after exhausting their retry budget",
    labels=("edge",),
)
breaker_transitions_total = Counter(
    "kubeinfer_breaker_transitions_total",
    "Circuit-breaker state transitions",
    labels=("edge", "to"),  # to: closed | open | half-open
)
breaker_state = Gauge(
    "kubeinfer_breaker_state",
    "Circuit-breaker state (0=closed, 1=open, 2=half-open)",
    labels=("edge",),
)
fault_injections_total = Counter(
    "kubeinfer_fault_injections_total",
    "Faults fired by the chaos harness (resilience/faultpoints.py)",
    labels=("point", "mode"),
)
agent_degraded_ticks_total = Counter(
    "kubeinfer_agent_degraded_ticks_total",
    "Node-agent ticks served from last-known bindings during a store outage",
    labels=("node",),
)
agent_store_stale_seconds = Gauge(
    "kubeinfer_agent_store_stale_seconds",
    "Seconds since the node agent last reached the store (0 = fresh)",
    labels=("node",),
)
