"""SchedulerBackend interface + implementations.

``SolveRequest`` is the host-side problem description (numpy SoA, unpadded):
the same shape the controller builds per tick, the sidecar service ships
over its wire protocol, and both solver tiers consume. ``SolveResult``
carries the assignment plus timing diagnostics the metrics layer exports
(per-solve latency is a first-class product requirement — BASELINE.json's
driver metric is p50 assign latency).

Backend selection: ``get_backend(policy)`` maps the ``schedulerPolicy`` spec
field to an implementation (SURVEY.md §7: "pluggable SchedulerBackend
selected by a new schedulerPolicy spec field").
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import time
from dataclasses import dataclass, field

import numpy as np

from kubeinfer_tpu.api.types import SchedulerPolicy

log = logging.getLogger(__name__)


def _profile_ctx():
    """Per-solve jax.profiler capture, enabled by KUBEINFER_PROFILE_DIR
    (SURVEY.md §5: "add jax.profiler traces from day one"). Each solve
    writes a TensorBoard-loadable trace under <dir>/plugins/profile/...;
    off (the default) costs nothing.
    """
    profile_dir = os.environ.get("KUBEINFER_PROFILE_DIR", "")
    if not profile_dir:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.trace(profile_dir)


@functools.cache
def _packed_solver():
    """Jitted unpack+solve over the single packed buffer (one compile per
    (padded bucket pair, policy); cached like any jit)."""
    import jax

    from kubeinfer_tpu.solver import solve as jax_solve
    from kubeinfer_tpu.solver.problem import unpack_problem

    @functools.partial(
        jax.jit, static_argnames=("J", "N", "policy", "accel", "seeded")
    )
    def solve_packed(
        buf, J: int, N: int, policy: str, accel: str, seeded: bool
    ):
        return jax_solve(
            unpack_problem(buf, J, N), policy=policy, accel=accel,
            seeded=seeded,
        )

    return solve_packed


def request_has_incumbents(
    job_current_node: "np.ndarray | None",
) -> bool:
    """Whether a request carries incumbent placements — the single
    definition both the production backend and bench.py use to decide
    the solver's static ``seeded`` flag (core.solve_greedy), so the
    benchmark always measures the same compiled graph production runs.
    """
    return job_current_node is not None and bool(
        np.any(np.asarray(job_current_node) >= 0)
    )


@dataclass
class SolveRequest:
    """One tick's batched placement problem (host-side, unpadded).

    Conventions match solver.problem.encode_problem_arrays: one job row per
    replica; gang ids couple rows all-or-nothing; current_node (-1 = none)
    feeds move hysteresis; node_cached is a [N, M] model-slot bitmap.
    """

    job_gpu: np.ndarray
    job_mem_gib: np.ndarray
    node_gpu_free: np.ndarray
    node_mem_free_gib: np.ndarray
    job_priority: np.ndarray | None = None
    job_gang: np.ndarray | None = None
    job_model: np.ndarray | None = None
    job_current_node: np.ndarray | None = None
    node_gpu_capacity: np.ndarray | None = None
    node_mem_capacity_gib: np.ndarray | None = None
    node_topology: np.ndarray | None = None
    node_cached: np.ndarray | None = None

    @property
    def num_jobs(self) -> int:
        return int(self.job_gpu.shape[0])

    @property
    def num_nodes(self) -> int:
        return int(self.node_gpu_free.shape[0])


@dataclass
class SolveResult:
    """Assignment (node index per job, -1 unplaced) + diagnostics."""

    assignment: np.ndarray  # i32[J]
    placed: int
    solve_ms: float
    policy: str
    rounds: int = 0
    extras: dict[str, float] = field(default_factory=dict)


def _descending_stable_perm(pr: np.ndarray) -> np.ndarray:
    """Stable descending-priority permutation.

    Priorities are almost always a handful of small integer levels;
    mapping them to uint8 keys lets numpy's stable integer argsort take
    its radix path (~15x cheaper than the f32 mergesort at the 10k-job
    scale, and this sort sits inside the headline pack+solve latency).
    Arbitrary floats (or a >256-level integer range) fall back to the
    f32 argsort. Output is identical to ``np.argsort(-pr,
    kind="stable")`` in all cases.
    """
    if not np.isfinite(pr).all():
        # NaN/inf priorities: the int cast below would emit a numpy
        # RuntimeWarning per solve; mergesort handles them directly
        return np.argsort(-pr, kind="stable")
    pi = pr.astype(np.int64)
    if (pi == pr).all():
        lo, hi = int(pi.min()), int(pi.max())
        if 1 < hi - lo + 1 <= 256:
            # numpy's stable argsort on uint8 keys is a radix sort
            # (~0.02ms at 10k vs ~0.35ms for f32 mergesort)
            return np.argsort((hi - pi).astype(np.uint8), kind="stable")
    return np.argsort(-pr, kind="stable")


class SchedulerBackend:
    """Places a batch of replicas onto nodes."""

    name = "abstract"

    def solve(self, req: SolveRequest) -> SolveResult:
        raise NotImplementedError

    def warmup(self) -> None:
        """Pre-pay one-time costs (jit compiles, library builds) so the
        first production tick stays inside the latency budget."""


class NativeGreedyBackend(SchedulerBackend):
    """Serial first-fit-decreasing via the C++ native tier.

    The comparison baseline for the >=100x claim and the no-accelerator
    fallback. Import is deferred so environments without a compiler can
    still use the JAX backends.
    """

    name = SchedulerPolicy.NATIVE_GREEDY.value

    def warmup(self) -> None:
        from kubeinfer_tpu.native import load_native

        load_native()

    def solve(self, req: SolveRequest) -> SolveResult:
        from kubeinfer_tpu.native import solve_greedy_native

        t0 = time.perf_counter()
        assignment, placed = solve_greedy_native(
            job_gpu=req.job_gpu,
            job_mem_gib=req.job_mem_gib,
            job_priority=req.job_priority,
            job_gang=req.job_gang,
            job_model=req.job_model,
            job_current_node=req.job_current_node,
            node_gpu_free=req.node_gpu_free,
            node_mem_free_gib=req.node_mem_free_gib,
            node_gpu_capacity=req.node_gpu_capacity,
            node_mem_capacity_gib=req.node_mem_capacity_gib,
            node_topology=req.node_topology,
            node_cached=req.node_cached,
        )
        ms = (time.perf_counter() - t0) * 1e3
        # encode_ms is 0 by construction, not by omission: the serial
        # tier has no device, so problem packing is inside solve_ms and
        # there is no separate host->device encode step to report.
        return SolveResult(
            assignment, placed, ms, self.name, extras={"encode_ms": 0.0}
        )


def auction_suitable(req: SolveRequest) -> bool:
    """Is this a one-replica-per-node instance the auction solver is
    built for (core.solve_auction's documented scope)?

    Two disqualifiers, each of which silently under-places under auction:
    - more jobs than nodes: auction places at most one job per node;
    - node-sharing demands: a job asking for at most half a node's chips
      could legally share the node — auction would still dedicate the
      whole node to it.
    """
    if req.num_jobs > req.num_nodes:
        return False
    caps = (
        req.node_gpu_capacity
        if req.node_gpu_capacity is not None
        else req.node_gpu_free
    )
    max_cap = float(np.max(caps)) if caps.size else 0.0
    min_demand = float(np.min(req.job_gpu)) if req.job_gpu.size else 0.0
    return min_demand * 2.0 > max_cap


class JaxBackend(SchedulerBackend):
    """Batched solve on the live JAX backend (TPU when present).

    One instance per policy (greedy/auction). Encoding pads both axes to
    buckets so the jit cache stays small; ``warmup`` pre-compiles the
    bucket a deployment expects to hit.

    ``jax-auction`` is guarded: the auction algorithm only handles
    one-replica-per-node (whole-node-request) instances and ignores
    priority (core.solve_auction docstring). A user-selected auction
    policy on an unsuitable problem auto-falls back to ``jax-greedy``
    with a warning and a metric rather than silently under-placing.
    """

    def __init__(self, policy: SchedulerPolicy):
        if policy not in (SchedulerPolicy.JAX_GREEDY, SchedulerPolicy.JAX_AUCTION):
            raise ValueError(f"not a JAX policy: {policy}")
        self._policy = policy
        self.name = policy.value

    def warmup(
        self, num_jobs: int = 1024, num_nodes: int = 128
    ) -> None:
        if self._policy is SchedulerPolicy.JAX_AUCTION:
            # The warmup problem must be one auction actually accepts
            # (whole-node requests, jobs <= nodes), or the fallback guard
            # fires, the GREEDY kernel compiles instead, and the first
            # production auction solve pays the jit compile in-tick.
            num_jobs = min(num_jobs, num_nodes)
            req = SolveRequest(
                job_gpu=np.full(num_jobs, 8.0, np.float32),
                job_mem_gib=np.full(num_jobs, 64.0, np.float32),
                node_gpu_free=np.full(num_nodes, 8.0, np.float32),
                node_mem_free_gib=np.full(num_nodes, 64.0, np.float32),
            )
        else:
            req = SolveRequest(
                job_gpu=np.ones(num_jobs, np.float32),
                job_mem_gib=np.ones(num_jobs, np.float32),
                node_gpu_free=np.full(num_nodes, 8.0, np.float32),
                node_mem_free_gib=np.full(num_nodes, 64.0, np.float32),
            )
        self.solve(req)

    def solve(self, req: SolveRequest) -> SolveResult:
        import jax

        from kubeinfer_tpu.solver.problem import pack_problem_arrays

        policy = self._policy.value
        fellback = False
        if (
            self._policy is SchedulerPolicy.JAX_AUCTION
            and not auction_suitable(req)
        ):
            from kubeinfer_tpu import metrics

            metrics.auction_fallback_total.inc()
            log.warning(
                "jax-auction requested for a non-whole-node problem "
                "(%d jobs, %d nodes): falling back to jax-greedy to avoid "
                "under-placement",
                req.num_jobs, req.num_nodes,
            )
            policy = SchedulerPolicy.JAX_GREEDY.value
            fellback = True

        t0 = time.perf_counter()
        # Priority-sort the job axis (stable, descending) before packing.
        # The solver's per-node fence means only one fence class can bid
        # in any round; with classes contiguous along J, the Pallas round
        # kernels' per-J-tile early-out skips the inactive ~3/4 of every
        # round's compute and S-field HBM traffic (pallas_kernels.py
        # module docstring). Pure host-side reordering — the solve itself
        # is order-independent up to tie-breaks — undone on the way out.
        perm = None
        if req.job_priority is not None and req.num_jobs > 1:
            pr = np.asarray(req.job_priority)
            if np.any(pr[1:] > pr[:-1]):  # not already descending
                perm = _descending_stable_perm(pr)

        # Single-buffer packing: the whole problem ships in ONE transfer
        # and unpacks with free slices/bitcasts inside the jitted solve —
        # per-field device_puts cost more than the solve itself under a
        # remote PJRT attachment (see problem.py packing layout). The
        # priority permutation is applied inside the padding copies
        # (job_perm) rather than as a separate pass per field.
        buf, _, _, J, N = pack_problem_arrays(
            job_gpu=req.job_gpu,
            job_mem_gib=req.job_mem_gib,
            job_priority=req.job_priority,
            job_gang=req.job_gang,
            job_model=req.job_model,
            job_current_node=req.job_current_node,
            node_gpu_free=req.node_gpu_free,
            node_mem_free_gib=req.node_mem_free_gib,
            node_gpu_capacity=req.node_gpu_capacity,
            node_mem_capacity_gib=req.node_mem_capacity_gib,
            node_topology=req.node_topology,
            node_cached=req.node_cached,
            job_perm=perm,
        )
        t_encode = time.perf_counter()
        # Incumbent seeding/preemption-repair machinery is compiled in
        # only when the request actually carries placements — fresh
        # solves skip ~0.2ms of inert control flow (core.solve_greedy's
        # `seeded` note).
        seeded = request_has_incumbents(req.job_current_node)
        with _profile_ctx():
            out = _packed_solver()(
                buf, J=J, N=N, policy=policy, accel="auto", seeded=seeded
            )
            # ONE host readback for everything the caller needs: each extra
            # sync (a separate np.asarray/int() call) is a full host<->device
            # round trip, which under a remote PJRT relay costs ~65-100ms.
            # Inside the profile context: dispatch is async, so the trace
            # must stay open until this sync or device activity is lost.
            # lint: allow[host-sync] the ONE deliberate readback described above
            node_host, rounds_host = jax.device_get((out.node, out.rounds))
        if perm is None:
            assignment = np.asarray(node_host[: req.num_jobs], np.int32)
        else:
            assignment = np.empty(req.num_jobs, np.int32)
            assignment[perm] = np.asarray(
                node_host[: req.num_jobs], np.int32
            )
        # Padded job rows can't place (valid=False) and padded node columns
        # can't be chosen (valid=False), so clipping to the true axes is
        # lossless; count placed on the clipped view.
        placed = int((assignment >= 0).sum())
        t1 = time.perf_counter()
        extras = {"encode_ms": (t_encode - t0) * 1e3}
        if fellback:
            extras["auction_fallback"] = 1.0
        return SolveResult(
            assignment,
            placed,
            (t1 - t0) * 1e3,
            policy,  # the policy that actually solved (fallback-aware)
            rounds=int(rounds_host),
            extras=extras,
        )


def solve_service_handler(body: dict) -> dict:
    """JSON solve RPC (the /solve endpoint's business logic).

    Request: ``{"policy": "...", "jobs": {gpu, memGib, priority?, gang?,
    model?, currentNode?}, "nodes": {gpuFree, memFreeGib, gpuCapacity?,
    memCapacityGib?, topology?}}`` — arrays as JSON lists, one entry per
    replica/node. Response: assignment + diagnostics. External
    controllers get placements without embedding JAX; the manager's own
    reconciler keeps the in-process fast path.
    """
    if not isinstance(body, dict):
        raise ValueError("body must be a JSON object")
    jobs = body.get("jobs") or {}
    nodes = body.get("nodes") or {}
    if not isinstance(jobs, dict) or not isinstance(nodes, dict):
        raise ValueError("jobs and nodes must be JSON objects")
    if "gpu" not in jobs or "gpuFree" not in nodes:
        raise ValueError("body needs jobs.gpu and nodes.gpuFree arrays")

    def arr(v, dtype, default=None):
        if v is None:
            return default
        return np.asarray(v, dtype)

    J, N = len(jobs["gpu"]), len(nodes["gpuFree"])
    req = SolveRequest(
        job_gpu=np.asarray(jobs["gpu"], np.float32),
        job_mem_gib=arr(
            jobs.get("memGib"), np.float32, np.zeros(J, np.float32)
        ),
        job_priority=arr(jobs.get("priority"), np.float32),
        job_gang=arr(jobs.get("gang"), np.int32),
        job_model=arr(jobs.get("model"), np.int32),
        job_current_node=arr(jobs.get("currentNode"), np.int32),
        node_gpu_free=np.asarray(nodes["gpuFree"], np.float32),
        node_mem_free_gib=arr(
            nodes.get("memFreeGib"), np.float32, np.zeros(N, np.float32)
        ),
        node_gpu_capacity=arr(nodes.get("gpuCapacity"), np.float32),
        node_mem_capacity_gib=arr(nodes.get("memCapacityGib"), np.float32),
        node_topology=arr(nodes.get("topology"), np.int32),
    )
    res = get_backend(body.get("policy", "jax-greedy")).solve(req)
    return {
        "assignment": res.assignment.tolist(),
        "placed": int(res.placed),
        "solveMs": round(res.solve_ms, 3),
        "policy": res.policy,
        "rounds": res.rounds,
    }


_BACKENDS: dict[str, SchedulerBackend] = {}


def get_backend(policy: str | SchedulerPolicy) -> SchedulerBackend:
    """Backend for a schedulerPolicy value; instances are cached (jit
    caches and native lib handles live on them)."""
    policy = SchedulerPolicy(policy)
    backend = _BACKENDS.get(policy.value)
    if backend is None:
        if policy is SchedulerPolicy.NATIVE_GREEDY:
            backend = NativeGreedyBackend()
        else:
            backend = JaxBackend(policy)
        _BACKENDS[policy.value] = backend
    return backend
