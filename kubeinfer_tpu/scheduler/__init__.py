"""Scheduler backends: the pluggable placement layer.

The ``SchedulerBackend`` interface is the north-star architecture from
BASELINE.json: the controller batches pending jobs + node-state vectors into
one dense request per tick and hands it to the backend selected by each
job's ``schedulerPolicy`` — the serial native scorer (baseline/fallback) or
the batched JAX solvers (TPU path).
"""

from kubeinfer_tpu.scheduler.backends import (
    JaxBackend,
    NativeGreedyBackend,
    SchedulerBackend,
    SolveRequest,
    SolveResult,
    get_backend,
)

__all__ = [
    "JaxBackend",
    "NativeGreedyBackend",
    "SchedulerBackend",
    "SolveRequest",
    "SolveResult",
    "get_backend",
]
