"""Control plane: the in-process API server equivalent.

The reference's coordination bus is the Kubernetes API server — every
controller Get/Create/Update and every agent Lease operation is a round-trip
to it (SURVEY.md §3.2, §3.3). kubeinfer_tpu ships its own versioned object
store with the same semantics the components rely on: optimistic concurrency
via resourceVersion, create-conflict atomicity, and watch streams — so the
whole framework runs self-contained (tests = envtest tier) or against a real
cluster later by swapping this module behind the same interface.
"""

from kubeinfer_tpu.controlplane.store import (
    ConflictError,
    NotFoundError,
    AlreadyExistsError,
    Store,
    WatchEvent,
)

__all__ = [
    "AlreadyExistsError",
    "ConflictError",
    "NotFoundError",
    "Store",
    "WatchEvent",
]
