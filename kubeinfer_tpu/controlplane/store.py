"""Versioned, watchable object store — the API-server equivalent.

Semantics held to (what the reference code depends on in the real API server):

- **Create is atomic**: two racing creates of the same key — the exact race
  the reference's lease election leans on ("race-safe: create conflicts
  fail", election.go:72-104) — yield exactly one winner; the loser gets
  ``AlreadyExistsError``.
- **Update is optimistic CAS**: an update must carry the resourceVersion it
  read; a stale version raises ``ConflictError`` (election.go:133-134 relies
  on this for lease stealing).
- **Watches** deliver ordered ADDED/MODIFIED/DELETED events per key after the
  subscription point; the controller's reconcile triggering
  (SetupWithManager/Owns, llmservice_controller.go:316-321) is built on this.

Objects are stored and returned as plain dicts (the typed API's
``to_dict``/``from_dict`` round-trip) and deep-copied at the boundary, so no
caller can mutate the store's truth in place — the same isolation a real API
server's serialization boundary provides.
"""

from __future__ import annotations

import copy
import json
import os
import queue
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Iterator
from kubeinfer_tpu.analysis.racecheck import guard, make_lock

# Journal entries between snapshot compactions. Control-plane mutation
# rates are a few per tick, so compaction is rare; the journal stays
# small enough that replay is never the startup bottleneck.
SNAPSHOT_EVERY = 512


class NotFoundError(KeyError):
    """Object does not exist (the IsNotFound branch, llmservice_controller.go:90)."""


class AlreadyExistsError(ValueError):
    """Create raced with an existing object (lease-creation race, election.go:95-103)."""


class ConflictError(ValueError):
    """Optimistic-concurrency failure: stale resourceVersion (election.go:133-134)."""


@dataclass(frozen=True)
class Key:
    kind: str
    namespace: str
    name: str


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    namespace: str
    name: str
    object: dict[str, Any]
    resource_version: int


@dataclass
class _Watcher:
    q: "queue.Queue[WatchEvent]"
    kind: str | None
    namespace: str | None
    closed: threading.Event = field(default_factory=threading.Event)


class Store:
    """Thread-safe versioned object store with watch streams.

    ``data_dir`` makes the store DURABLE — the etcd role the reference
    gets for free from the API server (SURVEY §1 coordination plane;
    every lease/CR semantic assumes objects outlive the process,
    election.go:72-141, llmservice_controller.go:84-164): every mutation
    appends one fsynced JSONL record to ``journal.jsonl``, compacted
    into an atomically-renamed ``snapshot.json`` every SNAPSHOT_EVERY
    records. On start, snapshot + journal replay restores both the
    objects AND the resourceVersion counter — CAS continuity across
    restarts is load-bearing (lease stealing compares the rv it read,
    election.go:133-134; a reset counter would let a stale holder win).
    Leases are replayed verbatim: the election's TTL check against
    renewTime already classifies a dead leader's lease as expired, so a
    restarted control plane converges without any special-casing.
    """

    def __init__(self, data_dir: str | os.PathLike | None = None) -> None:
        self._lock = make_lock("store.Store._lock")
        self._objects: dict[Key, dict[str, Any]] = {}
        self._rv = 0
        self._watchers: list[_Watcher] = []
        self._data_dir = Path(data_dir) if data_dir else None
        self._durable = self._data_dir is not None
        self._journal_f = None
        self._journal_n = 0
        if self._data_dir is not None:
            self._data_dir.mkdir(parents=True, exist_ok=True)
            self._replay()
            self._journal_f = open(
                self._data_dir / "journal.jsonl", "a", encoding="utf-8"
            )
        guard(self)

    # -- durability ------------------------------------------------------

    def _replay(self) -> None:
        """Restore objects + rv from snapshot.json then journal.jsonl.

        Records at or below the snapshot's rv are skipped (a crash
        between snapshot rename and journal rotation leaves pre-snapshot
        records behind — rv makes replay idempotent). A torn final line
        (crash mid-append) stops the replay at the last durable record.
        """
        snap_path = self._data_dir / "snapshot.json"
        if snap_path.exists():
            snap = json.loads(snap_path.read_text(encoding="utf-8"))
            self._rv = int(snap["rv"])
            for kind, ns, name, obj in snap["objects"]:
                self._objects[Key(kind, ns, name)] = obj
        jpath = self._data_dir / "journal.jsonl"
        if not jpath.exists():
            return
        data = jpath.read_bytes()
        good_end = 0
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break  # torn tail from a crash mid-append
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                break
            good_end += len(line)
            if rec["rv"] <= self._rv:
                continue
            key = Key(rec["kind"], rec["ns"], rec["name"])
            if rec["op"] == "delete":
                self._objects.pop(key, None)
            else:
                self._objects[key] = rec["obj"]
            self._rv = rec["rv"]
            self._journal_n += 1
        if good_end < len(data):
            # Drop the torn/corrupt tail BEFORE reopening for append —
            # appending after it would weld the next record onto the
            # partial line and lose both on the following replay.
            with open(jpath, "r+b") as f:
                f.truncate(good_end)

    def _check_open(self) -> None:
        """Reject mutations on a closed durable store BEFORE any state
        changes: raising from _append after the in-memory write would
        leave memory diverged from the journal (object applied, rv
        consumed, nothing durable) — the review repro for this."""
        if self._durable and self._journal_f is None:
            raise RuntimeError(
                "durable store is closed; mutations would be lost on "
                "restart"
            )

    def _append(
        self, op: str, key: Key, rv: int, obj: dict[str, Any] | None
    ) -> None:
        """Journal one mutation (called under self._lock, AFTER the
        in-memory mutation succeeded). Write+flush only — the fsync
        happens in ``_sync`` AFTER the lock is released, so node
        heartbeats at fleet scale (one update per node per interval on a
        1k-node soak) pay their own disk latency without serializing
        every concurrent get/list/watch behind it. Record order on disk
        is still total (writes happen under the lock); the crash-loss
        window is the mutations whose fsync hadn't completed — each
        mutator only returns to ITS caller after its own fsync."""
        if self._journal_f is None:
            return  # in-memory store (closed-durable rejected up front)
        rec: dict[str, Any] = {
            "op": op, "kind": key.kind, "ns": key.namespace,
            "name": key.name, "rv": rv,
        }
        if obj is not None:
            rec["obj"] = obj
        self._journal_f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._journal_f.flush()
        self._journal_n += 1
        if self._journal_n >= SNAPSHOT_EVERY:
            self._compact()

    def _sync(self) -> None:
        """fsync the journal outside the store lock (see _append). The
        journal file can rotate (compaction) or close concurrently —
        both leave the records already flushed durable via their own
        fsync/close, so the raced handle is safely skipped."""
        f = self._journal_f
        if f is None:
            return
        try:
            os.fsync(f.fileno())
        except ValueError:  # rotated/closed between read and fsync
            pass

    def _compact(self) -> None:
        """Write snapshot atomically (tmp + fsync + rename), then rotate
        the journal. Crash-safe at every boundary: before the rename the
        old snapshot+journal replay; after it, duplicate journal records
        are skipped by rv."""
        snap = {
            "rv": self._rv,
            "objects": [
                [k.kind, k.namespace, k.name, o]
                for k, o in self._objects.items()
            ],
        }
        tmp = self._data_dir / "snapshot.json.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._data_dir / "snapshot.json")
        # The rename's dirent update must hit disk before the journal is
        # truncated: otherwise a crash can persist the truncate but lose the
        # rename, dropping journaled records and regressing rv (the CAS /
        # lease-stealing continuity this store exists to protect).
        dfd = os.open(self._data_dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._journal_f.close()
        self._journal_f = open(
            self._data_dir / "journal.jsonl", "w", encoding="utf-8"
        )
        self._journal_n = 0

    # -- replication (follower surface) ----------------------------------
    #
    # The reference's control plane rides a REPLICATED etcd: a manager
    # (or its node) can vanish and every CR/lease survives with no
    # shared disk (election.go:72-141, llmservice_controller.go:84
    # assume the API server outlives any client). These three methods
    # are the follower half of that: a standby tails the primary's
    # watch stream and applies events VERBATIM — same objects, same
    # resourceVersion counter — into its own durable store, so a
    # promoted standby carries full state and CAS/lease-steal
    # continuity without shared disk.

    def dump(self) -> tuple[int, list]:
        """Consistent full-state copy for follower bootstrap/resync:
        (rv, [[kind, ns, name, obj], ...]) — the snapshot wire shape."""
        with self._lock:
            return self._rv, [
                [k.kind, k.namespace, k.name, copy.deepcopy(o)]
                for k, o in self._objects.items()
            ]

    def load_dump(self, rv: int, objects: list,
                  allow_regress: bool = False) -> None:
        """Replace local state with a primary's dump. Durability goes
        through _compact (atomic snapshot + journal rotation), so a
        crash mid-load replays either the old state or the new one,
        never a blend. rv normally only moves FORWARD; a follower
        adopting a new primary whose history is shorter than its own
        passes ``allow_regress=True`` — the serving primary's stream is
        the fleet's truth, and the snapshot rotation makes the lower
        counter consistent on disk (replay starts from the snapshot
        rv)."""
        with self._lock:
            self._check_open()
            if rv < self._rv and not allow_regress:
                raise ValueError(
                    f"dump rv {rv} behind local rv {self._rv}; refusing "
                    "to regress the CAS counter"
                )
            self._objects = {
                Key(kind, ns, name): copy.deepcopy(obj)
                for kind, ns, name, obj in objects
            }
            self._rv = rv
            if self._durable:
                self._compact()

    def apply_replicated(
        self, op: str, kind: str, namespace: str, name: str,
        obj: dict[str, Any] | None, rv: int,
    ) -> None:
        """Apply one replicated event verbatim (no new rv is minted —
        the primary already assigned it). Idempotent on replayed rvs,
        monotone by construction; journaled and fanned out to local
        watchers like any native mutation."""
        key = Key(kind, namespace, name)
        with self._lock:
            self._check_open()
            if rv <= self._rv:
                return  # replayed tail after a resync — already applied
            self._rv = rv
            if op == "DELETED":
                prev = self._objects.pop(key, None)
                self._append("delete", key, rv, None)
                if prev is not None:
                    self._notify("DELETED", kind, namespace, name, prev, rv)
            else:
                self._objects[key] = copy.deepcopy(obj)
                self._append(
                    "update" if op == "MODIFIED" else "create",
                    key, rv, self._objects[key],
                )
                self._notify(
                    "MODIFIED" if op == "MODIFIED" else "ADDED",
                    kind, namespace, name, self._objects[key], rv,
                )
        self._sync()

    def close(self) -> None:
        """Flush and close the journal. Further mutations on a durable
        store raise (RuntimeError from _append) rather than silently
        succeeding in memory only — an acknowledged-but-undurable write
        is exactly the CAS-continuity hole the journal exists to close.
        Call only at process shutdown, after all mutators stopped."""
        with self._lock:
            if self._journal_f is not None:
                self._journal_f.close()
                self._journal_f = None

    # -- helpers ---------------------------------------------------------

    @staticmethod
    def _meta(obj: dict[str, Any]) -> dict[str, Any]:
        return obj.setdefault("metadata", {})

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _notify(
        self, type_: str, kind: str, namespace: str, name: str,
        obj: dict[str, Any], rv: int,
    ) -> None:
        """Fan out one event. ``obj`` is the store's own dict; each delivered
        watcher gets its own deep copy (consumers may normalize events in
        place and must not see each other's — or the store's — state), and
        nothing is copied when no watcher matches."""
        for w in self._watchers:
            if w.closed.is_set():
                continue
            if w.kind is not None and w.kind != kind:
                continue
            if w.namespace is not None and w.namespace != namespace:
                continue
            w.q.put(
                WatchEvent(type_, kind, namespace, name, copy.deepcopy(obj), rv)
            )

    # -- CRUD ------------------------------------------------------------

    def create(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        obj = copy.deepcopy(obj)
        meta = self._meta(obj)
        name = meta.get("name", "")
        namespace = meta.get("namespace", "default")
        if not name:
            raise ValueError("metadata.name is required")
        key = Key(kind, namespace, name)
        with self._lock:
            self._check_open()
            if key in self._objects:
                raise AlreadyExistsError(f"{kind} {namespace}/{name} already exists")
            rv = self._next_rv()
            meta["namespace"] = namespace
            meta["resourceVersion"] = rv
            meta.setdefault("generation", 1)
            self._objects[key] = obj
            self._append("create", key, rv, obj)
            self._notify("ADDED", kind, namespace, name, obj, rv)
            out = copy.deepcopy(obj)
        self._sync()
        return out

    def get(self, kind: str, name: str, namespace: str = "default") -> dict[str, Any]:
        key = Key(kind, namespace, name)
        with self._lock:
            obj = self._objects.get(key)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(obj)

    def update(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        """CAS update: obj.metadata.resourceVersion must match the stored one."""
        obj = copy.deepcopy(obj)
        meta = self._meta(obj)
        name = meta.get("name", "")
        namespace = meta.get("namespace", "default")
        key = Key(kind, namespace, name)
        with self._lock:
            self._check_open()
            current = self._objects.get(key)
            if current is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            meta["namespace"] = namespace
            expected = current["metadata"].get("resourceVersion", 0)
            got = meta.get("resourceVersion", 0)
            if got != expected:
                raise ConflictError(
                    f"{kind} {namespace}/{name}: resourceVersion {got} != {expected}"
                )
            rv = self._next_rv()
            meta["resourceVersion"] = rv
            self._objects[key] = obj
            self._append("update", key, rv, obj)
            self._notify("MODIFIED", kind, namespace, name, obj, rv)
            out = copy.deepcopy(obj)
        self._sync()
        return out

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        key = Key(kind, namespace, name)
        with self._lock:
            self._check_open()
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            rv = self._next_rv()
            self._append("delete", key, rv, None)
            self._notify("DELETED", kind, namespace, name, obj, rv)
        self._sync()

    def list(self, kind: str, namespace: str | None = None) -> list[dict[str, Any]]:
        with self._lock:
            out = [
                copy.deepcopy(o)
                for k, o in self._objects.items()
                if k.kind == kind and (namespace is None or k.namespace == namespace)
            ]
        out.sort(key=lambda o: (o["metadata"]["namespace"], o["metadata"]["name"]))
        return out

    # -- watch -----------------------------------------------------------

    def watch(
        self, kind: str | None = None, namespace: str | None = None
    ) -> "Watch":
        w = _Watcher(q=queue.Queue(), kind=kind, namespace=namespace)
        with self._lock:
            self._watchers.append(w)
        return Watch(self, w)

    def _close_watch(self, w: _Watcher) -> None:
        w.closed.set()
        with self._lock:
            if w in self._watchers:
                self._watchers.remove(w)


class Watch:
    """Handle on a watch stream. Iterate or poll with ``next_event``."""

    def __init__(self, store: Store, watcher: _Watcher):
        self._store = store
        self._w = watcher

    def next_event(self, timeout: float | None = None) -> WatchEvent | None:
        try:
            return self._w.q.get(timeout=timeout)
        except queue.Empty:
            return None

    def drain(self) -> list[WatchEvent]:
        out = []
        while True:
            try:
                out.append(self._w.q.get_nowait())
            except queue.Empty:
                return out

    def close(self) -> None:
        self._store._close_watch(self._w)

    def __iter__(self) -> Iterator[WatchEvent]:
        while not self._w.closed.is_set():
            ev = self.next_event(timeout=0.1)
            if ev is not None:
                yield ev


def retry_on_conflict(
    fn: Callable[[], Any], attempts: int = 5
) -> Any:
    """Run a read-modify-write closure, retrying on ConflictError.

    The standard client-side pattern for status updates under contention
    (the reference's Status().Update can fail the same way,
    llmservice_controller.go:164).
    """
    last: Exception | None = None
    for _ in range(attempts):
        try:
            return fn()
        except ConflictError as e:  # re-read inside fn on next attempt
            last = e
    assert last is not None
    raise last
