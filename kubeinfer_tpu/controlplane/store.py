"""Versioned, watchable object store — the API-server equivalent.

Semantics held to (what the reference code depends on in the real API server):

- **Create is atomic**: two racing creates of the same key — the exact race
  the reference's lease election leans on ("race-safe: create conflicts
  fail", election.go:72-104) — yield exactly one winner; the loser gets
  ``AlreadyExistsError``.
- **Update is optimistic CAS**: an update must carry the resourceVersion it
  read; a stale version raises ``ConflictError`` (election.go:133-134 relies
  on this for lease stealing).
- **Watches** deliver ordered ADDED/MODIFIED/DELETED events per key after the
  subscription point; the controller's reconcile triggering
  (SetupWithManager/Owns, llmservice_controller.go:316-321) is built on this.

Objects are stored and returned as plain dicts (the typed API's
``to_dict``/``from_dict`` round-trip) and deep-copied at the boundary, so no
caller can mutate the store's truth in place — the same isolation a real API
server's serialization boundary provides.
"""

from __future__ import annotations

import copy
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator


class NotFoundError(KeyError):
    """Object does not exist (the IsNotFound branch, llmservice_controller.go:90)."""


class AlreadyExistsError(ValueError):
    """Create raced with an existing object (lease-creation race, election.go:95-103)."""


class ConflictError(ValueError):
    """Optimistic-concurrency failure: stale resourceVersion (election.go:133-134)."""


@dataclass(frozen=True)
class Key:
    kind: str
    namespace: str
    name: str


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    namespace: str
    name: str
    object: dict[str, Any]
    resource_version: int


@dataclass
class _Watcher:
    q: "queue.Queue[WatchEvent]"
    kind: str | None
    namespace: str | None
    closed: threading.Event = field(default_factory=threading.Event)


class Store:
    """Thread-safe versioned object store with watch streams."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._objects: dict[Key, dict[str, Any]] = {}
        self._rv = 0
        self._watchers: list[_Watcher] = []

    # -- helpers ---------------------------------------------------------

    @staticmethod
    def _meta(obj: dict[str, Any]) -> dict[str, Any]:
        return obj.setdefault("metadata", {})

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _notify(
        self, type_: str, kind: str, namespace: str, name: str,
        obj: dict[str, Any], rv: int,
    ) -> None:
        """Fan out one event. ``obj`` is the store's own dict; each delivered
        watcher gets its own deep copy (consumers may normalize events in
        place and must not see each other's — or the store's — state), and
        nothing is copied when no watcher matches."""
        for w in self._watchers:
            if w.closed.is_set():
                continue
            if w.kind is not None and w.kind != kind:
                continue
            if w.namespace is not None and w.namespace != namespace:
                continue
            w.q.put(
                WatchEvent(type_, kind, namespace, name, copy.deepcopy(obj), rv)
            )

    # -- CRUD ------------------------------------------------------------

    def create(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        obj = copy.deepcopy(obj)
        meta = self._meta(obj)
        name = meta.get("name", "")
        namespace = meta.get("namespace", "default")
        if not name:
            raise ValueError("metadata.name is required")
        key = Key(kind, namespace, name)
        with self._lock:
            if key in self._objects:
                raise AlreadyExistsError(f"{kind} {namespace}/{name} already exists")
            rv = self._next_rv()
            meta["namespace"] = namespace
            meta["resourceVersion"] = rv
            meta.setdefault("generation", 1)
            self._objects[key] = obj
            self._notify("ADDED", kind, namespace, name, obj, rv)
            return copy.deepcopy(obj)

    def get(self, kind: str, name: str, namespace: str = "default") -> dict[str, Any]:
        key = Key(kind, namespace, name)
        with self._lock:
            obj = self._objects.get(key)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(obj)

    def update(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        """CAS update: obj.metadata.resourceVersion must match the stored one."""
        obj = copy.deepcopy(obj)
        meta = self._meta(obj)
        name = meta.get("name", "")
        namespace = meta.get("namespace", "default")
        key = Key(kind, namespace, name)
        with self._lock:
            current = self._objects.get(key)
            if current is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            meta["namespace"] = namespace
            expected = current["metadata"].get("resourceVersion", 0)
            got = meta.get("resourceVersion", 0)
            if got != expected:
                raise ConflictError(
                    f"{kind} {namespace}/{name}: resourceVersion {got} != {expected}"
                )
            rv = self._next_rv()
            meta["resourceVersion"] = rv
            self._objects[key] = obj
            self._notify("MODIFIED", kind, namespace, name, obj, rv)
            return copy.deepcopy(obj)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        key = Key(kind, namespace, name)
        with self._lock:
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            rv = self._next_rv()
            self._notify("DELETED", kind, namespace, name, obj, rv)

    def list(self, kind: str, namespace: str | None = None) -> list[dict[str, Any]]:
        with self._lock:
            out = [
                copy.deepcopy(o)
                for k, o in self._objects.items()
                if k.kind == kind and (namespace is None or k.namespace == namespace)
            ]
        out.sort(key=lambda o: (o["metadata"]["namespace"], o["metadata"]["name"]))
        return out

    # -- watch -----------------------------------------------------------

    def watch(
        self, kind: str | None = None, namespace: str | None = None
    ) -> "Watch":
        w = _Watcher(q=queue.Queue(), kind=kind, namespace=namespace)
        with self._lock:
            self._watchers.append(w)
        return Watch(self, w)

    def _close_watch(self, w: _Watcher) -> None:
        w.closed.set()
        with self._lock:
            if w in self._watchers:
                self._watchers.remove(w)


class Watch:
    """Handle on a watch stream. Iterate or poll with ``next_event``."""

    def __init__(self, store: Store, watcher: _Watcher):
        self._store = store
        self._w = watcher

    def next_event(self, timeout: float | None = None) -> WatchEvent | None:
        try:
            return self._w.q.get(timeout=timeout)
        except queue.Empty:
            return None

    def drain(self) -> list[WatchEvent]:
        out = []
        while True:
            try:
                out.append(self._w.q.get_nowait())
            except queue.Empty:
                return out

    def close(self) -> None:
        self._store._close_watch(self._w)

    def __iter__(self) -> Iterator[WatchEvent]:
        while not self._w.closed.is_set():
            ev = self.next_event(timeout=0.1)
            if ev is not None:
                yield ev


def retry_on_conflict(
    fn: Callable[[], Any], attempts: int = 5
) -> Any:
    """Run a read-modify-write closure, retrying on ConflictError.

    The standard client-side pattern for status updates under contention
    (the reference's Status().Update can fail the same way,
    llmservice_controller.go:164).
    """
    last: Exception | None = None
    for _ in range(attempts):
        try:
            return fn()
        except ConflictError as e:  # re-read inside fn on next attempt
            last = e
    assert last is not None
    raise last
