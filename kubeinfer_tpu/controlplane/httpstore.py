"""HTTP transport for the control-plane store — the API-server wire protocol.

The reference control plane is the Kubernetes API server: the manager and
every agent reach it over HTTPS with ServiceAccount bearer tokens
(cmd/agent/main.go:56-63 in-cluster config; cmd/manager/main.go:157
GetConfigOrDie). kubeinfer_tpu is standalone, so the manager process *hosts*
the store (``StoreServer``) and agents/CLIs reach it through ``RemoteStore``,
which implements the exact same interface as the in-process ``Store``
(create/get/update/delete/list/watch) so every component runs unchanged
in-process (tests, e2e slice) or cross-process (real deployment).

Protocol (JSON over HTTP/1.1):

- ``GET  /healthz``                         liveness, unauthenticated
- ``GET  /apis/{kind}``                     list (``?namespace=`` optional)
- ``POST /apis/{kind}``                     create  → 409 already_exists
- ``GET  /apis/{kind}/{ns}/{name}``         get     → 404
- ``PUT  /apis/{kind}/{ns}/{name}``         CAS update → 409 conflict
- ``DELETE /apis/{kind}/{ns}/{name}``       delete  → 404
- ``GET  /rv``                              current resourceVersion
- ``GET  /watch?since=RV&timeout=S[&kind=&namespace=]``
  long-poll: events with resourceVersion > since, or ``[]`` on timeout.

Auth parity: the reference secures its endpoints with token authn/authz
filters (cmd/manager/main.go:126-138). Here a static bearer token guards
every route except /healthz; no token configured = open (dev mode),
mirroring ``--metrics-secure=false``.

Admission parity: LLMService writes are schema-validated server-side
(the CRD schema the reference API server enforces,
config/crd/bases/ai.ruijie.io_llmservices.yaml:45-60).
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import ThreadingHTTPServer
from typing import Any

from kubeinfer_tpu.api.types import LLMService, ValidationError
from kubeinfer_tpu.controlplane.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    Store,
    WatchEvent,
)
from kubeinfer_tpu.resilience import (
    CircuitBreaker,
    RetryPolicy,
    connect_failure,
    transient_http,
)
from kubeinfer_tpu.resilience import faultpoints
from kubeinfer_tpu.analysis.racecheck import make_condition
from kubeinfer_tpu.observability import tracing
from kubeinfer_tpu.utils.httpbase import (
    BaseEndpointHandler,
    client_ssl_context,
    token_matches,
    traceparent_header,
    wrap_server_tls,
)

log = logging.getLogger(__name__)

_SERVER_TRACER = tracing.get_tracer("store")
_CLIENT_TRACER = tracing.get_tracer("store-client")

EVENT_LOG_SIZE = 65536  # ring of recent events served to long-pollers


def load_token(path: str) -> str:
    """Read a bearer token from a file (one copy for manager/agent/ctl)."""
    with open(path, "r", encoding="utf-8") as f:
        return f.read().strip()


class StoreServer:
    """Serves a Store over HTTP and republishes its watch stream.

    ``solve_handler`` (optional) exposes the scheduler as an RPC:
    ``POST /solve`` with a JSON problem → assignment (SURVEY.md §7 step
    3's solve-service boundary; the in-process dispatch the manager's
    own reconciler uses stays the fast path — this endpoint is for
    EXTERNAL controllers that want placements without embedding JAX).
    """

    def __init__(self, store: Store, host: str = "127.0.0.1", port: int = 0,
                 token: str = "", solve_handler=None,
                 tls_cert: str = "", tls_key: str = "") -> None:
        self._store = store
        self._token = token
        self._tls = bool(tls_cert)
        self._solve_handler = solve_handler
        # Event ring: long-pollers replay from here by resourceVersion.
        self._events: collections.deque[WatchEvent] = collections.deque(
            maxlen=EVENT_LOG_SIZE
        )
        self._events_cond = make_condition("httpstore.StoreServer._events_cond")
        self._watch = store.watch()
        self._pump = threading.Thread(
            target=self._pump_events, daemon=True, name="store-event-pump"
        )

        server = self

        class Handler(BaseEndpointHandler):
            def _send(self, code: int, body: dict | list) -> None:
                self.respond(code, "application/json", json.dumps(body))

            def _authed(self) -> bool:
                if not server._token:
                    return True
                got = self.headers.get("Authorization", "")
                return token_matches(got, server._token)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def _drop_body(self) -> None:
                self.drop_body()

            def _route(self, method: str) -> None:
                parsed = urllib.parse.urlparse(self.path)
                # unquote AFTER splitting: %2F inside a name must not
                # become a path separator
                parts = [
                    urllib.parse.unquote(p)
                    for p in parsed.path.split("/") if p
                ]
                q = urllib.parse.parse_qs(parsed.query)
                if parts == ["healthz"]:
                    self._drop_body()
                    self._send(200, {"status": "ok"})
                    return
                if not self._authed():
                    self._drop_body()
                    self._send(401, {"error": "unauthorized"})
                    return
                if parts == ["debug", "spans"] and method == "GET":
                    # recorded spans as Chrome trace-event JSON (open in
                    # Perfetto; docs/OBSERVABILITY.md). Authenticated:
                    # traces carry request paths and object names.
                    self._drop_body()
                    tid = q.get("trace_id", [None])[0]
                    self._send(200, tracing.RECORDER.to_chrome_trace(tid))
                    return
                try:
                    if parts == ["rv"] and method == "GET":
                        self._send(200, {"resourceVersion": server._store._rv})
                    elif parts == ["watch"] and method == "GET":
                        since = int(q.get("since", ["0"])[0])
                        timeout = min(float(q.get("timeout", ["30"])[0]), 300.0)
                        kind = q.get("kind", [None])[0]
                        ns = q.get("namespace", [None])[0]
                        evs, rv, store_rv, oldest = server._poll_events(
                            since, timeout, kind, ns
                        )
                        self._send(200, {
                            "resourceVersion": rv,
                            # the store's ACTUAL counter, unclamped by
                            # `since` (the rv above is a watch cursor,
                            # floored at the caller's position): a
                            # follower compares this against its local
                            # cursor to detect a primary whose history
                            # is BEHIND it (restart with fresh state)
                            "storeRv": store_rv,
                            # earliest rv still in the event ring (0 =
                            # empty): a follower whose `since` predates
                            # it cannot prove continuity and must full-
                            # resync via /dump
                            "oldestEvent": oldest,
                            "events": [
                                {
                                    "type": e.type, "kind": e.kind,
                                    "namespace": e.namespace, "name": e.name,
                                    "object": e.object,
                                    "resourceVersion": e.resource_version,
                                }
                                for e in evs
                            ],
                        })
                    elif parts == ["dump"] and method == "GET":
                        rv, objects = server._store.dump()
                        self._send(200, {
                            "resourceVersion": rv, "objects": objects,
                        })
                    elif parts == ["solve"] and method == "POST":
                        if server._solve_handler is None:
                            self._drop_body()
                            self._send(404, {"error": "no solver attached"})
                        else:
                            self._send(
                                200, server._solve_handler(self._body())
                            )
                    elif len(parts) == 2 and parts[0] == "apis":
                        kind = parts[1]
                        if method == "GET":
                            ns = q.get("namespace", [None])[0]
                            self._send(200, server._store.list(kind, ns))
                        elif method == "POST":
                            obj = server._admit(kind, self._body())
                            self._send(201, server._store.create(kind, obj))
                        else:
                            self._drop_body()
                            self._send(405, {"error": "method not allowed"})
                    elif len(parts) == 4 and parts[0] == "apis":
                        kind, ns, name = parts[1], parts[2], parts[3]
                        if method == "GET":
                            self._send(200, server._store.get(kind, name, ns))
                        elif method == "PUT":
                            obj = server._admit(kind, self._body())
                            self._send(200, server._store.update(kind, obj))
                        elif method == "DELETE":
                            server._store.delete(kind, name, ns)
                            self._send(200, {"status": "deleted"})
                        else:
                            self._drop_body()
                            self._send(405, {"error": "method not allowed"})
                    else:
                        self._drop_body()
                        self._send(404, {"error": "no such route"})
                except NotFoundError as e:
                    self._send(404, {"error": "not_found", "message": str(e)})
                except AlreadyExistsError as e:
                    self._send(409, {"error": "already_exists", "message": str(e)})
                except ConflictError as e:
                    self._send(409, {"error": "conflict", "message": str(e)})
                except (ValidationError, ValueError, json.JSONDecodeError) as e:
                    self._send(400, {"error": "invalid", "message": str(e)})
                except Exception as e:  # don't kill the connection thread
                    log.exception("httpstore: internal error")
                    self._send(500, {"error": "internal", "message": str(e)})

            def _traced(self, method: str) -> None:
                # server-side span per request, joined to the caller's
                # trace via the inbound traceparent header (path as an
                # attr, not the span name — names stay low-cardinality)
                with _SERVER_TRACER.span(
                    f"store {method}", parent=self.trace_context(),
                    path=self.path,
                ):
                    self._route(method)

            def do_GET(self):
                self._traced("GET")

            def do_POST(self):
                self._traced("POST")

            def do_PUT(self):
                self._traced("PUT")

            def do_DELETE(self):
                self._traced("DELETE")

        self._httpd = wrap_server_tls(
            ThreadingHTTPServer((host, port), Handler), tls_cert, tls_key
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="store-http"
        )

    # -- lifecycle --------------------------------------------------------

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "StoreServer":
        self._pump.start()
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._watch.close()
        with self._events_cond:
            self._events_cond.notify_all()

    def abort(self) -> None:
        """Release the bound socket and watch for a server that was
        constructed (socket bound) but never ``start()``ed — e.g. a
        promotion that lost the race to ``stop()``. ``shutdown()`` would
        deadlock here: BaseServer.shutdown blocks on serve_forever's
        exit handshake, and serve_forever never ran."""
        self._httpd.server_close()
        self._watch.close()
        with self._events_cond:
            self._events_cond.notify_all()

    # -- admission --------------------------------------------------------

    @staticmethod
    def _admit(kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        """Server-side schema validation + defaulting for kinds with a
        schema (what the CRD schema + API-server defaulting do for the
        reference). The typed round-trip materializes defaulted fields
        (image, status skeleton) so consumers never see partial objects."""
        if kind == LLMService.KIND:
            svc = LLMService.from_dict(obj)
            svc.validate()
            return svc.to_dict()
        return obj

    # -- event plumbing ---------------------------------------------------

    def _pump_events(self) -> None:
        while True:
            ev = self._watch.next_event(timeout=1.0)
            if ev is None:
                if self._w_closed():
                    return
                continue
            with self._events_cond:
                self._events.append(ev)
                self._events_cond.notify_all()

    def _w_closed(self) -> bool:
        return self._watch._w.closed.is_set()

    def _poll_events(
        self, since: int, timeout: float, kind: str | None, ns: str | None
    ) -> tuple[list[WatchEvent], int, int, int]:
        """One long-poll page plus the gap markers, snapshotted together.

        Returns (events, watch-cursor rv, storeRv, oldestEvent). The
        markers are read under ``_events_cond`` IN THE SAME critical
        section that collects the events (ADVICE r5): an unlocked
        ``oldestEvent`` read racing the pump could otherwise pair a
        just-advanced ring head with an events page collected before the
        advance. (Direction analysis says that race only errs toward a
        spurious follower resync — oldest rises monotonically — but the
        snapshot makes the page self-consistent instead of leaning on
        that reasoning.) ``storeRv`` reads the store's counter, which may
        run AHEAD of the ring (writes land in the store before the pump
        republishes them); ahead is the safe direction for its one
        consumer — the behind-primary check in replica.py compares
        ``storeRv < follower cursor``.
        """
        def matching() -> list[WatchEvent]:
            # The ring is rv-ordered and pollers sit near the tip: scan
            # from the right and stop at the first already-seen event,
            # so each poll is O(new events), not O(ring).
            out: list[WatchEvent] = []
            for e in reversed(self._events):
                if e.resource_version <= since:
                    break
                if (kind is None or e.kind == kind) and (
                    ns is None or e.namespace == ns
                ):
                    out.append(e)
            out.reverse()
            return out

        with self._events_cond:
            evs = matching()
            if not evs and timeout > 0:
                self._events_cond.wait(timeout)
                evs = matching()
            rv = self._events[-1].resource_version if self._events else since
            oldest = self._events[0].resource_version if self._events else 0
            return evs, max(rv, since), self._store._rv, oldest


class RemoteStore:
    """Store-interface client over the wire protocol above.

    Drop-in for ``Store``: agents, controllers, and the CLI take whichever
    they are handed (the reference equivalently swaps in-cluster and
    kubeconfig clients, cmd/agent/main.go:56 vs _archive/election).

    Resilience (ISSUE 1): every request runs under a ``RetryPolicy``
    with idempotency-aware classification — GET/LIST/watch pages retry
    any transient transport failure (including a torn/corrupt response
    body); PUT/POST/DELETE retry ONLY connect-level failures, where the
    request provably never reached the server. A retried mutation that
    actually landed is caught by the protocol itself: creates surface
    AlreadyExistsError, CAS updates surface ConflictError, and every
    caller already treats both as "re-read and retry". A shared
    ``CircuitBreaker`` fails calls fast (``BreakerOpenError``, an
    OSError) during a sustained outage so high-frequency callers
    (heartbeat ticks) degrade in microseconds instead of burning a full
    retry schedule per tick.
    """

    # A store round-trip is local-network cheap; a short schedule rides
    # out a restart/failover without stretching anyone's failure
    # detector (deadline_s=0: the per-call cap is attempts × timeout,
    # and long-poll callers own their windows explicitly).
    _GET_POLICY = RetryPolicy(
        max_attempts=4, base_delay_s=0.05, max_delay_s=1.0, deadline_s=0,
        classify=transient_http,
    )
    _MUTATE_POLICY = RetryPolicy(
        max_attempts=4, base_delay_s=0.05, max_delay_s=1.0, deadline_s=0,
        classify=connect_failure,
    )
    # watch_page: NO retries and NO breaker accounting — the replica's
    # follow loop is itself a failure detector (failover_grace_s counts
    # consecutive failed polls), so a resilience layer underneath it
    # would stretch exactly the detection latency it calibrates.
    _RAW_POLICY = RetryPolicy(max_attempts=1, deadline_s=0)

    def __init__(self, base_url: str, token: str = "",
                 request_timeout_s: float = 35.0,
                 ca_file: str = "",
                 retry: bool = True,
                 breaker: CircuitBreaker | None = None) -> None:
        self.base_url = base_url.rstrip("/")
        self._token = token
        self._timeout = request_timeout_s
        # pinned CA bundle for https stores (None -> system default
        # verification for https URLs; ignored for http)
        self._ssl_ctx = client_ssl_context(ca_file)
        self._retry = retry
        # one breaker per client: all methods share the same TCP edge.
        # Tests/tools pass breaker=CircuitBreaker(failure_threshold=...)
        # to tune trip/reset; retry=False restores the seed's
        # single-attempt behavior (e.g. probes that time-box themselves).
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            edge="store", failure_threshold=5, reset_timeout_s=1.0,
        )

    # -- plumbing ---------------------------------------------------------

    def _req(self, method: str, path: str, body: dict | None = None,
             timeout: float | None = None,
             policy: RetryPolicy | None = None,
             use_breaker: bool = True) -> Any:
        if policy is None:
            policy = self._GET_POLICY if method == "GET" else self._MUTATE_POLICY
        if not self._retry:
            policy = self._RAW_POLICY
        return policy.call(
            lambda: self._req_once(method, path, body, timeout),
            edge="store",
            breaker=self._breaker if (use_breaker and self._retry) else None,
        )

    def _req_once(self, method: str, path: str, body: dict | None,
                  timeout: float | None) -> Any:
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        # one client span per ATTEMPT (this method is the retry unit):
        # retries show as sibling spans under the caller, and the
        # retry-policy events land on the enclosing caller span because
        # each attempt span has already ended when the policy fires them
        with _CLIENT_TRACER.span(
            f"store.{method}", path=path.split("?", 1)[0]
        ):
            tp = traceparent_header()
            if tp:
                req.add_header("traceparent", tp)
            faultpoints.fire("store.request", key=f"{method} {path}")
            try:
                with urllib.request.urlopen(
                    req, timeout=timeout or self._timeout,
                    context=self._ssl_ctx,
                ) as resp:
                    raw = faultpoints.mangle(
                        "store.request", resp.read(), key=f"{method} {path}"
                    )
                    return json.loads(raw or b"null")
            except urllib.error.HTTPError as e:
                payload = {}
                try:
                    payload = json.loads(e.read() or b"{}")
                except json.JSONDecodeError:
                    pass
                msg = payload.get("message", str(e))
                code = payload.get("error", "")
                if e.code == 404:
                    raise NotFoundError(msg) from None
                if e.code == 409 and code == "already_exists":
                    raise AlreadyExistsError(msg) from None
                if e.code == 409:
                    raise ConflictError(msg) from None
                if e.code == 400:
                    raise ValidationError(msg) from None
                if e.code == 401:
                    raise PermissionError(f"unauthorized: {url}") from None
                raise

    def healthz(self) -> bool:
        try:
            # single attempt, no breaker: health probes are their own
            # retry loop, and a probe must see the edge's real state
            return self._req(
                "GET", "/healthz", policy=self._RAW_POLICY, use_breaker=False
            )["status"] == "ok"
        except Exception:
            return False

    # -- Store interface --------------------------------------------------

    @staticmethod
    def _seg(s: str) -> str:
        # names/namespaces/kinds are data, not path structure: a name like
        # "a/b" must travel as one segment ("a%2Fb")
        return urllib.parse.quote(s, safe="")

    def create(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        return self._req("POST", f"/apis/{self._seg(kind)}", obj)

    def get(self, kind: str, name: str, namespace: str = "default") -> dict[str, Any]:
        return self._req(
            "GET",
            f"/apis/{self._seg(kind)}/{self._seg(namespace)}/{self._seg(name)}",
        )

    def update(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        meta = obj.get("metadata", {})
        ns = meta.get("namespace", "default")
        name = meta.get("name", "")
        return self._req(
            "PUT",
            f"/apis/{self._seg(kind)}/{self._seg(ns)}/{self._seg(name)}",
            obj,
        )

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        self._req(
            "DELETE",
            f"/apis/{self._seg(kind)}/{self._seg(namespace)}/{self._seg(name)}",
        )

    def list(self, kind: str, namespace: str | None = None) -> list[dict[str, Any]]:
        path = f"/apis/{self._seg(kind)}"
        if namespace is not None:
            path += f"?namespace={urllib.parse.quote(namespace)}"
        return self._req("GET", path)

    def watch(self, kind: str | None = None,
              namespace: str | None = None) -> "RemoteWatch":
        rv = self._req("GET", "/rv")["resourceVersion"]
        return RemoteWatch(self, kind, namespace, since=rv)

    # -- replication plumbing (controlplane/replica.py) -------------------

    def rv(self) -> int:
        return self._req("GET", "/rv")["resourceVersion"]

    def dump(self) -> tuple[int, list]:
        """Primary's full state for follower bootstrap/resync."""
        resp = self._req("GET", "/dump")
        return resp["resourceVersion"], resp["objects"]

    def watch_page(self, since: int, timeout: float) -> dict:
        """One raw long-poll page INCLUDING the gap marker
        (``oldestEvent``) — the follower needs it to decide between
        tailing and a full resync; RemoteWatch deliberately hides it."""
        return self._req(
            "GET",
            f"/watch?since={since}&timeout={timeout}",
            # small cushion over the server's long-poll window: the
            # client-side timeout is the blackhole-failure detector, so
            # it must not dwarf the replica's failover grace
            timeout=timeout + 2.0,
            # no retry/breaker: the replica's grace accounting counts
            # RAW poll failures (see _RAW_POLICY note above)
            policy=self._RAW_POLICY,
            use_breaker=False,
        )


class RemoteWatch:
    """Long-poll watch stream with the in-process ``Watch`` interface."""

    def __init__(self, store: RemoteStore, kind: str | None,
                 namespace: str | None, since: int) -> None:
        self._store = store
        self._kind = kind
        self._ns = namespace
        self._since = since
        self._pending: collections.deque[WatchEvent] = collections.deque()
        self._closed = False

    def _fetch(self, timeout: float) -> None:
        q = {"since": str(self._since), "timeout": f"{timeout:.3f}"}
        if self._kind is not None:
            q["kind"] = self._kind
        if self._ns is not None:
            q["namespace"] = self._ns
        path = "/watch?" + urllib.parse.urlencode(q)
        # network timeout must outlive the server-side long-poll window;
        # the deadline caps the whole retry schedule at roughly one
        # extra window so next_event() stays responsive to close()
        resp = self._store._req(
            "GET", path, timeout=timeout + 10.0,
            policy=RetryPolicy(
                max_attempts=3, base_delay_s=0.05, max_delay_s=0.5,
                deadline_s=timeout + 15.0, classify=transient_http,
            ),
        )
        self._since = max(self._since, resp["resourceVersion"])
        for e in resp["events"]:
            self._pending.append(
                WatchEvent(
                    type=e["type"], kind=e["kind"], namespace=e["namespace"],
                    name=e["name"], object=e["object"],
                    resource_version=e["resourceVersion"],
                )
            )
            self._since = max(self._since, e["resourceVersion"])

    def next_event(self, timeout: float | None = None) -> WatchEvent | None:
        if self._closed:
            return None
        if not self._pending:
            try:
                self._fetch(timeout if timeout is not None else 30.0)
            except (OSError, NotFoundError, json.JSONDecodeError):
                # transient (incl. a corrupt page that exhausted its
                # retries); caller's periodic tick covers it
                return None
        return self._pending.popleft() if self._pending else None

    def drain(self) -> list[WatchEvent]:
        if not self._closed and not self._pending:
            try:
                self._fetch(timeout=0.0)
            except (OSError, NotFoundError, json.JSONDecodeError):
                pass
        out = list(self._pending)
        self._pending.clear()
        return out

    def close(self) -> None:
        self._closed = True

    def __iter__(self):
        while not self._closed:
            ev = self.next_event(timeout=1.0)
            if ev is not None:
                yield ev
