"""Journal-streaming store standby — the etcd-replication role.

The reference control plane rests on a replicated, always-on etcd: the
API server (and with it every CR and lease) survives the loss of any one
node, and a standby manager sees full state instantly
(election.go:72-141; llmservice_controller.go:84 assumes the API server
answers). kubeinfer_tpu hosts its store inside the manager process, so
without replication the store host is a single point of failure even
though the journal makes it restart-durable (r4 verdict missing #1).

``StoreReplica`` closes that gap: a standby manager tails the primary's
watch stream over the existing HTTP transport and applies every event
VERBATIM — same objects, same resourceVersion counter — into its own
durable local store (Store.apply_replicated). When the primary dies, the
standby promotes: it binds the shared store frontend address and serves
its replica. rv continuity across promotion is the load-bearing part —
agents' watch cursors stay valid and lease CAS-stealing (the election
protocol, lease.py) works against the promoted store exactly as it did
against the dead primary's.

Promotion arbitration is the frontend BIND: only one process can own the
shared host:port (the VIP role a cluster load balancer plays for the
reference's API server). A standby that loses the bind race resumes
following — the address now answers again, served by whichever standby
won — after a full /dump resync if its tail cursor fell behind.

Gap handling: the primary's event ring is finite (EVENT_LOG_SIZE), so a
follower whose cursor predates ``oldestEvent`` cannot prove continuity
and full-resyncs via ``/dump`` (atomic snapshot swap, Store.load_dump).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable

from kubeinfer_tpu.controlplane.httpstore import RemoteStore
from kubeinfer_tpu.controlplane.store import Store

log = logging.getLogger(__name__)


class StoreReplica:
    """Follow a primary store into a local durable replica; call back on
    sustained primary failure so the owner can attempt promotion.

    ``on_primary_dead`` returns True when promotion succeeded (this
    replica's store is now being served; the follow loop exits) or False
    when the bind was lost to a sibling standby (the loop resyncs and
    resumes following the new primary at the same address).
    """

    def __init__(
        self,
        remote: RemoteStore,
        data_dir: str,
        failover_grace_s: float = 5.0,
        poll_timeout_s: float | None = None,
    ) -> None:
        self.store = Store(data_dir=data_dir)
        self._remote = remote
        self._grace = failover_grace_s
        # Detection latency for a packet-blackhole failure is one
        # in-flight long-poll timeout, so the poll window derives from
        # the grace: worst-case promotion starts ~(poll + cushion +
        # grace) after the failure, the same order as the knob's
        # documented meaning, instead of a fixed window that could
        # triple it.
        self._poll = (
            poll_timeout_s if poll_timeout_s is not None
            else min(5.0, max(0.5, failover_grace_s / 2.0))
        )
        self._stop = threading.Event()
        self._synced = threading.Event()  # first successful sync/tail
        self._thread: threading.Thread | None = None
        self.promoted = threading.Event()

    # -- lifecycle --------------------------------------------------------

    def start(self, on_primary_dead: Callable[[], bool]) -> "StoreReplica":
        self._thread = threading.Thread(
            target=self._loop, args=(on_primary_dead,), daemon=True,
            name="store-replica",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # the store is NOT closed here when promoted — ownership moved
        # to the serving manager, which closes it on shutdown
        if not self.promoted.is_set():
            self.store.close()

    def wait_synced(self, timeout: float) -> bool:
        return self._synced.wait(timeout)

    @property
    def synced(self) -> bool:
        """True after the first successful sync/tail (probe surface)."""
        return self._synced.is_set()

    # -- follow loop ------------------------------------------------------

    def _loop(self, on_primary_dead: Callable[[], bool]) -> None:
        last_ok: float | None = None
        need_resync_check = True
        while not self._stop.is_set():
            try:
                if need_resync_check:
                    self._maybe_resync()
                    need_resync_check = False
                page = self._remote.watch_page(self.store._rv, self._poll)
                oldest = page.get("oldestEvent", 0)
                tip = page["resourceVersion"]
                if page.get("storeRv", tip) < self.store._rv:
                    # the primary's ACTUAL counter is BEHIND our cursor
                    # (restarted with a shorter/fresh history inside the
                    # grace window; the watch cursor itself is clamped
                    # to `since` so `tip` can never show this): every
                    # page would no-op forever while we report synced —
                    # divergence repair via _maybe_resync
                    need_resync_check = True
                    continue
                if tip > self.store._rv and (
                    oldest == 0 or oldest > self.store._rv + 1
                ):
                    # the primary is ahead but the ring cannot prove
                    # continuity from our cursor (rolled over, or empty
                    # after a primary restart): events were lost
                    need_resync_check = True
                    continue
                for e in page["events"]:
                    self.store.apply_replicated(
                        e["type"], e["kind"], e["namespace"], e["name"],
                        e.get("object"), e["resourceVersion"],
                    )
                last_ok = None
                self._synced.set()
            except Exception as e:  # transport/primary failure
                import time

                # the journal tail is no longer live: /replicaz must
                # stop reporting synced or an operator could trust a
                # failover onto an arbitrarily stale replica during a
                # partition the bind-arbitrated promotion cannot win
                self._synced.clear()
                now = time.monotonic()
                if last_ok is None:
                    last_ok = now
                    log.warning("replica: primary unreachable: %s", e)
                if now - last_ok >= self._grace:
                    log.warning(
                        "replica: primary dead for %.1fs; attempting "
                        "promotion", now - last_ok,
                    )
                    if on_primary_dead():
                        self.promoted.set()
                        return
                    # lost the bind race: a sibling promoted. Resync
                    # against the address (now answering again) and
                    # resume following.
                    last_ok = None
                    need_resync_check = True
                if self._stop.wait(min(self._poll, 1.0)):
                    return

    def _maybe_resync(self) -> None:
        """Full /dump resync when the tail cursor cannot be proven
        continuous (bootstrap from empty, or the ring rolled over)."""
        rv, objects = self._remote.dump()
        if rv == self.store._rv:
            return  # already current (normal warm start)
        if rv < self.store._rv:
            # The remote is BEHIND us: a sibling standby with a shorter
            # replication tail won the bind race. The serving primary's
            # history is the fleet's truth now — our surplus records
            # were never acked to any client while WE were a standby, so
            # adopting the shorter history wholesale is divergence
            # repair, not data loss. (If we were once a primary, the
            # surplus is the async-replication loss window — gone the
            # moment the fleet moved on, whatever we keep locally.)
            # Keeping our longer state instead would silently diverge:
            # the primary's events at rvs we already passed would be
            # filtered out of our watch stream forever.
            log.warning(
                "replica: remote rv %d behind local %d; adopting the "
                "serving primary's state (divergence repair)",
                rv, self.store._rv,
            )
            self.store.load_dump(rv, objects, allow_regress=True)
            return
        self.store.load_dump(rv, objects)
        log.info(
            "replica: synced %d objects at rv %d", len(objects), rv
        )
