"""kubeinfer_tpu — a TPU-native distributed AI inference job scheduler.

A brand-new framework with the capabilities of Moore-Z/kubeinfer (a Kubernetes
operator scheduling distributed LLM inference workloads; see
/root/reference), re-designed TPU-first:

- ``api``          — job/service resource types (parity with reference
                     api/v1/llmservice_types.go:25-98, plus ``schedulerPolicy``).
- ``solver``       — the accelerated scheduling core: batched jobs x nodes
                     feasibility/cost tensors solved under ``jax.jit``
                     (the component the reference lacks entirely; placement
                     there is delegated to kube-scheduler,
                     internal/controller/llmservice_controller.go:193-312).
- ``parallel``     — device-mesh sharding of the solver (pjit/shard_map) for
                     multi-chip scale-out over ICI/DCN.
- ``controlplane`` — in-memory versioned object store with watches and
                     Leases: the coordination bus (the reference uses the
                     K8s API server for this role) and the envtest-equivalent
                     test control plane.
- ``controller``   — batching reconciler + pluggable SchedulerBackend
                     (parity with internal/controller/llmservice_controller.go,
                     re-architected from per-CR serial to per-tick batched).
- ``agent``        — lease election, coordinator/follower model distribution,
                     inference-runtime lifecycle, node-state reporting
                     (parity with cmd/agent + internal/agent/*).
- ``models``       — learned placement cost model (flax) usable as a solver
                     scoring policy; the flagship jittable model.
- ``metrics``      — Prometheus collectors (parity with pkg/metrics/metrics.go
                     plus solve-latency/placement-quality instrumentation).
- ``native``       — C++ tier: serial greedy baseline scorer (the >=100x
                     comparison baseline) and fast host-side helpers, via
                     ctypes.
- ``utils``        — clock abstraction (real + simulated), logging, env config.
"""

__version__ = "0.1.0"
