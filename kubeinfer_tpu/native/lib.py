"""ctypes loader + numpy wrapper for the native serial scorer.

Build contract: ``make -C native`` at the repo root produces
``native/libkubeinfer_native.so``. The loader auto-builds once (g++ is part
of the supported toolchain) and raises ``NativeLibraryError`` with the exact
failing command if the library can't be produced or its ABI tag mismatches.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

import numpy as np
from kubeinfer_tpu.analysis.racecheck import make_lock

ABI_VERSION = 1

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libkubeinfer_native.so"

_lock = make_lock("native.lib._lock")
_lib: ctypes.CDLL | None = None


class NativeLibraryError(RuntimeError):
    """The native library is missing/unbuildable or ABI-incompatible."""


def _build() -> None:
    try:
        proc = subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)], capture_output=True, text=True
        )
    except FileNotFoundError as e:  # no make on PATH
        raise NativeLibraryError(f"building native library failed: {e}") from e
    if proc.returncode != 0:
        raise NativeLibraryError(
            f"building native library failed: `make -C {_NATIVE_DIR}` "
            f"exited {proc.returncode}\nstdout: {proc.stdout}\nstderr: {proc.stderr}"
        )


def _stale() -> bool:
    if not _LIB_PATH.exists():
        return True
    lib_mtime = _LIB_PATH.stat().st_mtime
    return any(
        src.stat().st_mtime > lib_mtime for src in _NATIVE_DIR.glob("*.cpp")
    )


def load_native() -> ctypes.CDLL:
    """Load (building if needed) the native library. Thread-safe, cached."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        # Rebuild BEFORE the first dlopen: once a .so is mapped, relinking it
        # in place and re-dlopening the same path returns the cached stale
        # handle — only a fresh process would see the rebuild.
        if _stale():
            # lint: allow[blocking-under-lock] once-per-process cc build (~seconds) must serialize: two racing builders would link a torn .so; callers accept first-load latency
            _build()
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
        except OSError as e:
            raise NativeLibraryError(f"loading {_LIB_PATH} failed: {e}") from e

        lib.ki_abi_version.restype = ctypes.c_int
        got = lib.ki_abi_version()
        if got != ABI_VERSION:
            raise NativeLibraryError(
                f"native ABI version {got} != expected {ABI_VERSION}; "
                f"run `make -C {_NATIVE_DIR} clean all` and restart"
            )

        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.ki_solve_greedy.restype = ctypes.c_int
        lib.ki_solve_greedy.argtypes = [
            ctypes.c_int, ctypes.c_int,
            f32p, f32p, f32p, i32p, i32p, i32p,
            f32p, f32p, f32p, f32p, i32p, u8p, ctypes.c_int,
            f32p, i32p,
        ]
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        load_native()
        return True
    except NativeLibraryError:
        return False


def solve_greedy_native(
    *,
    job_gpu: np.ndarray,
    job_mem_gib: np.ndarray,
    job_priority: np.ndarray | None = None,
    job_gang: np.ndarray | None = None,
    job_model: np.ndarray | None = None,
    job_current_node: np.ndarray | None = None,
    node_gpu_free: np.ndarray,
    node_mem_free_gib: np.ndarray,
    node_gpu_capacity: np.ndarray | None = None,
    node_mem_capacity_gib: np.ndarray | None = None,
    node_topology: np.ndarray | None = None,
    node_cached: np.ndarray | None = None,  # bool/uint8 [N, M]
    weights: tuple[float, float, float, float, float] = (1.0, 0.5, 5.0, 8.0, 2.0),
) -> tuple[np.ndarray, int]:
    """Run the serial scorer. Returns (assignment i32[J] with -1 unplaced,
    placed count). Input conventions match
    kubeinfer_tpu.solver.problem.encode_problem_arrays.
    """
    lib = load_native()
    J = int(job_gpu.shape[0])
    N = int(node_gpu_free.shape[0])

    # Length validation up front: the C side only null-checks, so a short
    # array would be an out-of-bounds read, not a Python exception.
    for label, arr, want in (
        ("job_mem_gib", job_mem_gib, J),
        ("job_priority", job_priority, J),
        ("job_gang", job_gang, J),
        ("job_model", job_model, J),
        ("job_current_node", job_current_node, J),
        ("node_mem_free_gib", node_mem_free_gib, N),
        ("node_gpu_capacity", node_gpu_capacity, N),
        ("node_mem_capacity_gib", node_mem_capacity_gib, N),
        ("node_topology", node_topology, N),
    ):
        if arr is not None and arr.shape != (want,):
            raise ValueError(f"{label} shape {arr.shape} != ({want},)")
    if node_cached is not None and (
        node_cached.ndim != 2 or node_cached.shape[0] != N
    ):
        raise ValueError(
            f"node_cached shape {node_cached.shape} != ({N}, num_models)"
        )
    if len(weights) != 5:
        raise ValueError(f"weights must have 5 elements, got {len(weights)}")

    def f32(a, default=None):
        if a is None:
            a = default
        return np.ascontiguousarray(a, np.float32)

    def i32(a, default=None):
        if a is None:
            a = default
        return np.ascontiguousarray(a, np.int32)

    jg = f32(job_gpu)
    jm = f32(job_mem_gib)
    jp = f32(job_priority, np.zeros(J))
    jgang = i32(job_gang, np.full(J, -1))
    jmodel = i32(job_model, np.zeros(J))
    jcur = i32(job_current_node, np.full(J, -1))
    ngf = f32(node_gpu_free)
    nmf = f32(node_mem_free_gib)
    ngc = f32(node_gpu_capacity, ngf)
    nmc = f32(node_mem_capacity_gib, nmf)
    ntopo = i32(node_topology, np.zeros(N))
    if node_cached is None:
        cached = np.zeros((N, 1), np.uint8)
    else:
        cached = np.ascontiguousarray(node_cached, np.uint8)
    max_models = int(cached.shape[1])
    w = np.asarray(weights, np.float32)
    out = np.empty(J, np.int32)

    placed = lib.ki_solve_greedy(
        J, N, jg, jm, jp, jgang, jmodel, jcur,
        ngf, nmf, ngc, nmc, ntopo, cached, max_models, w, out,
    )
    if placed < 0:
        raise NativeLibraryError("ki_solve_greedy rejected its arguments")
    return out, int(placed)
