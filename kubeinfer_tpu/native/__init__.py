"""Native tier: ctypes bindings for libkubeinfer_native.so.

The reference has no native components (100% Go, SURVEY.md §2); the native
tier here exists for the runtime pieces that must stay off the accelerator —
today the serial baseline scorer the TPU solver is measured against
(BASELINE.json north star "≥100× the serial scorer").
"""

from kubeinfer_tpu.native.lib import (
    NativeLibraryError,
    load_native,
    native_available,
    solve_greedy_native,
)

__all__ = [
    "NativeLibraryError",
    "load_native",
    "native_available",
    "solve_greedy_native",
]
