"""Scheduler flight recorder: a bounded ring of admission decisions.

When a slot is retired under chaos, or an admit stalls behind pool
backpressure, the Prometheus counters say *that* it happened but not
*what the scheduler saw* at that moment. The flight recorder keeps the
last N scheduler decisions — submit / admit / retire / evict /
backpressure — each stamped with the queue depth and KV-pool occupancy
observed at decision time, so a post-mortem (``/debug/flightrecorder``,
or the automatic dump when ``_fail_inflight`` releases waiters) replays
the lead-up instead of guessing from aggregates.

Same construction rules as the step profiler: plain ``deque`` ring (no
``os.urandom`` — seeded RNG streams stay untouched), timestamps from
``tracing.now()`` so SimulatedClock tests see one coherent timeline,
bounded memory by capacity.
"""

from __future__ import annotations

import collections
import itertools
from dataclasses import dataclass, field

from kubeinfer_tpu.analysis.racecheck import make_lock
from kubeinfer_tpu.observability import tracing

__all__ = ["FlightEvent", "FlightRecorder", "set_monitor", "get_monitor"]

# the decision vocabulary; note() rejects anything else so dashboards
# and tests can enumerate the kinds. The transition structure over
# these — which kind may follow which, per request — lives in ONE
# place: analysis/protocol.py SPEC. protolint checks the two stay
# set-equal, so a kind added here without a declared transition (or
# removed here while the spec still names it) fails lint.
KINDS = (
    "submit", "admit", "retire", "evict", "backpressure", "fail_inflight",
    "preempt", "resume", "chunk",
    # per-request terminal failure (stop()/_fail_inflight sweeps name
    # each dropped request; "fail_inflight" stays the aggregate)
    "fail",
    # disaggregated prefill/decode (disagg/): a remote prefix staged
    # for scatter, landed in the pool, or rejected at validation
    "import_staged", "import", "import_reject",
    # live-session migration (drain): the drain window, one streamed
    # chunk, a session handed off, and the sink-failure fall-forward
    "drain_start", "drain_end", "migrate_chunk", "migrate",
    "migrate_sink_error",
)

# Detail-schema hook: when armed (tests/conftest.py for chaos tests,
# schedfuzz's run_scenario), every note() on every recorder is fed to
# the protocol monitor — under the recorder's own lock, so per-recorder
# events arrive in seq order and the oracle never sees a reordering the
# ring itself didn't. The monitor records violations rather than
# raising (a raise here would kill a scheduler thread mid-handoff).
_MONITOR = None

# recorder identity for the monitor's chain keying: two engines in one
# test must not alias request ids
_UIDS = itertools.count()


def set_monitor(monitor) -> None:
    global _MONITOR
    _MONITOR = monitor


def get_monitor():
    return _MONITOR


@dataclass(frozen=True)
class FlightEvent:
    seq: int
    t: float  # tracing-clock seconds
    kind: str
    queue_depth: int  # submit queue + holdover at decision time
    kv_in_use: int  # pool blocks referenced at decision time
    kv_free: int  # pool free-list size at decision time
    detail: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "seq": self.seq, "t": self.t, "kind": self.kind,
            "queue_depth": self.queue_depth, "kv_in_use": self.kv_in_use,
            "kv_free": self.kv_free, "detail": dict(self.detail),
        }

    def render(self) -> str:
        extra = " ".join(f"{k}={v}" for k, v in sorted(self.detail.items()))
        return (
            f"[{self.seq:6d}] t={self.t:.6f} {self.kind:<12} "
            f"queue={self.queue_depth} kv={self.kv_in_use}/"
            f"{self.kv_in_use + self.kv_free}{' ' + extra if extra else ''}"
        )


class FlightRecorder:
    """Fixed-capacity ring of :class:`FlightEvent` (newest win)."""

    def __init__(self, capacity: int = 512,
                 name: str = "observability.FlightRecorder._lock") -> None:
        self._lock = make_lock(name)
        self._ring: collections.deque[FlightEvent] = collections.deque(
            maxlen=capacity
        )
        self._seq = 0
        self.uid = next(_UIDS)

    def note(self, kind: str, queue_depth: int = 0, kv_in_use: int = -1,
             kv_free: int = -1, t: float | None = None,
             **detail) -> FlightEvent:
        if kind not in KINDS:
            raise ValueError(f"unknown flight-recorder kind {kind!r}")
        t = tracing.now() if t is None else t
        with self._lock:
            ev = FlightEvent(
                seq=self._seq, t=t, kind=kind, queue_depth=queue_depth,
                kv_in_use=kv_in_use, kv_free=kv_free, detail=detail,
            )
            self._seq += 1
            self._ring.append(ev)
            mon = _MONITOR
            if mon is not None:
                mon.observe(self, ev)
        return ev

    def snapshot(self, since_seq: int = -1) -> list[FlightEvent]:
        """Events with ``seq > since_seq`` (all, by default) — the same
        exactly-once cursor contract as ``StepProfiler.snapshot``, so a
        long-run drainer (fleetview, ``/debug/flightrecorder?since=``)
        replays each decision once even though the ring itself keeps
        overwriting. Events already evicted by the ring before the
        drainer came back are gone; ``recorded`` in :meth:`to_dict`
        versus the cursor gap is how a reader detects that loss."""
        with self._lock:
            return [e for e in self._ring if e.seq > since_seq]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def to_dict(self, since_seq: int = -1) -> dict:
        events = self.snapshot(since_seq)
        return {
            "capacity": self._ring.maxlen,
            "recorded": self._seq,
            "events": [e.to_dict() for e in events],
        }

    def render(self) -> str:
        """Human-readable dump, oldest first — what ``_fail_inflight``
        logs so a crashed serving run leaves its last decisions in the
        log stream without anyone having to curl the debug endpoint
        before the process dies."""
        return "\n".join(e.render() for e in self.snapshot())

    def counter_events(self, pid: int) -> list[dict]:
        """Chrome trace-event ``C`` samples: queue-depth and kv-block
        curves from the decision stream, merged next to the span
        timeline (docs/OBSERVABILITY.md)."""
        events: list[dict] = []
        for e in self.snapshot():
            ts = e.t * 1e6
            events.append({
                "ph": "C", "name": "queue_depth", "pid": pid, "tid": 0,
                "ts": ts, "args": {"depth": e.queue_depth},
            })
            if e.kv_in_use >= 0:
                events.append({
                    "ph": "C", "name": "kv_blocks", "pid": pid, "tid": 0,
                    "ts": ts,
                    "args": {"in_use": e.kv_in_use, "free": e.kv_free},
                })
        return events
