"""Fleet aggregation plane: merged traces, request ledgers, envelopes.

PRs 4/6 gave each replica deep telemetry — spans, StepProfiler rings,
FlightRecorder decision logs — but every reader so far is per-replica,
and the questions that matter at fleet scale are joins: "where did THIS
request's p99 latency go, across the three replicas it touched?" and
"what is max sustained req/s before the TTFT SLO breaks?". The
reference operator only ever aggregates pod status counts
(llmservice_controller.go:66-174 syncs replica counts, never
request-level capacity) — this module is the deliberate divergence
ROADMAP item 5 names.

Three layers, each consuming the one below:

- **Per-replica drains.** :meth:`FleetView.drain` advances an
  exactly-once cursor per registered replica over its StepProfiler and
  FlightRecorder rings (the ``seq > since`` contract both now share),
  accumulating history the bounded rings would overwrite. Rings stay
  small and hot-path-cheap; the fleet view owns the long memory.
- **Request ledgers.** Spans from every replica land in the shared
  :data:`tracing.RECORDER` tagged with a ``replica`` attr; grouping by
  trace id reassembles each request's path. The engine stamps its
  phases contiguously by construction (queue_wait ends at t_admit where
  prefill starts; prefill ends at first-token where decode starts —
  batching.py), so the ledger's breakdown is queue/route/prefill/
  stream/decode plus an explicit ``other`` residual that absorbs
  whatever the instrumented phases do not cover (proxy overhead,
  inter-hop gaps during migration); the six always sum to the ledger's
  end-to-end by construction, which is what makes tail attribution
  mechanical instead of forensic.
- **Envelope analytics.** Offered-load sweep points fold into
  goodput-vs-offered curves and a knee: the highest offered load whose
  p99 TTFT still holds the SLO objective with a bounded error rate.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Sequence

from kubeinfer_tpu.observability import tracing

__all__ = [
    "PHASES",
    "EnvelopePoint",
    "FleetView",
    "RequestLedger",
    "build_ledgers",
    "detect_knee",
    "envelope_point",
    "tail_attribution",
]

# ledger phases in serving order; "other" is the derived residual, not
# a span name
PHASES = ("queue", "route", "prefill", "stream", "decode")

# span name -> ledger phase. One flat rule set — THE join contract
# documented in docs/OBSERVABILITY.md; a new instrumented phase means a
# new row here and nowhere else.
_PHASE_OF = {
    "engine.queue_wait": "queue",
    "router.route": "route",
    "engine.prefill": "prefill",
    "server.kv_import": "stream",
    "engine.decode": "decode",
}


@dataclass
class RequestLedger:
    """One request's end-to-end accounting, joined across hops by trace
    id. Durations are summed per phase (a migrated request has one
    prefill span per hop); ``other_s`` is the explicit residual so
    ``sum(phases) + other == e2e`` exactly."""

    trace_id: str
    t_start: float
    t_end: float
    phase_s: dict[str, float]
    other_s: float
    # replica attr of each phase's spans, in span start order —
    # "router routed to r1, prefill ran on p0, decode on r1" reads
    # straight off this
    phase_replicas: dict[str, list[str]]
    hops: int  # engine admissions (1 + migration resumes)
    spans: int

    @property
    def e2e_s(self) -> float:
        return self.t_end - self.t_start

    def dominant(self) -> tuple[str, str | None]:
        """(phase, replica) that ate the most time — the tail-cohort
        attribution unit. ``other`` can dominate (it is a finding, not
        a bookkeeping artifact: it means the latency lived outside the
        instrumented phases)."""
        best, best_d = "other", self.other_s
        for ph, d in self.phase_s.items():
            if d > best_d:
                best, best_d = ph, d
        reps = self.phase_replicas.get(best) or []
        return best, (reps[-1] if reps else None)

    def to_dict(self) -> dict:
        phase, replica = self.dominant()
        return {
            "trace_id": self.trace_id,
            "t_start": self.t_start,
            "t_end": self.t_end,
            "e2e_s": self.e2e_s,
            "phases_s": dict(self.phase_s),
            "other_s": self.other_s,
            "phase_replicas": {
                k: list(v) for k, v in self.phase_replicas.items()
            },
            "hops": self.hops,
            "spans": self.spans,
            "dominant_phase": phase,
            "dominant_replica": replica,
        }


def build_ledgers(spans: Sequence[tracing.Span]) -> list[RequestLedger]:
    """Join spans into per-request ledgers by trace id.

    Join rules (docs/OBSERVABILITY.md "Fleet envelope"):

    - a trace yields a ledger iff it contains at least one engine span
      (queue_wait/prefill/decode) — traces that never reached an engine
      (pure routing failures, bench scaffolding) are not requests;
    - the e2e bracket is the trace's ``client.request`` root span when
      present (the loadgen replay always makes one), else the min/max
      extent of the trace's own spans;
    - per-phase time is the SUM of that phase's span durations — a
      migrated request contributes one prefill span per hop, and the
      inter-hop gap lands in ``other`` rather than being hidden;
    - head-sampling keeps or drops whole traces (tracing.py), so every
      ledger built here is complete — there are no partially sampled
      ledgers to mis-rank.
    """
    by_trace: dict[str, list[tracing.Span]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    out: list[RequestLedger] = []
    for tid, group in by_trace.items():
        group.sort(key=lambda s: (s.start, s.name))
        engine_spans = [s for s in group
                        if s.name in ("engine.queue_wait",
                                      "engine.prefill", "engine.decode")]
        if not engine_spans:
            continue
        root = next(
            (s for s in group if s.name == "client.request"), None
        )
        phase_s = {ph: 0.0 for ph in PHASES}
        phase_replicas: dict[str, list[str]] = {}
        lo, hi = float("inf"), float("-inf")
        for s in group:
            end = s.end if s.end is not None else s.start
            lo, hi = min(lo, s.start), max(hi, end)
            ph = _PHASE_OF.get(s.name)
            if ph is None:
                continue
            phase_s[ph] += max(0.0, end - s.start)
            rep = s.attrs.get("replica")
            if rep is not None:
                phase_replicas.setdefault(ph, []).append(str(rep))
        if root is not None:
            t0 = root.start
            t1 = root.end if root.end is not None else hi
        else:
            t0, t1 = lo, hi
        e2e = max(0.0, t1 - t0)
        out.append(RequestLedger(
            trace_id=tid, t_start=t0, t_end=t1,
            phase_s=phase_s,
            other_s=max(0.0, e2e - sum(phase_s.values())),
            phase_replicas=phase_replicas,
            hops=max(1, sum(1 for s in group
                            if s.name == "engine.prefill")),
            spans=len(group),
        ))
    out.sort(key=lambda led: led.t_start)
    return out


def tail_attribution(ledgers: Sequence[RequestLedger],
                     q: float = 99.0) -> dict:
    """Who ate the tail: take the ledgers at or above the q-th e2e
    percentile and count dominant (phase, replica) pairs. The answer
    the envelope exists to make mechanical — "p99 is queue time on
    replica r1" — as plain counts, no interpretation layer."""
    if not ledgers:
        return {"cohort": 0, "by_phase": {}, "by_replica": {},
                "e2e_s_cut": None}
    e2es = sorted(led.e2e_s for led in ledgers)
    # nearest-rank percentile: the cut is an observed value, so the
    # cohort is never empty
    k = max(0, min(len(e2es) - 1, int(len(e2es) * q / 100.0)))
    cut = e2es[k]
    cohort = [led for led in ledgers if led.e2e_s >= cut]
    by_phase: dict[str, int] = {}
    by_replica: dict[str, int] = {}
    for led in cohort:
        phase, replica = led.dominant()
        by_phase[phase] = by_phase.get(phase, 0) + 1
        if replica is not None:
            by_replica[replica] = by_replica.get(replica, 0) + 1
    return {
        "cohort": len(cohort),
        "e2e_s_cut": cut,
        "by_phase": dict(sorted(by_phase.items(),
                                key=lambda kv: -kv[1])),
        "by_replica": dict(sorted(by_replica.items(),
                                  key=lambda kv: -kv[1])),
    }


# --- per-replica drains + merged trace -------------------------------------


@dataclass
class _ReplicaSource:
    """One registered replica's accumulated telemetry. The cursors make
    drains exactly-once; the lists are the long memory the bounded
    rings don't keep."""

    name: str
    engine: object  # ContinuousEngine (duck-typed: .profiler, .flight)
    steps: list = field(default_factory=list)
    flights: list = field(default_factory=list)
    _step_seq: int = -1
    _flight_seq: int = -1


class FleetView:
    """Drains per-replica telemetry into one merged view.

    Single-threaded by design: the bench/harness thread that owns the
    sweep calls drain()/ledgers()/merged_chrome_trace(); the replicas'
    own locks protect the rings being read. Registering an engine twice
    under one name replaces the source (fresh engines per sweep point)."""

    def __init__(self, recorder: tracing.SpanRecorder | None = None) -> None:
        self._recorder = recorder if recorder is not None else tracing.RECORDER
        self._sources: dict[str, _ReplicaSource] = {}

    def register(self, name: str, engine) -> None:
        self._sources[name] = _ReplicaSource(name=name, engine=engine)

    def drain(self) -> dict[str, tuple[int, int]]:
        """Pull new step/flight records from every registered replica;
        returns {replica: (new_steps, new_flight_events)}. Called
        periodically during a run (and once after), so ring capacity
        bounds the POLL interval, not the run length."""
        drained: dict[str, tuple[int, int]] = {}
        for name, src in self._sources.items():
            steps = src.engine.profiler.snapshot(since_seq=src._step_seq)
            if steps:
                src._step_seq = steps[-1].seq
                src.steps.extend(steps)
            evs = src.engine.flight.snapshot(since_seq=src._flight_seq)
            if evs:
                src._flight_seq = evs[-1].seq
                src.flights.extend(evs)
            drained[name] = (len(steps), len(evs))
        return drained

    def merged_chrome_trace(
        self, spans: Sequence[tracing.Span] | None = None,
    ) -> dict:
        """One Chrome trace for the whole fleet: spans get per-replica
        process groups (pid = "replica:component", from each span's
        ``replica`` attr), and every registered replica's drained
        step/flight counters render as its own counter track. Open in
        Perfetto: a request's row crosses replica process groups
        exactly where it migrated."""
        spans = self._recorder.snapshot() if spans is None else list(spans)
        relabeled: list[tracing.Span] = []
        for s in spans:
            rep = s.attrs.get("replica")
            if rep is None:
                relabeled.append(s)
                continue
            ns = tracing.Span(
                s.name, f"{rep}:{s.component}", s.trace_id, s.span_id,
                s.parent_id, s.start, s.attrs,
            )
            ns.end = s.end
            ns.events = list(s.events)
            relabeled.append(ns)
        doc = tracing.to_chrome_trace(relabeled)
        pid = max(
            (e.get("pid", 0) for e in doc["traceEvents"]), default=0
        )
        for name in sorted(self._sources):
            src = self._sources[name]
            pid += 1
            doc["traceEvents"].append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": f"{name}:counters"},
            })
            for r in src.steps:
                ts = r.t * 1e6
                doc["traceEvents"].append({
                    "ph": "C", "name": "batch_occupancy", "pid": pid,
                    "tid": 0, "ts": ts,
                    "args": {"live_rows": r.live_rows},
                })
            for e in src.flights:
                ts = e.t * 1e6
                doc["traceEvents"].append({
                    "ph": "C", "name": "queue_depth", "pid": pid,
                    "tid": 0, "ts": ts, "args": {"depth": e.queue_depth},
                })
                if e.kv_in_use >= 0:
                    doc["traceEvents"].append({
                        "ph": "C", "name": "kv_blocks", "pid": pid,
                        "tid": 0, "ts": ts,
                        "args": {"in_use": e.kv_in_use,
                                 "free": e.kv_free},
                    })
        return doc

    def ledgers(
        self, spans: Sequence[tracing.Span] | None = None,
    ) -> list[RequestLedger]:
        spans = self._recorder.snapshot() if spans is None else spans
        return build_ledgers(spans)

    def steps(self, name: str) -> list:
        return list(self._sources[name].steps)

    def flights(self, name: str) -> list:
        return list(self._sources[name].flights)


# --- envelope analytics ----------------------------------------------------


@dataclass(frozen=True)
class EnvelopePoint:
    """One offered-load sweep point, as the curve artifact stores it."""

    offered_req_per_s: float
    completed: int
    errors: int
    late_dispatches: int
    goodput_tokens_per_s: float
    ttft_ms_p50: float
    ttft_ms_p99: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def envelope_point(offered_req_per_s: float, result) -> EnvelopePoint:
    """Fold one loadgen ReplayResult into a sweep point. Duck-typed on
    the result's surface (completed()/errors()/ttft_ms_percentile/
    goodput_tokens_per_s) so tests can feed synthetic results."""
    return EnvelopePoint(
        offered_req_per_s=float(offered_req_per_s),
        completed=len(result.completed()),
        errors=int(result.errors()),
        late_dispatches=int(result.late_dispatches),
        goodput_tokens_per_s=float(result.goodput_tokens_per_s()),
        ttft_ms_p50=float(result.ttft_ms_percentile(50.0)),
        ttft_ms_p99=float(result.ttft_ms_percentile(99.0)),
    )


def detect_knee(points: Sequence[EnvelopePoint], slo_ttft_ms: float,
                max_error_frac: float = 0.01) -> EnvelopePoint | None:
    """The knee: the HIGHEST offered load whose p99 TTFT holds the SLO
    objective AND whose error fraction stays bounded (an overloaded
    fleet that sheds its way to a good p99 has not sustained the
    load). Returns None when no sweep point qualifies — the fleet's
    knee is below the sweep's floor, which the caller should report,
    not paper over."""
    knee: EnvelopePoint | None = None
    for p in sorted(points, key=lambda p: p.offered_req_per_s):
        total = p.completed + p.errors
        err_frac = p.errors / total if total else 1.0
        p99 = p.ttft_ms_p99
        if p99 == p99 and p99 <= slo_ttft_ms and \
                err_frac <= max_error_frac:
            knee = p
    return knee
