"""CLI: ``python -m kubeinfer_tpu.observability`` — one traced request.

Boots a tiny-preset engine + continuous batcher + inference server and a
store server on loopback, issues ONE /v1/completions request (plus a
store round trip) under a single client root span, and writes that
trace as Chrome trace-event JSON under docs/traces/ — the zero-setup
way to see the span model end-to-end and to regenerate the checked-in
demo artifact. ``make trace-demo`` wraps this.

Runs on the virtual CPU mesh unconditionally (same forcing as
tests/conftest.py): the demo is about trace STRUCTURE, not device
performance, and must never touch the experimental axon relay.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

# must win over this box's global JAX_PLATFORMS=axon BEFORE jax imports
os.environ["JAX_PLATFORMS"] = "cpu"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubeinfer_tpu.observability",
        description="run one traced serving request; write a "
                    "Perfetto-loadable Chrome trace JSON")
    ap.add_argument("--out", default="docs/traces/serving_demo.trace.json",
                    help="output path for the trace JSON")
    ap.add_argument("--max-tokens", type=int, default=8)
    args = ap.parse_args(argv)

    import urllib.request

    import jax

    jax.config.update("jax_platforms", "cpu")

    from kubeinfer_tpu.controlplane.httpstore import RemoteStore, StoreServer
    from kubeinfer_tpu.controlplane.store import Store
    from kubeinfer_tpu.inference import PRESETS, init_params
    from kubeinfer_tpu.inference.batching import ContinuousEngine
    from kubeinfer_tpu.inference.engine import Engine
    from kubeinfer_tpu.inference.server import InferenceServer
    from kubeinfer_tpu.observability import tracing
    from kubeinfer_tpu.utils.httpbase import inject_traceparent

    cfg = PRESETS["tiny"]
    params = init_params(cfg, jax.random.PRNGKey(0))
    cont = ContinuousEngine(params, cfg, n_slots=2, cache_len=64).start()
    srv = InferenceServer(
        Engine(params, cfg), model_id="trace-demo", port=0, continuous=cont
    ).start()
    store_srv = StoreServer(Store(), port=0).start()
    remote = RemoteStore(store_srv.address)
    tracer = tracing.get_tracer("client")
    try:
        # warm the compile caches OUTSIDE the demo trace, so the span
        # durations in the artifact reflect serving, not jit compiles
        cont.generate([1, 2, 3], max_new_tokens=2)
        tracing.RECORDER.clear()
        with tracer.span("client.request") as root:
            remote.create("Widget", {
                "metadata": {"name": "demo", "namespace": "default"},
            })
            remote.get("Widget", "demo")
            body = json.dumps({
                "prompt": [3, 1, 4, 1, 5], "max_tokens": args.max_tokens,
            }).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/completions",
                data=body, method="POST",
                headers=inject_traceparent(
                    {"Content-Type": "application/json"}
                ),
            )
            with urllib.request.urlopen(req, timeout=120) as resp:
                json.loads(resp.read())
        trace_id = root.trace_id
    finally:
        srv.stop()
        store_srv.shutdown()
        cont.stop()

    doc = tracing.RECORDER.to_chrome_trace(trace_id)
    # the server's /debug/spans does this merge live; the artifact
    # carries the same counter tracks so the checked-in demo shows the
    # occupancy / queue-depth / kv-block curves next to the spans
    srv._merge_counter_tracks(doc)
    counters = sum(1 for e in doc["traceEvents"] if e.get("ph") == "C")
    out = pathlib.Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(doc, indent=1) + "\n")
    spans = tracing.RECORDER.snapshot(trace_id)
    components = sorted({s.component for s in spans})
    print(f"trace {trace_id}: {len(spans)} spans across "
          f"{len(components)} components {components}; "
          f"{counters} counter samples")
    print(f"wrote {out} — open at https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
