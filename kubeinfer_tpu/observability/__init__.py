"""Request-scoped observability: tracing, trace-context propagation,
Chrome trace export. See docs/OBSERVABILITY.md for the span model."""

from kubeinfer_tpu.observability.tracing import (
    RECORDER,
    Span,
    SpanContext,
    SpanRecorder,
    TraceContextFilter,
    Tracer,
    add_event,
    current_context,
    current_span,
    get_tracer,
    new_root_context,
    now,
    parse_traceparent,
    set_clock,
    to_chrome_trace,
)

__all__ = [
    "RECORDER",
    "Span",
    "SpanContext",
    "SpanRecorder",
    "TraceContextFilter",
    "Tracer",
    "add_event",
    "current_context",
    "current_span",
    "get_tracer",
    "new_root_context",
    "now",
    "parse_traceparent",
    "set_clock",
    "to_chrome_trace",
]
