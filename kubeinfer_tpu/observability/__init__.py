"""Request-scoped observability: tracing, trace-context propagation,
Chrome trace export, step-level engine profiling, the scheduler flight
recorder, and SLO burn-rate monitoring — plus the fleet plane:
trace-driven load generation (loadgen), cross-replica request ledgers
and merged traces (fleetview), and envelope analytics. See
docs/OBSERVABILITY.md."""

from kubeinfer_tpu.observability.tracing import (
    RECORDER,
    Span,
    SpanContext,
    SpanRecorder,
    TraceContextFilter,
    Tracer,
    add_event,
    current_context,
    current_span,
    get_tracer,
    new_root_context,
    now,
    parse_traceparent,
    set_clock,
    set_span_sampling,
    span_sampling,
    to_chrome_trace,
    trace_sampled,
)

__all__ = [
    "RECORDER",
    "Span",
    "SpanContext",
    "SpanRecorder",
    "TraceContextFilter",
    "Tracer",
    "add_event",
    "current_context",
    "current_span",
    "get_tracer",
    "new_root_context",
    "now",
    "parse_traceparent",
    "set_clock",
    "set_span_sampling",
    "span_sampling",
    "to_chrome_trace",
    "trace_sampled",
    # step profiler / flight recorder / SLO monitor are intentionally
    # NOT re-exported from the package root: tracing must stay an
    # import leaf (its docstring contract), and the engine/server
    # import the submodules directly — kubeinfer_tpu.observability
    # .stepprof / .flightrecorder / .slo; same for the fleet plane
    # (.loadgen / .fleetview), whose only consumers are bench and
    # tests.
]
