"""Seeded open-loop load generation: arrival processes x length families.

The fleet envelope (docs/OBSERVABILITY.md) needs traffic that looks
like production — bursty arrivals, heavy-tail prompt lengths, shared
prefixes — but replays byte-identically, because a capacity knee that
moves with the harness's RNG is not a measurement. The reference
operator has nothing at this level (its tests drive single requests at
controllers, llmservice_controller.go:66-174 never models load); this
module is the schedfuzz discipline applied to traffic instead of
scheduler interleavings: ONE seed determines every arrival time, every
family draw, every prompt token, and a sha256 checksum over the
canonical schedule pins it cross-process.

Two halves, deliberately separable:

- **Schedule construction** (:func:`make_schedule`) is pure numpy on a
  ``default_rng(seed)`` — no clocks, no threads, no jax — so building
  an O(10^5)-request schedule costs milliseconds and tests can assert
  byte-identity without touching an engine.
- **Open-loop replay** (:func:`replay`) paces the schedule against a
  caller-supplied ``post`` callable (the real ``RouterServer.forward``
  in the fleet benches). Open-loop means arrivals NEVER wait for
  completions — the whole point of an envelope is to observe the system
  past its knee, and a closed loop self-throttles exactly there. Each
  request runs under a fresh client root span, so every hop it takes
  through the fleet (router route -> prefill -> KV stream -> decode)
  joins one trace id and fleetview can assemble per-request ledgers.

Arrival processes (all with the same mean ``rate``):

- ``poisson``: memoryless baseline — iid exponential inter-arrivals.
- ``diurnal``: sinusoidal rate modulation (period ``diurnal_period_s``,
  peak ``1 + diurnal_depth`` over the mean) via Lewis-Shedler thinning;
  the day/night cycle compressed to bench scale.
- ``burst``: on/off traffic — arrivals only inside the duty window of
  each ``burst_period_s`` cycle, at ``rate / burst_duty`` while on. The
  storm-admission case, sustained.

Length families are the round-9 heavy-tail pair (bench.py
serving_slo_bench): longs draw 480/496/512-token prompts, shorts draw
8..16, mixed by ``long_frac``. Prompts share per-group prefixes so the
router's content-addressed affinity has something real to route on.
"""

from __future__ import annotations

import hashlib
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Sequence

import numpy as np

from kubeinfer_tpu.observability import tracing

__all__ = [
    "PROCESSES",
    "ArrivalSchedule",
    "ReplayRecord",
    "ReplayResult",
    "ScheduledRequest",
    "make_schedule",
    "replay",
]

PROCESSES = ("poisson", "diurnal", "burst")

# round-9 heavy-tail families (serving_slo_bench): near-boundary longs
# keep prefill compute comparable across runs while varying enough that
# the radix trie sees distinct prefixes; shorts are one block
_LONG_LENS = (480, 496, 512)
_SHORT_LO, _SHORT_HI = 8, 17  # rng.integers half-open, so 8..16

# tokens of each prompt drawn from the request's (seed, group) stream
# instead of its private one: half the prompt, capped at two 32-token
# blocks — longs share fingerprintable prefixes within a group, shorts
# stay sub-block (no fingerprint, like real interactive traffic)
_PREFIX_CAP = 64


@dataclass(frozen=True)
class ScheduledRequest:
    """One arrival, fully determined at schedule-build time. Tokens are
    NOT stored — 10^5 requests x 512 tokens would dominate memory — but
    ``prompt_seed``/``group`` determine them exactly
    (:meth:`ArrivalSchedule.prompt_tokens`)."""

    index: int
    t: float  # arrival offset from schedule start, seconds
    family: str  # "long" | "short"
    prompt_len: int
    max_new: int
    group: int  # prefix-sharing cohort
    prompt_seed: int

    def canonical(self) -> str:
        """One checksum line. 9 decimal places on the arrival offset:
        float64 survives a round-trip at that precision for any bench-
        scale offset, so equal schedules hash equal and unequal ones
        differ in the text itself (greppable when a pin breaks)."""
        return (
            f"{self.t:.9f},{self.family},{self.prompt_len},"
            f"{self.max_new},{self.group},{self.prompt_seed}"
        )


@dataclass(frozen=True)
class ArrivalSchedule:
    """An immutable arrival schedule plus the knobs that built it (kept
    so the checksum covers intent, not just outcome — two processes can
    emit the same arrival times and must still hash apart)."""

    process: str
    seed: int
    rate: float  # mean offered req/s
    requests: tuple[ScheduledRequest, ...]

    def duration_s(self) -> float:
        return self.requests[-1].t if self.requests else 0.0

    def offered_req_per_s(self) -> float:
        d = self.duration_s()
        return len(self.requests) / d if d > 0 else 0.0

    def checksum(self) -> str:
        h = hashlib.sha256()
        h.update(
            f"{self.process},{self.seed},{self.rate:.9f},"
            f"{len(self.requests)}\n".encode()
        )
        for r in self.requests:
            h.update(r.canonical().encode())
            h.update(b"\n")
        return h.hexdigest()

    def prompt_tokens(self, r: ScheduledRequest,
                      vocab_size: int) -> list[int]:
        """Materialize one request's prompt ids. Group prefix first,
        private tail second, each from its own ``default_rng`` — so a
        replay (or a retry) regenerates the identical prompt without
        the schedule having stored it."""
        prefix_len = min(r.prompt_len // 2, _PREFIX_CAP)
        pre = np.random.default_rng([self.seed, r.group]).integers(
            0, vocab_size, prefix_len
        )
        tail = np.random.default_rng(r.prompt_seed).integers(
            0, vocab_size, r.prompt_len - prefix_len
        )
        return pre.tolist() + tail.tolist()


def _poisson_arrivals(rng: np.random.Generator, rate: float,
                      n: int) -> np.ndarray:
    return np.cumsum(rng.exponential(1.0 / rate, n))


def _diurnal_arrivals(rng: np.random.Generator, rate: float, n: int,
                      depth: float, period_s: float) -> np.ndarray:
    """Lewis-Shedler thinning against the peak rate, chunked so the
    draw count stays a pure function of (seed, params) — the Python-
    loop version would be too, but at 10^5 arrivals the vector form is
    what keeps schedule construction off the bench clock."""
    peak = rate * (1.0 + depth)
    times: list[float] = []
    t = 0.0
    while len(times) < n:
        m = max(1024, 2 * (n - len(times)))
        cand = t + np.cumsum(rng.exponential(1.0 / peak, m))
        lam = rate * (1.0 + depth * np.sin(
            2.0 * np.pi * cand / period_s
        ))
        keep = rng.random(m) * peak <= lam
        times.extend(cand[keep].tolist())
        t = float(cand[-1])
    return np.asarray(times[:n])


def _burst_arrivals(rng: np.random.Generator, rate: float, n: int,
                    duty: float, period_s: float) -> np.ndarray:
    """On/off: draw Poisson arrivals in 'active time' at the on-rate
    (``rate / duty``), then splice the off windows back in — exact, no
    rejection, and the mean over whole cycles is ``rate`` by
    construction."""
    on_s = period_s * duty
    active = np.cumsum(rng.exponential(duty / rate, n))
    return np.floor(active / on_s) * period_s + np.mod(active, on_s)


def make_schedule(
    process: str = "poisson",
    rate: float = 10.0,
    n_requests: int = 1000,
    seed: int = 0,
    long_frac: float = 0.2,
    long_new: int = 64,
    short_new: int = 4,
    n_groups: int = 8,
    diurnal_depth: float = 0.5,
    diurnal_period_s: float = 60.0,
    burst_duty: float = 0.25,
    burst_period_s: float = 10.0,
) -> ArrivalSchedule:
    """Build one deterministic schedule. Every random draw comes from
    ONE ``default_rng(seed)`` in a fixed order (arrival times, then the
    per-request family/length/group/seed planes), so same seed =>
    byte-identical schedule — the property the determinism tests and
    the cross-process golden checksum pin."""
    if process not in PROCESSES:
        raise ValueError(
            f"unknown arrival process {process!r} (want one of "
            f"{PROCESSES})"
        )
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if not (0.0 <= long_frac <= 1.0):
        raise ValueError(f"long_frac must be in [0, 1], got {long_frac}")
    rng = np.random.default_rng(seed)
    if process == "poisson":
        times = _poisson_arrivals(rng, rate, n_requests)
    elif process == "diurnal":
        times = _diurnal_arrivals(
            rng, rate, n_requests, diurnal_depth, diurnal_period_s
        )
    else:
        times = _burst_arrivals(
            rng, rate, n_requests, burst_duty, burst_period_s
        )
    # whole planes drawn at once (not per request) so construction is
    # vectorized; the order of the five draws is part of the format —
    # reordering them silently breaks every pinned checksum
    is_long = rng.random(n_requests) < long_frac
    long_lens = rng.choice(np.asarray(_LONG_LENS), size=n_requests)
    short_lens = rng.integers(_SHORT_LO, _SHORT_HI, size=n_requests)
    groups = rng.integers(0, n_groups, size=n_requests)
    prompt_seeds = rng.integers(0, 2**31 - 1, size=n_requests)
    reqs = tuple(
        ScheduledRequest(
            index=i,
            t=float(times[i]),
            family="long" if is_long[i] else "short",
            prompt_len=int(long_lens[i] if is_long[i] else short_lens[i]),
            max_new=int(long_new if is_long[i] else short_new),
            group=int(groups[i]),
            prompt_seed=int(prompt_seeds[i]),
        )
        for i in range(n_requests)
    )
    return ArrivalSchedule(
        process=process, seed=seed, rate=rate, requests=reqs,
    )


# --- open-loop replay ------------------------------------------------------


@dataclass
class ReplayRecord:
    """What the client observed for one scheduled request."""

    index: int
    family: str
    trace_id: str
    t_sched: float  # scheduled arrival offset
    t_sent: float  # tracing-clock send time
    t_done: float  # tracing-clock completion (or failure) time
    ok: bool
    error: str | None = None
    ttft_ms: float | None = None  # server-reported (kubeinfer ext)
    tpot_ms: float | None = None
    replica: str | None = None
    tokens_out: int = 0


@dataclass
class ReplayResult:
    records: list[ReplayRecord]
    duration_s: float  # first dispatch to last completion, wall
    late_dispatches: int  # arrivals the pacer could not issue on time

    def completed(self) -> list[ReplayRecord]:
        return [r for r in self.records if r.ok]

    def errors(self) -> int:
        return sum(1 for r in self.records if not r.ok)

    def ttft_ms_percentile(self, q: float) -> float:
        ttfts = [r.ttft_ms for r in self.completed()
                 if r.ttft_ms is not None]
        if not ttfts:
            return float("nan")
        return float(np.percentile(np.asarray(ttfts), q))

    def goodput_tokens_per_s(self) -> float:
        if self.duration_s <= 0:
            return 0.0
        return sum(r.tokens_out for r in self.completed()) / self.duration_s


def replay(
    schedule: ArrivalSchedule,
    post: Callable[[dict], dict],
    vocab_size: int,
    *,
    speed: float = 1.0,
    max_workers: int = 32,
    request_extra: dict | None = None,
    on_dispatch: Callable[[], None] | None = None,
) -> ReplayResult:
    """Replay ``schedule`` open-loop against ``post``.

    ``post`` takes an OpenAI-ish completion body and returns the
    response dict (raising on failure) — the fleet benches pass a thin
    wrapper over ``RouterServer.forward``. ``speed`` compresses the
    schedule's time axis (2.0 = twice as fast); pacing uses the wall
    clock because the engines under test do. The worker pool bounds
    in-flight client threads, NOT the offered load: when every worker
    is busy, dispatches queue inside the executor and the records count
    as late — visible in the result rather than silently converting the
    run to closed-loop. Server-side TTFT/TPOT come from the
    ``kubeinfer`` response extension, so client queueing never pollutes
    the latency the envelope curves report.

    Every request runs under a fresh ``client.request`` root span;
    driving ``RouterServer.forward`` on the worker thread makes the
    router's spans children of it, which is the join fleetview's
    ledgers key on.
    """
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    tracer = tracing.get_tracer("client")
    records: list[ReplayRecord | None] = [None] * len(schedule.requests)

    def _one(r: ScheduledRequest) -> None:
        body = {
            "prompt": schedule.prompt_tokens(r, vocab_size),
            "max_tokens": r.max_new,
        }
        if request_extra:
            body.update(request_extra)
        with tracer.span("client.request", index=r.index,
                         family=r.family) as sp:
            t_sent = tracing.now()
            rec = ReplayRecord(
                index=r.index, family=r.family,
                trace_id=sp.trace_id, t_sched=r.t,
                t_sent=t_sent, t_done=t_sent, ok=False,
            )
            try:
                resp = post(body)
                ext = resp.get("kubeinfer") or {}
                usage = resp.get("usage") or {}
                rec.ok = True
                rec.ttft_ms = ext.get("ttft_ms")
                rec.tpot_ms = ext.get("tpot_ms")
                rec.replica = ext.get("replica")
                rec.tokens_out = int(usage.get("completion_tokens", 0))
            except Exception as e:
                # the envelope MUST survive past the knee — overload
                # errors are data points, not run failures
                rec.error = f"{type(e).__name__}: {e}"
                sp.set(error=type(e).__name__)
            rec.t_done = tracing.now()
        records[r.index] = rec

    late = 0
    t_wall0 = time.monotonic()
    with ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix="loadgen",
    ) as pool:
        futs = []
        for r in schedule.requests:
            target = t_wall0 + r.t / speed
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            else:
                late += 1
            futs.append(pool.submit(_one, r))
            if on_dispatch is not None:
                on_dispatch()
        for f in futs:
            f.result()
    duration = time.monotonic() - t_wall0
    done = [rec for rec in records if rec is not None]
    return ReplayResult(
        records=done, duration_s=duration, late_dispatches=late,
    )
