"""SLO burn-rate monitor over the serving request timelines.

PR 4's tracing stamped every continuous-batch request with a timeline
(submit/admit/first-token/done) and the server derived TTFT / TPOT /
queue-wait histograms from it. Histograms answer "what is the
distribution"; an operator paging decision needs "how fast are we
burning the error budget" — the multi-window burn-rate construction
from the SRE workbook: for an objective "fraction of requests with
value <= threshold must be >= objective", the burn rate over a window
is

    burn = bad_fraction(window) / (1 - objective)

so burn 1.0 means exactly on budget, 14.4 over 1h is the classic
page-now threshold, and comparing a short and a long window separates
a fresh regression (short >> long) from slow smolder (both elevated).

Implementation rules match the rest of the observability layer: all
timestamps flow through ``tracing.now()`` (SimulatedClock tests assert
exact burn rates), observations land in plain bounded rings (no
``os.urandom``), and the monitor is passive — the server feeds it from
``_observe_breakdown`` and reads gauges at scrape time, so an idle
process pays nothing.

The ring capacity bounds the lookback: with capacity C and request
rate r, windows longer than C/r undercount bad requests *and* total
requests alike, so the burn rate degrades toward the recent-window
value rather than lying in either direction.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass

from kubeinfer_tpu.analysis.racecheck import make_lock
from kubeinfer_tpu.observability import tracing

__all__ = ["SLOObjective", "SLOMonitor", "DEFAULT_OBJECTIVES"]


@dataclass(frozen=True)
class SLOObjective:
    """``objective`` of requests must see ``value <= threshold_s``."""

    name: str  # "ttft" | "tpot" | "queue_wait" | custom
    threshold_s: float
    objective: float  # target good fraction, in (0, 1)

    def __post_init__(self) -> None:
        if not (0.0 < self.objective < 1.0):
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}"
            )
        if self.threshold_s <= 0.0:
            raise ValueError(
                f"threshold_s must be > 0, got {self.threshold_s}"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    @classmethod
    def parse(cls, spec: str) -> "SLOObjective":
        """``name:threshold_s:objective`` (the --slo CLI syntax), e.g.
        ``ttft:0.5:0.99`` = 99% of requests reach first token in
        <= 500 ms."""
        parts = spec.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"SLO spec {spec!r} is not name:threshold_s:objective"
            )
        return cls(parts[0], float(parts[1]), float(parts[2]))


# Deliberately loose defaults (tiny CPU-mesh test engines must not sit
# permanently in violation); production deployments pass their own via
# --slo / InferenceServer(slo=...).
DEFAULT_OBJECTIVES = (
    SLOObjective("ttft", 2.0, 0.99),
    SLOObjective("tpot", 0.5, 0.99),
    SLOObjective("queue_wait", 1.0, 0.99),
)


class SLOMonitor:
    """Multi-window burn rates over per-request latency observations."""

    def __init__(self, objectives=DEFAULT_OBJECTIVES,
                 windows: tuple[float, ...] = (60.0, 300.0, 1800.0),
                 capacity: int = 8192,
                 name: str = "observability.SLOMonitor._lock") -> None:
        if not windows:
            raise ValueError("at least one window is required")
        self.objectives: dict[str, SLOObjective] = {
            o.name: o for o in objectives
        }
        self.windows = tuple(sorted(float(w) for w in windows))
        self._lock = make_lock(name)
        self._obs: dict[str, collections.deque] = {
            n: collections.deque(maxlen=capacity) for n in self.objectives
        }

    def observe(self, name: str, value_s: float,
                t: float | None = None) -> None:
        """Record one request's value for objective ``name``; unknown
        names are dropped (the server observes every breakdown metric
        unconditionally — which ones carry an SLO is configuration)."""
        ring = self._obs.get(name)
        if ring is None:
            return
        t = tracing.now() if t is None else t
        with self._lock:
            ring.append((t, value_s))

    def _window_counts(self, name: str, now: float) -> dict[float, tuple]:
        obj = self.objectives[name]
        with self._lock:
            obs = list(self._obs[name])
        out = {}
        for w in self.windows:
            inside = [(t, v) for t, v in obs if t >= now - w]
            bad = sum(1 for _, v in inside if v > obj.threshold_s)
            out[w] = (bad, len(inside))
        return out

    def burn_rates(self, now: float | None = None) -> dict:
        """{objective name: {window seconds: burn rate}}. An empty
        window burns 0 (no traffic spends no budget)."""
        now = tracing.now() if now is None else now
        rates: dict[str, dict[float, float]] = {}
        for name, obj in self.objectives.items():
            counts = self._window_counts(name, now)
            rates[name] = {
                w: (bad / total) / obj.budget if total else 0.0
                for w, (bad, total) in counts.items()
            }
        return rates

    def budget_remaining(self, now: float | None = None) -> dict:
        """{objective name: remaining budget fraction over the LONGEST
        window}: 1.0 = untouched, 0.0 = exactly spent, negative =
        overrun (kept signed so dashboards show how far over)."""
        now = tracing.now() if now is None else now
        longest = self.windows[-1]
        out = {}
        for name, obj in self.objectives.items():
            bad, total = self._window_counts(name, now)[longest]
            frac = (bad / total) if total else 0.0
            out[name] = 1.0 - frac / obj.budget
        return out

    def snapshot(self, now: float | None = None) -> dict:
        """/debug/slo payload: objectives, per-window counts and burn
        rates, remaining budget — everything the gauges summarize, with
        the raw counts for auditability."""
        now = tracing.now() if now is None else now
        remaining = self.budget_remaining(now)
        doc: dict = {"now": now, "windows": list(self.windows),
                     "objectives": {}}
        for name, obj in self.objectives.items():
            counts = self._window_counts(name, now)
            doc["objectives"][name] = {
                "threshold_s": obj.threshold_s,
                "objective": obj.objective,
                "budget": obj.budget,
                "windows": {
                    str(int(w)): {
                        "bad": bad,
                        "total": total,
                        "burn_rate": (
                            (bad / total) / obj.budget if total else 0.0
                        ),
                    }
                    for w, (bad, total) in counts.items()
                },
                "budget_remaining": remaining[name],
            }
        return doc
