"""Step-level engine profiler: one fixed-size record per device dispatch.

The span layer (tracing.py) answers "where did THIS request's latency
go"; this module answers "what was the ENGINE doing" — how full each
batched dispatch was, how many padded tokens the bucketing burned, and
whether a step paid a first-dispatch-of-shape compile. The reference
operator has nothing at this level (vLLM keeps the equivalent inside
its scheduler, vllm.go:93-112 only proxies the process); our engine
owns the step loop, so it can be first-class.

Design constraints, matching tracing.py:

- **Clock discipline.** Callers stamp start/end with ``tracing.now()``
  (the one timestamp source), so SimulatedClock tests get bit-stable
  goodput/occupancy numbers.
- **Plain bounded ring.** A ``deque(maxlen=...)`` of frozen records —
  no ``os.urandom``, no ids — so profiling never perturbs the seeded
  RNG streams the samplers and the fault registry rely on, and memory
  is bounded under sustained traffic.
- **Cheap on the hot path.** ``record()`` is a tuple build + deque
  append under a lock; the KV pool (which takes its own lock) is only
  *sampled* every ``kv_sample_every`` records, with the last sample
  carried forward in between.

Readers (the /metrics scrape, ``stats_summary()``, bench) pull
snapshots; the monotonic ``seq`` lets a scraper replay only the records
it has not yet folded into its histograms.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass

from kubeinfer_tpu.analysis.racecheck import make_lock
from kubeinfer_tpu.observability import tracing

__all__ = ["StepRecord", "StepProfiler"]

PHASES = ("prefill", "decode", "spec", "chunk")


@dataclass(frozen=True)
class StepRecord:
    """One device dispatch, as the scheduler saw it."""

    seq: int  # monotonic dispatch index (scrape cursors key on it)
    t: float  # dispatch end, tracing-clock seconds
    phase: str  # "prefill" | "decode" | "spec" | "chunk"
    bucket: int  # compiled-shape knob: suffix bucket / batch width
    live_rows: int  # rows carrying a real request
    n_slots: int  # batch capacity the dispatch was padded to
    live_tokens: int  # tokens that reached a request this step
    padded_tokens: int  # tokens computed for padding only
    dur_s: float  # step wall time (end - start)
    compiled: bool  # first dispatch of (phase, bucket) on this profiler
    kv_in_use: int  # sampled pool blocks referenced (-1 = not sampled)
    kv_free: int  # sampled pool free-list size (-1 = not sampled)
    # model steps fused into this ONE dispatch (decode windows: K; every
    # other phase: 1). dur_s brackets the whole window, so per-step time
    # is dur_s / steps and per-token timestamps inside the bracket are
    # interpolated (docs/OBSERVABILITY.md). Defaulted so records built
    # by older callers/tests keep their shape.
    steps: int = 1

    def occupancy(self) -> float:
        return self.live_rows / max(1, self.n_slots)

    def padding_waste(self) -> float:
        total = self.live_tokens + self.padded_tokens
        return self.padded_tokens / total if total else 0.0

    def to_dict(self) -> dict:
        return {
            "seq": self.seq, "t": self.t, "phase": self.phase,
            "bucket": self.bucket, "live_rows": self.live_rows,
            "n_slots": self.n_slots, "live_tokens": self.live_tokens,
            "padded_tokens": self.padded_tokens, "dur_s": self.dur_s,
            "compiled": self.compiled, "kv_in_use": self.kv_in_use,
            "kv_free": self.kv_free, "steps": self.steps,
        }


class StepProfiler:
    """Fixed-capacity ring of :class:`StepRecord`.

    ``kv_stats`` is an optional ``() -> (in_use, free)`` callback
    (ContinuousEngine wires the block pool's counters). It is invoked
    OUTSIDE this profiler's lock so the lock order stays acyclic with
    the pool's own lock (docs/ARCHITECTURE.md lock-order table).
    """

    def __init__(self, n_slots: int, capacity: int = 2048,
                 kv_sample_every: int = 8, kv_stats=None,
                 name: str = "observability.StepProfiler._lock") -> None:
        self.n_slots = n_slots
        self._kv_stats = kv_stats
        self._kv_sample_every = max(1, kv_sample_every)
        self._lock = make_lock(name)
        self._ring: collections.deque[StepRecord] = collections.deque(
            maxlen=capacity
        )
        self._seq = 0
        self._seen_shapes: set[tuple[str, int]] = set()
        self._compile_count = 0
        self._last_kv = (-1, -1)

    # -- writer (scheduler thread) -----------------------------------------

    def record(self, phase: str, bucket: int, live_rows: int,
               live_tokens: int, padded_tokens: int,
               start: float, end: float, steps: int = 1) -> StepRecord:
        """Append one dispatch record; returns it (tests and the flight
        recorder read fields straight off the return)."""
        kv_in_use, kv_free = self._last_kv
        sample = (
            self._kv_stats is not None
            and self._seq % self._kv_sample_every == 0
        )
        if sample:
            kv_in_use, kv_free = self._kv_stats()
        with self._lock:
            shape = (phase, bucket)
            compiled = shape not in self._seen_shapes
            if compiled:
                self._seen_shapes.add(shape)
                self._compile_count += 1
            if sample:
                self._last_kv = (kv_in_use, kv_free)
            rec = StepRecord(
                seq=self._seq, t=end, phase=phase, bucket=bucket,
                live_rows=live_rows, n_slots=self.n_slots,
                live_tokens=live_tokens, padded_tokens=padded_tokens,
                dur_s=max(0.0, end - start), compiled=compiled,
                kv_in_use=kv_in_use, kv_free=kv_free, steps=steps,
            )
            self._seq += 1
            self._ring.append(rec)
        return rec

    # -- readers (any thread) ----------------------------------------------

    @property
    def compile_count(self) -> int:
        with self._lock:
            return self._compile_count

    def snapshot(self, since_seq: int = -1) -> list[StepRecord]:
        """Records with ``seq > since_seq`` (all, by default). The
        /metrics scrape passes its last-seen seq so step-duration
        histogram observations are made exactly once per dispatch."""
        with self._lock:
            return [r for r in self._ring if r.seq > since_seq]

    def summary(self, window_s: float = 60.0,
                now: float | None = None) -> dict:
        """Sliding-window aggregates over records with
        ``t >= now - window_s``.

        goodput = live tokens emitted in the window / window width —
        the serving throughput that excludes padding (the raw step
        count times batch width is what a naive tokens/sec would
        report; the gap between the two IS the waste this profiler
        exists to expose). Occupancy averages over decode steps (the
        steady-state shape); with no decode steps yet it falls back to
        all records so a prefill-only engine still reports something
        truthful.
        """
        now = tracing.now() if now is None else now
        recs = self.snapshot()
        win = [r for r in recs if r.t >= now - window_s]
        live = sum(r.live_tokens for r in win)
        padded = sum(r.padded_tokens for r in win)
        decode = [r for r in win if r.phase == "decode"]
        occ_base = decode or win
        occupancy = (
            sum(r.occupancy() for r in occ_base) / len(occ_base)
            if occ_base else 0.0
        )
        # denominator = fused model steps (per-ROW token positions),
        # not live_tokens: live_tokens scales with batch width, which
        # would make the ratio depend on occupancy. Per-step it is
        # exactly 1.0 for the single-step loop and 1/K for fused
        # windows (0.125 at K=8) at any batch width. bench.py
        # publishes it as decode_dispatches_per_token.
        decode_steps = sum(r.steps for r in decode)
        return {
            "window_s": window_s,
            "steps": len(win),
            "goodput_tokens_per_sec": live / window_s if window_s else 0.0,
            "batch_occupancy": occupancy,
            "padding_waste_frac": padded / max(1, live + padded),
            "compile_count": self.compile_count,
            "decode_dispatches_per_token": (
                len(decode) / decode_steps if decode_steps else 0.0
            ),
        }

    def counter_events(self, pid: int) -> list[dict]:
        """Chrome trace-event ``C`` (counter) samples: Perfetto draws
        one curve per ``name``, sampled at each step's end time —
        occupancy and padded tokens alongside the span timeline."""
        events: list[dict] = []
        for r in self.snapshot():
            ts = r.t * 1e6
            events.append({
                "ph": "C", "name": "batch_occupancy", "pid": pid,
                "tid": 0, "ts": ts,
                "args": {"live_rows": r.live_rows},
            })
            events.append({
                "ph": "C", "name": "padded_tokens", "pid": pid,
                "tid": 0, "ts": ts,
                "args": {"padded": r.padded_tokens},
            })
        return events
