"""In-process distributed tracing: spans, W3C trace context, ring recorder.

The reference operator has no request-scoped tracing at all (its
observability is the coarse Prometheus counters mirrored in
``metrics/registry.py``, pkg/metrics/metrics.go:27-146); this layer is
new mechanism, motivated by the serving path: one completion crosses
manager -> store -> node-agent -> engine with retries and fault
injection in between, and only a shared trace id can say which hop the
latency lived in.

Design constraints, in order:

- **No heavy deps.** No OpenTelemetry SDK in the image, so the span
  model is hand-rolled: 128-bit trace id / 64-bit span id, parent
  links, timed events, all hex-encoded exactly as W3C ``traceparent``
  wants them, so the wire format IS the standard one and a future OTLP
  exporter only needs a translator.
- **Import leaf.** This module imports only ``utils.clock`` and the
  lock factories — never metrics, resilience, or httpbase — so every
  other layer (including those two) can import it without cycles.
- **Deterministic under test.** All timestamps come from a ``Clock``;
  tests swap in ``SimulatedClock`` via :func:`set_clock` (or a
  per-tracer clock) and get bit-stable span timings.
- **Bounded memory.** Spans land in a fixed-capacity ring
  (:class:`SpanRecorder`); a serving process under load overwrites old
  traces instead of growing.
- **Cheap when idle.** ``add_event`` and ``current_context`` are a
  thread-local list peek; no span active means no allocation.

Span ids use ``os.urandom`` rather than ``random`` so tracing never
perturbs the seeded RNG streams the fault-injection registry and the
samplers rely on.
"""

from __future__ import annotations

import collections
import logging
import os
import re
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Iterator

from kubeinfer_tpu.analysis.racecheck import make_lock
from kubeinfer_tpu.utils.clock import Clock, RealClock

__all__ = [
    "SpanContext",
    "Span",
    "SpanRecorder",
    "Tracer",
    "TraceContextFilter",
    "RECORDER",
    "add_event",
    "current_context",
    "current_span",
    "get_tracer",
    "new_root_context",
    "now",
    "parse_traceparent",
    "set_clock",
    "set_span_sampling",
    "span_sampling",
    "to_chrome_trace",
    "trace_sampled",
]


# --- trace context ---------------------------------------------------------


_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


@dataclass(frozen=True)
class SpanContext:
    """The propagated identity of a span: what crosses process/hop
    boundaries (W3C trace-context `traceparent`, version 00)."""

    trace_id: str  # 32 lowercase hex chars
    span_id: str  # 16 lowercase hex chars

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


def parse_traceparent(header: str | None) -> SpanContext | None:
    """Parse a ``traceparent`` header; malformed or all-zero ids yield
    None (the spec says an invalid header restarts the trace rather
    than poisoning it)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id)


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def new_root_context() -> SpanContext:
    """Fresh trace anchor for work with no inbound context (direct
    ``submit()`` callers, bench runs): children parented to it still
    group under one trace id even though the anchor span itself is
    never recorded."""
    return SpanContext(_new_trace_id(), _new_span_id())


# --- clock indirection -----------------------------------------------------

# module default used by every tracer without an explicit clock;
# swapped wholesale by tests (never mutated concurrently with reads
# that care — a mid-test swap only skews timestamps, never crashes)
_default_clock: Clock = RealClock()


def set_clock(clock: Clock) -> Clock:
    """Install ``clock`` as the default tracing clock; returns the
    previous one so tests can restore it."""
    global _default_clock
    prev = _default_clock
    _default_clock = clock
    return prev


def now() -> float:
    """Current tracing time (seconds). The one timestamp source for
    span start/end and the instrumented request timelines, so
    simulated-clock tests see a single coherent timeline."""
    return _default_clock.now()


# --- head sampling ---------------------------------------------------------

# Keep-1-in-N switch applied at the single record point
# (:meth:`Tracer.finish`). The decision is a pure function of the trace
# id, NOT a counter or RNG draw: every hop of a distributed request
# (router, prefill replica, decode replica) hashes the same propagated
# trace id to the same verdict, so a sampled-in trace keeps ALL its
# spans and a sampled-out one keeps none — ledgers stay whole or absent,
# never partial. Sampled-out requests still count in metrics: the
# histograms and SLO monitors read the request timeline fields
# (t_submit/t_admit/t_first/t_done), which this switch never touches.
_sample_every: int = 1


def set_span_sampling(n: int) -> int:
    """Keep one trace in ``n`` (1 = keep everything, the default);
    returns the previous setting so callers can restore it."""
    global _sample_every
    if n < 1:
        raise ValueError(f"span sampling must be >= 1, got {n}")
    prev = _sample_every
    _sample_every = n
    return prev


def span_sampling() -> int:
    return _sample_every


def trace_sampled(trace_id: str, n: int | None = None) -> bool:
    """Deterministic keep/drop verdict for a trace id. The low 32 bits
    of the (uniformly random) id are as good a hash as any; a malformed
    id is kept so a bad inbound header degrades to over-recording, not
    a silent ledger hole."""
    n = _sample_every if n is None else n
    if n <= 1:
        return True
    try:
        return int(trace_id[-8:], 16) % n == 0
    except ValueError:
        return True


# --- spans -----------------------------------------------------------------


class Span:
    """One timed operation. Mutable until ended; recorded exactly once.

    Not thread-safe by design: a span belongs to the thread (or the
    single scheduler owner) that created it. Cross-thread causality is
    expressed by passing the span's ``context`` as another span's
    parent, never by sharing the Span object.
    """

    __slots__ = (
        "name", "component", "trace_id", "span_id", "parent_id",
        "start", "end", "attrs", "events",
    )

    def __init__(self, name: str, component: str, trace_id: str,
                 span_id: str, parent_id: str | None, start: float,
                 attrs: dict | None = None) -> None:
        self.name = name
        self.component = component
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end: float | None = None
        self.attrs: dict = dict(attrs) if attrs else {}
        self.events: list[tuple[float, str, dict]] = []

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, ts: float | None = None, **attrs) -> None:
        self.events.append((now() if ts is None else ts, name, attrs))

    def duration(self) -> float:
        return (self.end if self.end is not None else self.start) - self.start

    def __repr__(self) -> str:  # debugging aid only
        return (
            f"Span({self.name!r}, component={self.component!r}, "
            f"trace={self.trace_id[:8]}.., span={self.span_id}, "
            f"parent={self.parent_id}, dur={self.duration():.6f})"
        )


class SpanRecorder:
    """Fixed-capacity ring of ended spans.

    The capacity bounds memory under sustained traffic; readers get
    snapshots (copies) so export never races recording.
    """

    def __init__(self, capacity: int = 8192,
                 name: str = "observability.SpanRecorder._lock") -> None:
        self._lock = make_lock(name)
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=capacity
        )

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def snapshot(self, trace_id: str | None = None) -> list[Span]:
        with self._lock:
            spans = list(self._spans)
        if trace_id is not None:
            spans = [s for s in spans if s.trace_id == trace_id]
        return spans

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def to_chrome_trace(self, trace_id: str | None = None) -> dict:
        return to_chrome_trace(self.snapshot(trace_id))


RECORDER = SpanRecorder()


# --- thread-local active-span stack ---------------------------------------

# plain threading.local: per-thread state needs no lock by construction
_tls = threading.local()


def _stack() -> list[Span]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Span | None:
    st = _stack()
    return st[-1] if st else None


def current_context() -> SpanContext | None:
    sp = current_span()
    return sp.context if sp is not None else None


def add_event(name: str, **attrs) -> None:
    """Attach a timed event to the innermost active span of THIS
    thread; silently a no-op when none is active — instrumentation
    sites (retry loops, fault points) call this unconditionally."""
    sp = current_span()
    if sp is not None:
        sp.event(name, **attrs)


# --- tracer ----------------------------------------------------------------


class Tracer:
    """Factory for spans of one component.

    ``recorder``/``clock`` default to the module globals *at call
    time*, so tests that install a SimulatedClock via :func:`set_clock`
    affect tracers created before the swap too.
    """

    def __init__(self, component: str, recorder: SpanRecorder | None = None,
                 clock: Clock | None = None) -> None:
        self.component = component
        self._recorder = recorder
        self._clock = clock

    def _now(self) -> float:
        return (self._clock or _default_clock).now()

    def _rec(self) -> SpanRecorder:
        return self._recorder if self._recorder is not None else RECORDER

    def start_span(self, name: str, parent: SpanContext | None = None,
                   start: float | None = None, **attrs) -> Span:
        """Create (but do not activate or record) a span. ``parent``
        None means: the thread's current span if any, else a new root."""
        if parent is None:
            parent = current_context()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = _new_trace_id(), None
        return Span(
            name, self.component, trace_id, _new_span_id(), parent_id,
            self._now() if start is None else start, attrs,
        )

    def finish(self, span: Span, end: float | None = None) -> Span:
        # THE single record point — both span() and record_span() land
        # here, so the head-sampling gate lives here and nowhere else.
        # The span is still ended and returned either way: callers that
        # read timings/attrs off the return see no difference.
        span.end = self._now() if end is None else end
        if trace_sampled(span.trace_id):
            self._rec().record(span)
        return span

    def record_span(self, name: str, start: float, end: float,
                    parent: SpanContext | None = None, **attrs) -> Span:
        """Record a span retroactively from timestamps captured
        elsewhere — how the batcher turns its request timeline
        (submit/admit/first-token/done) into queue-wait/prefill/decode
        spans without holding a live span across scheduler passes."""
        span = self.start_span(name, parent=parent, start=start, **attrs)
        return self.finish(span, end=end)

    @contextmanager
    def span(self, name: str, parent: SpanContext | None = None,
             **attrs) -> Iterator[Span]:
        """Run a block under an active span: pushed on the thread's
        stack (so nested spans and ``add_event`` parent correctly),
        error-annotated on exception, always ended and recorded."""
        sp = self.start_span(name, parent=parent, **attrs)
        st = _stack()
        st.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.set(error=type(e).__name__)
            raise
        finally:
            if st and st[-1] is sp:
                st.pop()
            else:  # defensive: unbalanced exit must not corrupt siblings
                try:
                    st.remove(sp)
                except ValueError:
                    pass
            self.finish(sp)


def get_tracer(component: str) -> Tracer:
    return Tracer(component)


# --- exporters -------------------------------------------------------------


def to_chrome_trace(spans: list[Span]) -> dict:
    """Render spans as Chrome trace-event JSON (the format under
    ``docs/traces/``, loadable in Perfetto / chrome://tracing).

    Mapping: component -> pid (named via ``M`` metadata events), trace
    -> tid (so one request's spans share a row, named ``trace <id..>``
    via ``thread_name`` metadata — Perfetto then labels every row by
    component/trace instead of raw integers), span -> ``X`` complete
    event, span event -> ``i`` instant. Times are microseconds as the
    format requires; trace/span/parent ids ride in ``args`` so the
    causal links survive the conversion.
    """
    components = sorted({s.component for s in spans})
    pid_of = {c: i + 1 for i, c in enumerate(components)}
    tids: dict[str, int] = {}
    events: list[dict] = []
    for c in components:
        events.append({
            "ph": "M", "name": "process_name", "pid": pid_of[c], "tid": 0,
            "args": {"name": c},
        })
    # (pid, tid) -> trace id: one thread_name metadata event per row a
    # component actually uses (tids are shared across components so one
    # trace aligns horizontally across process groups)
    rows: dict[tuple[int, int], str] = {}
    for s in spans:
        tid = tids.setdefault(s.trace_id, len(tids) + 1)
        pid = pid_of[s.component]
        rows.setdefault((pid, tid), s.trace_id)
        end = s.end if s.end is not None else s.start
        args = {
            "trace_id": s.trace_id,
            "span_id": s.span_id,
            "parent_id": s.parent_id or "",
        }
        args.update({k: v for k, v in s.attrs.items()})
        events.append({
            "ph": "X", "name": s.name, "cat": s.component,
            "pid": pid, "tid": tid,
            "ts": s.start * 1e6, "dur": max(0.0, (end - s.start) * 1e6),
            "args": args,
        })
        for ts, name, attrs in s.events:
            events.append({
                "ph": "i", "s": "t", "name": name, "cat": s.component,
                "pid": pid, "tid": tid, "ts": ts * 1e6,
                "args": dict(attrs),
            })
    for (pid, tid), trace_id in sorted(rows.items()):
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": f"trace {trace_id[:8]}"},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --- logging correlation ---------------------------------------------------


class TraceContextFilter(logging.Filter):
    """Stamps every record with the emitting thread's ``trace_id`` (or
    ``-``), so a format string like ``%(trace_id)s %(message)s``
    correlates log lines with the trace that produced them. A Filter
    rather than an Adapter so one ``addFilter`` covers a whole handler
    regardless of which logger emitted."""

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = current_context()
        record.trace_id = ctx.trace_id if ctx is not None else "-"
        return True
