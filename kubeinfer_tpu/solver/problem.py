"""Static-shape tensor encoding of a scheduling problem.

Jobs and nodes arrive as Python lists that vary per reconcile tick; XLA wants
static shapes. Both axes are padded up to bucketed sizes (powers of two) so
the jitted solver compiles once per bucket pair and is reused across ticks
(SURVEY.md §7 hard part 2). Padding rows/columns are marked invalid and can
never be chosen.

Structure-of-arrays layout: each job field is one contiguous vector, so the
solver's [J, N] broadcasts are pure vectorized ops on the MXU/VPU — no
ragged per-job structures anywhere on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# Bucket sizes for both axes: powers of two plus 1.5x midpoints, so padding
# overhead stays <= 50% while keeping the jit-cache small. Smallest 64 keeps
# tiny test problems cheap; largest covers the 50k-job soak (BASELINE.json
# config 5).
BUCKETS = (
    64, 128, 256, 384, 512, 768, 1024, 1536, 2048, 3072, 4096, 6144,
    8192, 12288, 16384, 24576, 32768, 49152, 65536,
)

# Max distinct model ids participating in cache-affinity scoring per solve.
# Models beyond the table share slot 0 ("no affinity"); static so the
# node-cache bitmap has a fixed shape.
MAX_MODELS = 256

GIB = float(1024**3)


def bucket_size(n: int) -> int:
    """Smallest bucket >= n (>= 1)."""
    for b in BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"problem axis {n} exceeds max bucket {BUCKETS[-1]}")


@dataclass
class JobSet:
    """Padded job-side arrays (length J). One row per *replica* to place.

    ``gang_id`` couples rows into all-or-nothing groups (gang scheduling,
    BASELINE.json config 3); -1 = no gang. ``current_node`` is the incumbent
    placement (-1 = none) feeding the hysteresis term so full re-solves under
    churn don't thrash placements (config 4, SURVEY.md §7 hard part 4).
    """

    gpu_demand: jax.Array  # f32[J] chips requested (fractional allowed)
    mem_demand: jax.Array  # f32[J] accelerator memory, GiB
    priority: jax.Array  # f32[J] higher = more important
    gang_id: jax.Array  # i32[J] -1 = no gang
    model_id: jax.Array  # i32[J] slot in the model table (0 = none)
    current_node: jax.Array  # i32[J] incumbent node index, -1 = unplaced
    valid: jax.Array  # bool[J] padding mask

    def tree_flatten(self):  # registered below
        return (
            (self.gpu_demand, self.mem_demand, self.priority, self.gang_id,
             self.model_id, self.current_node, self.valid),
            None,
        )


@dataclass
class NodeSet:
    """Padded node-side arrays (length N).

    ``cached`` is the node x model bitmap behind cache-affinity scoring: a
    replica whose model already sits on a node's disk is cheaper there (the
    tensor form of the reference's shared-cache goal — its coordinator /
    follower plane exists to create exactly these cache hits).
    ``topology`` holds (group) coordinates for affinity scoring
    (BASELINE.json config 5).
    """

    gpu_free: jax.Array  # f32[N]
    mem_free: jax.Array  # f32[N] GiB
    gpu_capacity: jax.Array  # f32[N] total chips (normalizes fit scoring)
    mem_capacity: jax.Array  # f32[N] total GiB
    topology: jax.Array  # i32[N] topology group id
    cached: jax.Array  # bool[N, MAX_MODELS]
    valid: jax.Array  # bool[N]


@dataclass
class Problem:
    """One tick's scheduling problem, fully on device.

    Deliberately carries NO true (unpadded) counts: the ``valid`` masks
    are the on-device truth, and host-side callers track their own true
    sizes (e.g. ``SolveRequest.num_jobs``). An earlier revision kept true
    counts here as pytree metadata — which keyed the jit cache, so every
    distinct job count recompiled the solver and defeated bucketing.
    """

    jobs: JobSet
    nodes: NodeSet


jax.tree_util.register_dataclass(
    JobSet,
    data_fields=["gpu_demand", "mem_demand", "priority", "gang_id", "model_id",
                 "current_node", "valid"],
    meta_fields=[],
)
jax.tree_util.register_dataclass(
    NodeSet,
    data_fields=["gpu_free", "mem_free", "gpu_capacity", "mem_capacity",
                 "topology", "cached", "valid"],
    meta_fields=[],
)
jax.tree_util.register_dataclass(
    Problem,
    data_fields=["jobs", "nodes"],
    meta_fields=[],
)


@dataclass
class JobRow:
    """Host-side description of one replica to place (pre-encoding)."""

    gpu: float = 0.0
    mem_gib: float = 0.0
    priority: float = 0.0
    gang: int = -1
    model: str = ""
    current_node: int = -1


@dataclass
class NodeRow:
    """Host-side description of one node (pre-encoding)."""

    gpu_free: float = 0.0
    mem_free_gib: float = 0.0
    topology: int = 0
    cached_models: Sequence[str] = field(default_factory=tuple)
    gpu_capacity: float = 0.0  # 0 => same as gpu_free
    mem_capacity_gib: float = 0.0  # 0 => same as mem_free_gib


def _densify_gangs(gang: np.ndarray) -> np.ndarray:
    """Remap arbitrary gang ids to dense [0, n_gangs) so they always fit the
    solver's segment-op bound (gang ids must be < J; see _gang_repair).
    Without this, ids >= J would clip together and merge distinct gangs."""
    out = np.full(gang.shape, -1, np.int32)
    mask = gang >= 0
    if mask.any():
        _, inverse = np.unique(gang[mask], return_inverse=True)
        out[mask] = inverse.astype(np.int32)
    return out


def _padded_sizes(
    J_true: int, N_true: int, job_multiple: int, node_multiple: int
) -> tuple[int, int]:
    """Bucketed padded axis sizes, rounded up to the mesh-axis multiples —
    shared by the dict-based and direct-pack encoders so their layouts
    can never desync."""
    J = bucket_size(max(J_true, 1))
    N = bucket_size(max(N_true, 1))
    J = -(-J // max(job_multiple, 1)) * max(job_multiple, 1)
    N = -(-N // max(node_multiple, 1)) * max(node_multiple, 1)
    return J, N


def _clamp_model_ids(jm: np.ndarray) -> np.ndarray:
    """Out-of-table model slots collapse to 0 ("no affinity") rather than
    letting a downstream clip manufacture false cache hits for whichever
    model owns slot MAX_MODELS-1."""
    return np.where((jm >= 0) & (jm < MAX_MODELS), jm, 0)


def _prep_padded_arrays(
    *,
    job_gpu: np.ndarray,
    job_mem_gib: np.ndarray,
    job_priority: np.ndarray | None = None,
    job_gang: np.ndarray | None = None,
    job_model: np.ndarray | None = None,
    job_current_node: np.ndarray | None = None,
    node_gpu_free: np.ndarray,
    node_mem_free_gib: np.ndarray,
    node_gpu_capacity: np.ndarray | None = None,
    node_mem_capacity_gib: np.ndarray | None = None,
    node_topology: np.ndarray | None = None,
    node_cached: np.ndarray | None = None,
    job_multiple: int = 1,
    node_multiple: int = 1,
    job_perm: np.ndarray | None = None,
) -> tuple[dict[str, np.ndarray], dict[str, np.ndarray], int, int, int, int]:
    """Shared host-side prep: bucket, pad, densify. Returns numpy fields
    (jobs dict, nodes dict) + (J_true, N_true, J, N).

    ``job_perm`` reorders the job axis during the padding copy (one fused
    fancy-index per field instead of a separate pre-permutation pass) —
    the backend's priority sort uses this; see backends.py.
    """
    J_true = int(job_gpu.shape[0])
    N_true = int(node_gpu_free.shape[0])
    J, N = _padded_sizes(J_true, N_true, job_multiple, node_multiple)

    def padj(a, fill, dtype):
        out = np.full(J, fill, dtype)
        if job_perm is None:
            out[:J_true] = a
        else:
            out[:J_true] = np.asarray(a)[job_perm]
        return out

    def padn(a, fill, dtype):
        out = np.full(N, fill, dtype)
        out[:N_true] = a
        return out

    cached = np.zeros((N, MAX_MODELS), bool)
    if node_cached is not None:
        cached[:N_true, : node_cached.shape[1]] = node_cached
    jvalid = np.zeros(J, bool)
    jvalid[:J_true] = True
    nvalid = np.zeros(N, bool)
    nvalid[:N_true] = True

    zeros_j = np.zeros(J_true, np.float32)
    jobs = {
        "gpu_demand": padj(job_gpu, 0, np.float32),
        "mem_demand": padj(job_mem_gib, 0, np.float32),
        "priority": padj(
            job_priority if job_priority is not None else zeros_j,
            0, np.float32,
        ),
        "gang_id": padj(
            _densify_gangs(np.asarray(job_gang, np.int32))
            if job_gang is not None
            else np.full(J_true, -1),
            -1, np.int32,
        ),
        "model_id": padj(
            # Out-of-table slots collapse to 0 ("no affinity") rather than
            # letting jnp.take's clip manufacture false cache hits for
            # whichever model owns slot MAX_MODELS-1.
            _clamp_model_ids(np.asarray(job_model))
            if job_model is not None
            else np.zeros(J_true),
            0, np.int32,
        ),
        "current_node": padj(
            job_current_node
            if job_current_node is not None
            else np.full(J_true, -1),
            -1, np.int32,
        ),
        "valid": jvalid,
    }
    nodes = {
        "gpu_free": padn(node_gpu_free, 0, np.float32),
        "mem_free": padn(node_mem_free_gib, 0, np.float32),
        "gpu_capacity": padn(
            node_gpu_capacity if node_gpu_capacity is not None else node_gpu_free,
            0, np.float32,
        ),
        "mem_capacity": padn(
            node_mem_capacity_gib
            if node_mem_capacity_gib is not None
            else node_mem_free_gib,
            0, np.float32,
        ),
        "topology": padn(
            node_topology if node_topology is not None else np.zeros(N_true),
            0, np.int32,
        ),
        "cached": cached,
        "valid": nvalid,
    }
    return jobs, nodes, J_true, N_true, J, N


def encode_problem_arrays(
    *,
    job_gpu: np.ndarray,
    job_mem_gib: np.ndarray,
    job_priority: np.ndarray | None = None,
    job_gang: np.ndarray | None = None,
    job_model: np.ndarray | None = None,  # i32 model slots (0 = none)
    job_current_node: np.ndarray | None = None,
    node_gpu_free: np.ndarray,
    node_mem_free_gib: np.ndarray,
    node_gpu_capacity: np.ndarray | None = None,
    node_mem_capacity_gib: np.ndarray | None = None,
    node_topology: np.ndarray | None = None,
    node_cached: np.ndarray | None = None,  # bool [N, MAX_MODELS]
    job_multiple: int = 1,
    node_multiple: int = 1,
) -> Problem:
    """Vectorized fast path: pack pre-built numpy arrays (one np.pad + one
    device_put per field). This is what the reconciler and benchmarks use —
    O(J+N) numpy ops, no per-object Python loop. ``encode_problem`` below is
    the convenience row-based wrapper for small problems and tests.

    ``job_multiple``/``node_multiple`` round the padded axis up to a multiple
    of a mesh axis size, so shards stay equal-sized when the problem is
    placed on a device mesh whose axis does not divide the bucket (buckets
    are all multiples of 64, so powers of two <= 64 never need this)."""
    jobs, nodes, J_true, N_true, _, _ = _prep_padded_arrays(
        job_gpu=job_gpu, job_mem_gib=job_mem_gib, job_priority=job_priority,
        job_gang=job_gang, job_model=job_model,
        job_current_node=job_current_node,
        node_gpu_free=node_gpu_free, node_mem_free_gib=node_mem_free_gib,
        node_gpu_capacity=node_gpu_capacity,
        node_mem_capacity_gib=node_mem_capacity_gib,
        node_topology=node_topology, node_cached=node_cached,
        job_multiple=job_multiple, node_multiple=node_multiple,
    )
    return Problem(
        jobs=JobSet(**{k: jnp.asarray(v) for k, v in jobs.items()}),
        nodes=NodeSet(**{k: jnp.asarray(v) for k, v in nodes.items()}),
    )


# --- single-buffer packing (one host->device transfer per solve) -----------
#
# Under a remote PJRT attachment every device_put pays per-transfer
# overhead; 14 field transfers per solve cost more than the solve. The
# packed path lays the whole problem into ONE contiguous f32 buffer
# (i32/bool regions bitcast — no value conversion) and unpacks with free
# slices/bitcasts inside the jitted solve.
#
# Layout, in 4-byte words (J/N are the padded bucket sizes):
#   [0,   3J) job f32 fields: gpu_demand, mem_demand, priority
#   [3J,  7J) job i32 fields: gang_id, model_id, current_node, valid
#   [7J, 7J+4N) node f32 fields: gpu_free, mem_free, gpu_capacity,
#               mem_capacity
#   [7J+4N, 7J+6N) node i32 fields: topology, valid
#   [7J+6N, 7J+6N+N*MAX_MODELS/4) cached bitmap, uint8[N, MAX_MODELS]

_CACHED_WORDS = MAX_MODELS // 4  # f32 words per node of cached bitmap


def packed_words(J: int, N: int) -> int:
    return 7 * J + 6 * N + N * _CACHED_WORDS


def pack_problem_arrays(
    *,
    job_gpu: np.ndarray,
    job_mem_gib: np.ndarray,
    job_priority: np.ndarray | None = None,
    job_gang: np.ndarray | None = None,
    job_model: np.ndarray | None = None,
    job_current_node: np.ndarray | None = None,
    node_gpu_free: np.ndarray,
    node_mem_free_gib: np.ndarray,
    node_gpu_capacity: np.ndarray | None = None,
    node_mem_capacity_gib: np.ndarray | None = None,
    node_topology: np.ndarray | None = None,
    node_cached: np.ndarray | None = None,
    job_multiple: int = 1,
    node_multiple: int = 1,
    job_perm: np.ndarray | None = None,
) -> tuple[np.ndarray, int, int, int, int]:
    """Host-side packing; same kwargs as ``encode_problem_arrays``.

    Returns ``(buf f32[packed_words], J_true, N_true, J, N)``.

    Fields are written DIRECTLY into their buffer slices (one zeroed
    allocation, one copy per field) rather than materializing 14 padded
    intermediates and copying them again — the pack sits inside the
    headline pack+solve latency, and the double-copy was ~half its cost.
    ``job_perm`` applies the backend's priority permutation during the
    field copy (see backends.py).
    """
    J_true = int(job_gpu.shape[0])
    N_true = int(node_gpu_free.shape[0])
    J, N = _padded_sizes(J_true, N_true, job_multiple, node_multiple)

    # np.empty + explicit pad fills: np.zeros would page-fault the whole
    # buffer lazily on first write; the pad tails are a fraction of it
    buf = np.empty(packed_words(J, N), np.float32)
    i32 = buf.view(np.int32)

    def putf(o, a, pad=0.0):
        dst = buf[o : o + J]
        dst[J_true:] = pad
        if a is None:
            dst[:J_true] = pad
        else:
            a = np.asarray(a)
            dst[:J_true] = a[job_perm] if job_perm is not None else a

    putf(0, job_gpu)
    putf(J, job_mem_gib)
    putf(2 * J, job_priority)
    gang = i32[3 * J : 4 * J]
    gang[J_true:] = -1
    if job_gang is not None:
        gang[:J_true] = _densify_gangs(
            np.asarray(job_gang, np.int32)[job_perm]
            if job_perm is not None
            else np.asarray(job_gang, np.int32)
        )
    else:
        gang[:J_true] = -1
    model = i32[4 * J : 5 * J]
    model[J_true:] = 0
    if job_model is not None:
        jm = np.asarray(job_model)
        if job_perm is not None:
            jm = jm[job_perm]
        model[:J_true] = _clamp_model_ids(jm)
    else:
        model[:J_true] = 0
    cur = i32[5 * J : 6 * J]
    cur[:] = -1
    if job_current_node is not None:
        jc = np.asarray(job_current_node, np.int32)
        cur[:J_true] = jc[job_perm] if job_perm is not None else jc
    jv = i32[6 * J : 7 * J]
    jv[:J_true] = 1
    jv[J_true:] = 0
    o = 7 * J

    def putn(off, a, fallback=None):
        dst = buf[off : off + N]
        dst[N_true:] = 0.0
        dst[:N_true] = a if a is not None else fallback

    putn(o, node_gpu_free)
    putn(o + N, node_mem_free_gib)
    putn(o + 2 * N, node_gpu_capacity, node_gpu_free)
    putn(o + 3 * N, node_mem_capacity_gib, node_mem_free_gib)
    topo = i32[o + 4 * N : o + 5 * N]
    topo[N_true:] = 0
    if node_topology is not None:
        topo[:N_true] = node_topology
    else:
        topo[:N_true] = 0
    nv = i32[o + 5 * N : o + 6 * N]
    nv[:N_true] = 1
    nv[N_true:] = 0
    cached = buf[o + 6 * N :].view(np.uint8).reshape(N, MAX_MODELS)
    if node_cached is not None:
        nc = np.asarray(node_cached)
        w = nc.shape[1]
        cached[:N_true, :w] = nc
        cached[:N_true, w:] = 0
        cached[N_true:] = 0
    else:
        cached[:] = 0
    return buf, J_true, N_true, J, N


def unpack_problem(buf: jax.Array, J: int, N: int) -> Problem:
    """Jittable inverse of ``pack_problem_arrays`` (slices + bitcasts only;
    XLA fuses these into the consumers, so unpacking is effectively free).
    """
    from jax import lax

    def f32(o, n):
        return lax.slice(buf, (o,), (o + n,))

    def i32(o, n):
        return lax.bitcast_convert_type(f32(o, n), jnp.int32)

    gpu_d, mem_d, prio = f32(0, J), f32(J, J), f32(2 * J, J)
    gang, model, cur = i32(3 * J, J), i32(4 * J, J), i32(5 * J, J)
    jvalid = i32(6 * J, J) != 0
    o = 7 * J
    gpu_f, mem_f = f32(o, N), f32(o + N, N)
    gpu_c, mem_c = f32(o + 2 * N, N), f32(o + 3 * N, N)
    topo = i32(o + 4 * N, N)
    nvalid = i32(o + 5 * N, N) != 0
    cached = lax.bitcast_convert_type(
        f32(o + 6 * N, N * _CACHED_WORDS).reshape(N, _CACHED_WORDS),
        jnp.uint8,
    ).reshape(N, MAX_MODELS) != 0
    return Problem(
        jobs=JobSet(
            gpu_demand=gpu_d, mem_demand=mem_d, priority=prio,
            gang_id=gang, model_id=model, current_node=cur, valid=jvalid,
        ),
        nodes=NodeSet(
            gpu_free=gpu_f, mem_free=mem_f, gpu_capacity=gpu_c,
            mem_capacity=mem_c, topology=topo, cached=cached, valid=nvalid,
        ),
    )


def encode_problem(
    jobs: Sequence[JobRow],
    nodes: Sequence[NodeRow],
) -> tuple[Problem, dict[str, int]]:
    """Pack host-side rows into padded device arrays.

    Returns the Problem plus the model-name -> slot table used (so callers
    can interpret cache stats). Encoding is plain numpy — O(J + N + cache
    entries) host work — then one transfer per field.
    """
    J = bucket_size(max(len(jobs), 1))
    N = bucket_size(max(len(nodes), 1))

    model_table: dict[str, int] = {}

    def model_slot(name: str) -> int:
        if not name:
            return 0
        if name not in model_table:
            if len(model_table) + 1 >= MAX_MODELS:
                return 0  # table full: no affinity signal for this model
            model_table[name] = len(model_table) + 1  # slot 0 reserved: none
        return model_table[name]

    gpu_d = np.zeros(J, np.float32)
    mem_d = np.zeros(J, np.float32)
    prio = np.zeros(J, np.float32)
    gang = np.full(J, -1, np.int32)
    model = np.zeros(J, np.int32)
    cur = np.full(J, -1, np.int32)
    jvalid = np.zeros(J, bool)
    for i, j in enumerate(jobs):
        gpu_d[i] = j.gpu
        mem_d[i] = j.mem_gib
        prio[i] = j.priority
        gang[i] = j.gang
        model[i] = model_slot(j.model)
        cur[i] = j.current_node
        jvalid[i] = True
    gang[: len(jobs)] = _densify_gangs(gang[: len(jobs)])

    gpu_f = np.zeros(N, np.float32)
    mem_f = np.zeros(N, np.float32)
    gpu_c = np.zeros(N, np.float32)
    mem_c = np.zeros(N, np.float32)
    topo = np.zeros(N, np.int32)
    cached = np.zeros((N, MAX_MODELS), bool)
    nvalid = np.zeros(N, bool)
    for i, n in enumerate(nodes):
        gpu_f[i] = n.gpu_free
        mem_f[i] = n.mem_free_gib
        gpu_c[i] = n.gpu_capacity or n.gpu_free
        mem_c[i] = n.mem_capacity_gib or n.mem_free_gib
        topo[i] = n.topology
        for m in n.cached_models:
            s = model_slot(m)
            if s:
                cached[i, s] = True
        nvalid[i] = True

    problem = Problem(
        jobs=JobSet(
            gpu_demand=jnp.asarray(gpu_d),
            mem_demand=jnp.asarray(mem_d),
            priority=jnp.asarray(prio),
            gang_id=jnp.asarray(gang),
            model_id=jnp.asarray(model),
            current_node=jnp.asarray(cur),
            valid=jnp.asarray(jvalid),
        ),
        nodes=NodeSet(
            gpu_free=jnp.asarray(gpu_f),
            mem_free=jnp.asarray(mem_f),
            gpu_capacity=jnp.asarray(gpu_c),
            mem_capacity=jnp.asarray(mem_c),
            topology=jnp.asarray(topo),
            cached=jnp.asarray(cached),
            valid=jnp.asarray(nvalid),
        ),
    )
    return problem, model_table
