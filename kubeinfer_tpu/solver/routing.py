"""Batched request->replica routing as a bucketed solver problem.

The fleet router (router/core.py) scores replicas one request at a time
in Python — fine at trickle rates, quadratic pain in an arrival storm.
This module folds a whole arrival batch into ONE jit dispatch over a
requests x replicas cost tensor built from the planes the fleet already
advertises: prefix match-depth from the radix fingerprint summaries,
queue pressure and admission slots, KV headroom (``kv_blocks_free`` /
``kv_pool_bytes``, real per-replica signals), and the hard masks
(dead / draining / breaker-open / per-request excluded) folded into a
single -1 sentinel on the match plane.

Shapes follow the placement solver's bucketing contract exactly
(problem.py BUCKETS): both axes pad to bucket sizes so the solve
compiles once per (B, R) bucket pair and static weights, and padding
rows/columns can never be chosen. Fingerprints are 63-bit FNV values —
wider than the device int32 lane — so the membership/match plane is
built HOST-side in numpy int64 (one searchsorted per request chain
against the union of advertised sets) and only the small [B, R] i32
match plane plus [R] vectors ship to the device; problem.py already
encodes host-side for the same reason.

Three solve modes, all one dispatch:

- ``parity``: every row takes its independent masked argmax — the exact
  batched form of ``FleetRouter.route``'s per-request scan. B=1 is the
  degenerate case the router pins byte-compatible in tests.
- ``greedy``: rounds with queue-pressure feedback — each accepted
  assignment raises its replica's effective pressure by 1/slots and
  per-round acceptance is capped at the replica's slot width, so a
  storm of identical prompts spreads instead of dog-piling the one
  warm replica. Within a round, contended slots go to the
  lowest-request-index bidders (deterministic, documented).
- ``auction``: Bertsekas-style forward auction — each round the best
  bidder per replica wins at a price raised by its bid (value gap to
  its second choice + eps); prices rise where contention is real and
  later rounds route around them. Non-displacing (assignments are
  final), so the classic eps-optimality bound does not strictly hold;
  stragglers past ``max_rounds`` complete via the parity fill.

The per-round primitive (masked score row-argmax) has a Pallas kernel
(pallas_kernels.route_pick_pallas) bit-identical to its jnp twin — see
the parity argument in pallas_kernels.py; the twin is the CPU/test and
unaligned-bucket path.

Divergence from the reference: llmservice_controller.go:66-174 routes
cache-blind through a Service/kube-proxy (random member selection);
there is no request tier to batch at all. This module exists because
the paper's honesty note names the batched cost-tensor solver as the
genuinely new component — routing is where it finally faces traffic.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubeinfer_tpu.inference.kv_blocks import (
    _FP_MASK,
    _FP_PRIME,
    _FP_SEED,
)
from kubeinfer_tpu.solver import pallas_kernels as pk
from kubeinfer_tpu.solver.problem import bucket_size

# Encode-time clips bounding the score range (documented parity caveats
# vs the unclipped Python scorer; both are far past the point where the
# ordering could matter to a sane fleet):
# - pressure beyond 64 queues-per-slot reads as "saturated, identically
#   repellent" — the Python scorer keeps discounting linearly, but a
#   replica that deep loses to anything unclipped regardless.
# - match depth beyond 4096 blocks exceeds any advertised summary
#   (SUMMARY_FINGERPRINT_BUDGET caps sets at 512, optimistic growth at
#   2048) by 2x; deeper claims clip equal.
PRESSURE_CLIP = 64.0
MATCH_CLIP = 4096

# Auction bid floor: a row with no second choice still raises its
# replica's price by eps, so repeated rounds cannot stall on free wins.
# In block units (the score scale); small vs ALPHA_QUEUE_BLOCKS so
# prices meaningfully move only under real contention.
_AUCTION_EPS = 0.0625


@dataclass
class RouteProblem:
    """One arrival batch's routing problem, fully on device.

    ``match`` folds the hard masks: -1 = this (request, replica) pair is
    ineligible (dead / draining / breaker-open / excluded / padding);
    >= 0 = eligible with that prefix match depth in blocks. Carries no
    true counts (same jit-cache rationale as problem.Problem)."""

    match: jax.Array  # i32[B, R] depth in blocks, -1 = ineligible
    pressure: jax.Array  # f32[R] queue depth / slots, clipped
    stale: jax.Array  # bool[R] signal older than STALE_AFTER_S
    slots: jax.Array  # f32[R] admission slot width (>= 1)
    headroom: jax.Array  # f32[R] free-KV fraction in [0, 1]
    req_valid: jax.Array  # bool[B] padding mask


@dataclass
class RouteAssignment:
    """Route-solve output: per-request replica index (-1 = no eligible
    replica) plus diagnostics."""

    replica: jax.Array  # i32[B]
    score: jax.Array  # f32[B] solver-side score of the chosen replica
    rounds: jax.Array  # i32 solve rounds used


jax.tree_util.register_dataclass(
    RouteProblem,
    data_fields=["match", "pressure", "stale", "slots", "headroom",
                 "req_valid"],
    meta_fields=[],
)
jax.tree_util.register_dataclass(
    RouteAssignment,
    data_fields=["replica", "score", "rounds"],
    meta_fields=[],
)


def batched_prefix_fingerprints(
    token_batch: Sequence[Sequence[int]],
    block_size: int,
    max_depth: int,
) -> np.ndarray:
    """Whole-batch form of ``kv_blocks.prefix_fingerprints``:
    ``i64[B, depth_max]`` with -1 past each request's full-block depth.

    Bit-identical to the per-request chain (pinned in tests): FNV-1a's
    63-bit fold is ``(h ^ t) * PRIME mod 2**63``, and because 2**63
    divides 2**64, numpy's native uint64 wraparound multiply followed
    by the 63-bit mask computes exactly the same residue — so the hash
    vectorizes across the batch with one python-level loop over token
    POSITIONS instead of one per (request, token). This is what keeps
    the plane build off the storm path's critical section: at B=256 the
    per-request Python fold alone would cost more than the solve.
    """
    B = len(token_batch)
    if block_size <= 0:
        raise ValueError(f"block_size must be > 0, got {block_size}")
    lens = np.fromiter((len(t) for t in token_batch), np.int64, B) \
        if B else np.zeros(0, np.int64)
    depths = np.minimum(lens // block_size, max_depth)
    depth_max = int(depths.max()) if B else 0
    out = np.full((B, depth_max), -1, np.int64)
    if depth_max == 0:
        return out
    T = depth_max * block_size
    mask = np.uint64(_FP_MASK)
    prime = np.uint64(_FP_PRIME)
    # zero-pad past each request's tail: padded positions chain garbage
    # into h, but every depth they could affect is masked to -1 below.
    # Rectangular batches (the storm common case — equal-length
    # prompts) convert in one call; the per-row loop is the ragged
    # fallback and the slowest part of the build when taken.
    if lens.min() == lens.max() and int(lens[0]) >= T:
        toks = (
            np.asarray(token_batch, np.int64)[:, :T].astype(np.uint64)
            & mask
        )
    else:
        toks = np.zeros((B, T), np.uint64)
        for b, t in enumerate(token_batch):
            n = min(len(t), T)
            if n:
                toks[b, :n] = (
                    np.asarray(t[:n], np.int64).astype(np.uint64) & mask
                )
    h = np.full(B, _FP_SEED, np.uint64)
    for d in range(depth_max):
        base = d * block_size
        for j in range(block_size):
            h = ((h ^ toks[:, base + j]) * prime) & mask
        out[:, d] = h.astype(np.int64)
    out[np.arange(depth_max)[None, :] >= depths[:, None]] = -1
    return out


def build_match_plane(
    token_batch: Sequence[Sequence[int]],
    fp_sets: Sequence[set | frozenset],
    block_sizes: Sequence[int],
) -> np.ndarray:
    """Host-side [B, R] prefix match-depth plane, in blocks.

    Vectorized form of scoring.match_depth over the whole batch: one
    int64 union table of every advertised fingerprint per block size,
    one searchsorted per request chain, then a depth sweep that keeps
    the DEEPEST membership per replica — identical semantics to the
    deepest-first Python scan (kv_blocks.prefix_fingerprints only
    fingerprints full blocks, so chains are exact prefixes).
    """
    B, R = len(token_batch), len(fp_sets)
    match = np.zeros((B, R), np.int32)
    if B == 0 or R == 0:
        return match
    by_bs: dict[int, list[int]] = {}
    for r, bs in enumerate(block_sizes):
        if bs and fp_sets[r]:
            by_bs.setdefault(int(bs), []).append(r)
    for bs, cols in by_bs.items():
        # -1 pads short chains: FNV fingerprints are 63-bit non-negative,
        # so the sentinel can never collide with a real fingerprint
        fps = batched_prefix_fingerprints(token_batch, bs, MATCH_CLIP)
        depth_max = fps.shape[1]
        if depth_max == 0:
            continue
        union = np.array(
            sorted(frozenset().union(*(fp_sets[r] for r in cols))),
            np.int64,
        )
        # membership bitmap with a trailing all-False row for "not in
        # any set": rows index the union table, columns this bs group
        memb = np.zeros((len(union) + 1, len(cols)), bool)
        for k, r in enumerate(cols):
            memb[
                np.searchsorted(union, np.fromiter(
                    fp_sets[r], np.int64, len(fp_sets[r])
                )),
                k,
            ] = True
        pos = np.searchsorted(union, fps)
        ok = pos < len(union)
        ok &= np.where(
            ok, union[np.minimum(pos, len(union) - 1)] == fps, False
        )
        row = np.where(ok & (fps != -1), pos, len(union))
        depth = np.zeros((B, len(cols)), np.int32)
        # ascending-d overwrite keeps the deepest hit — matches the
        # scorer's deepest-first scan (summary truncation can drop an
        # ancestor while keeping a deeper node)
        for d in range(depth_max):
            depth = np.where(memb[row[:, d]], d + 1, depth)
        match[:, cols] = depth
    return match


def pack_route_arrays(
    match: np.ndarray,  # i32[B_true, R_true], -1 = ineligible
    pressure: np.ndarray,  # f32[R_true]
    stale: np.ndarray,  # bool[R_true]
    slots: np.ndarray,  # f32[R_true]
    headroom: np.ndarray,  # f32[R_true]
) -> tuple[RouteProblem, int, int]:
    """Pad to bucket shapes and ship to device. Padding rows/columns
    carry match=-1 (never choosable) and req_valid=False. Returns
    (problem, B, R) with the padded axis sizes."""
    B_true, R_true = match.shape
    B = bucket_size(max(B_true, 1))
    R = bucket_size(max(R_true, 1))
    m = np.full((B, R), -1, np.int32)
    m[:B_true, :R_true] = np.minimum(match, MATCH_CLIP)
    pr = np.zeros(R, np.float32)
    pr[:R_true] = np.minimum(pressure, PRESSURE_CLIP)
    st = np.zeros(R, bool)
    st[:R_true] = stale
    sl = np.ones(R, np.float32)
    sl[:R_true] = np.maximum(slots, 1.0)
    hr = np.ones(R, np.float32)
    hr[:R_true] = np.clip(headroom, 0.0, 1.0)
    rv = np.zeros(B, bool)
    rv[:B_true] = True
    return (
        RouteProblem(
            match=jnp.asarray(m), pressure=jnp.asarray(pr),
            stale=jnp.asarray(st), slots=jnp.asarray(sl),
            headroom=jnp.asarray(hr), req_valid=jnp.asarray(rv),
        ),
        B,
        R,
    )


def _route_accel(accel: str, B: int, R: int) -> str:
    """Mirror of core._resolve_accel for the route solve: pallas needs
    f32 sublane alignment on B and 128 lanes on R plus a real TPU;
    ``interpret`` runs the kernel on any backend (parity tests)."""
    if accel != "auto":
        if accel not in ("jnp", "pallas", "interpret"):
            raise ValueError(f"unknown accel {accel!r}")
        return accel
    if B % 8 == 0 and R % 128 == 0 and jax.default_backend() == "tpu":
        return "pallas"
    return "jnp"


# Defaults mirror router/scoring.py (ALPHA_QUEUE_BLOCKS /
# STALE_PENALTY_BLOCKS) but are plain numbers here: scoring stays
# numpy/jax-free by charter (the reconciler imports it on its tick
# path), so this module cannot import it without inverting layering;
# tests/test_router_solver.py pins the constants equal.
@functools.partial(
    jax.jit,
    static_argnames=(
        "alpha", "stale_penalty", "gamma", "mode", "max_rounds", "accel",
    ),
)
def solve_routes(
    rp: RouteProblem,
    *,
    alpha: float = 4.0,
    stale_penalty: float = 8.0,
    gamma: float = 0.0,
    mode: str = "parity",
    max_rounds: int = 8,
    accel: str = "auto",
) -> RouteAssignment:
    """Assign every request in the batch to a replica in one dispatch.

    ``score[b, r] = match[b, r] - alpha * pressure[r]
                    - stale_penalty * stale[r] - gamma * (1 - headroom[r])``

    With gamma=0 (the default) this is exactly the router's per-request
    objective (scoring.replica_score) — computed in f32 here vs the
    scorer's float64, a documented tie-break-width caveat; gamma > 0
    adds the KV-headroom plane for storm batches that could overrun a
    replica's free pool. Weights are static: they are per-router
    constants, and baking them keeps the quantization-free score math
    (one f32 add per candidate) identical between the Pallas kernel and
    its jnp twin. Ties resolve to the lowest replica index; callers
    sort the replica axis by name, making this the router's
    lowest-name tie-break.
    """
    B, R = rp.match.shape
    resolved = _route_accel(accel, B, R)
    if resolved == "jnp":
        pick = pk.route_pick_jnp
    else:
        pick = functools.partial(
            pk.route_pick_pallas, interpret=(resolved == "interpret")
        )
    bias0 = (
        jnp.float32(-alpha) * rp.pressure
        - jnp.where(rp.stale, jnp.float32(stale_penalty), jnp.float32(0.0))
        - jnp.float32(gamma) * (jnp.float32(1.0) - rp.headroom)
    )
    has_cand = jnp.any(rp.match >= 0, axis=1) & rp.req_valid

    if mode == "parity":
        v, i = pick(rp.match, bias0, has_cand)
        return RouteAssignment(replica=i, score=v, rounds=jnp.int32(1))

    if mode == "greedy":
        inv_slots = jnp.float32(1.0) / rp.slots
        r_iota = lax.broadcasted_iota(jnp.int32, (B, R), 1)

        def cond(st):
            assigned, _load, rounds = st
            return jnp.any((assigned < 0) & has_cand) & (
                rounds < max_rounds
            )

        def body(st):
            assigned, load, rounds = st
            active = (assigned < 0) & has_cand
            _v, i = pick(rp.match, bias0 - jnp.float32(alpha) * load,
                         active)
            onehot = active[:, None] & (i[:, None] == r_iota)
            # exclusive rank by request index among this round's bidders
            # for each replica; f32 cumsum is exact up to 2^24 rows
            rank = jnp.cumsum(onehot.astype(jnp.float32), axis=0) - onehot
            accept = onehot & (rank < rp.slots[None, :])
            got = jnp.any(accept, axis=1)
            assigned = jnp.where(got, i, assigned)
            load = load + jnp.sum(
                accept, axis=0
            ).astype(jnp.float32) * inv_slots
            return assigned, load, rounds + 1

        init = (
            jnp.full((B,), -1, jnp.int32),
            jnp.zeros((R,), jnp.float32),
            jnp.int32(0),
        )
        assigned, load, rounds = lax.while_loop(cond, body, init)
        # completeness fill: slots >= 1 guarantees per-round progress,
        # but max_rounds can still strand cap-starved rows — they take
        # their plain argmax at the final feedback-adjusted bias
        active = (assigned < 0) & has_cand
        _v, i = pick(rp.match, bias0 - jnp.float32(alpha) * load, active)
        assigned = jnp.where(active, i, assigned)
        return RouteAssignment(
            replica=assigned,
            score=_gather_scores(rp, bias0, assigned),
            rounds=rounds,
        )

    if mode == "auction":
        neg = jnp.float32(pk.ROUTE_NEG)
        b_iota = lax.broadcasted_iota(jnp.int32, (B, R), 0)
        r_iota = lax.broadcasted_iota(jnp.int32, (B, R), 1)

        def cond(st):
            assigned, _price, rounds = st
            return jnp.any((assigned < 0) & has_cand) & (
                rounds < max_rounds
            )

        def body(st):
            assigned, price, rounds = st
            active = (assigned < 0) & has_cand
            bias = bias0 - price
            v1, i1 = pick(rp.match, bias, active)
            # second-best value: mask each row's first choice, re-pick
            v2, i2 = pick(
                jnp.where(r_iota == i1[:, None], -1, rp.match),
                bias, active,
            )
            v2 = jnp.where(i2 >= 0, v2, v1)  # sole candidate: bid eps
            bid = v1 - v2 + jnp.float32(_AUCTION_EPS)
            onehot = active[:, None] & (i1[:, None] == r_iota)
            bids = jnp.where(onehot, bid[:, None], neg)
            wv = jnp.max(bids, axis=0)
            # winner = highest bid, ties to the lowest request index
            wb = jnp.min(
                jnp.where(bids == wv[None, :], b_iota,
                          jnp.int32(0x7FFFFFFF)),
                axis=0,
            )
            win = onehot & (b_iota == wb[None, :])
            got = jnp.any(win, axis=1)
            assigned = jnp.where(got, i1, assigned)
            price = price + jnp.where(wv > neg, wv, jnp.float32(0.0))
            return assigned, price, rounds + 1

        init = (
            jnp.full((B,), -1, jnp.int32),
            jnp.zeros((R,), jnp.float32),
            jnp.int32(0),
        )
        assigned, price, rounds = lax.while_loop(cond, body, init)
        active = (assigned < 0) & has_cand
        _v, i = pick(rp.match, bias0 - price, active)
        assigned = jnp.where(active, i, assigned)
        return RouteAssignment(
            replica=assigned,
            score=_gather_scores(rp, bias0, assigned),
            rounds=rounds,
        )

    raise ValueError(f"unknown route mode {mode!r}")


def _gather_scores(
    rp: RouteProblem, bias0: jax.Array, assigned: jax.Array
) -> jax.Array:
    """Base-plane score of each chosen replica (feedback/price terms
    excluded — diagnostics report the objective the router documents,
    not the transient solve state)."""
    safe = jnp.maximum(assigned, 0)
    m = jnp.take_along_axis(rp.match, safe[:, None], axis=1)[:, 0]
    s = m.astype(jnp.float32) + jnp.take(bias0, safe)
    return jnp.where(assigned >= 0, s, jnp.float32(pk.ROUTE_NEG))


def decode_routes(out: RouteAssignment, n_requests: int) -> np.ndarray:
    """Host readback of the assignment, clipped to the true batch.

    Padding rows carry match=-1 everywhere so their index is -1; the
    clip is lossless."""
    # lint: allow[host-sync] the ONE deliberate readback per batched route solve — the router must hand each request its replica now
    rep = jax.device_get(out.replica)
    return np.asarray(rep[:n_requests], np.int32)


def solved_affinity(
    job_model: np.ndarray,  # i32[B] model slots (0 = none)
    node_cached: np.ndarray,  # uint8[N, MAX_MODELS]
    node_pressure: np.ndarray,  # f32[N]
    node_slots: np.ndarray,  # f32[N]
    *,
    alpha: float,
    cutoff: float,
    max_rounds: int = 8,
) -> np.ndarray:
    """Reconciler affinity bitmap from a real route solve.

    Replaces the old binary PRESSURE_AFFINITY_CUTOFF gate: each job row
    becomes a pseudo-request whose match depth on a caching node is
    ``cutoff * alpha`` blocks — the depth at which the router's score
    goes negative exactly when pressure reaches the cutoff, so the old
    gate's semantics fall out of the same cost planes the router
    solves. The greedy mode's pressure feedback then spreads pulls
    across caching nodes, and a node keeps its affinity bit only where
    the solve actually assigned one of that model's pseudo-requests to
    it AND the model is genuinely cached there (an uncached node picked
    purely for load must not claim a cache hit in the placement
    tensor).

    Divergence from the old gate (deliberate): the cutoff is now
    RELATIVE — a caching node drowning at pressure p keeps its pull
    against alternatives within ``cutoff`` of it, instead of every node
    past an absolute threshold going cache-blind at once.
    """
    B = int(len(job_model))
    N = int(node_cached.shape[0])
    out = np.zeros_like(node_cached)
    if B == 0 or N == 0:
        return out
    jm = np.clip(np.asarray(job_model, np.int64), 0,
                 node_cached.shape[1] - 1)
    cached_for_job = node_cached[:, jm].T.astype(bool)  # [B, N]
    if not cached_for_job.any():
        return out  # no affinity signal anywhere: skip the dispatch
    mscale = max(int(round(cutoff * alpha)), 1)
    match = np.where(cached_for_job, mscale, 0).astype(np.int32)
    rp, _, _ = pack_route_arrays(
        match,
        np.asarray(node_pressure, np.float32),
        np.zeros(N, bool),
        np.asarray(node_slots, np.float32),
        np.ones(N, np.float32),
    )
    assigned = decode_routes(
        solve_routes(
            rp, alpha=float(alpha), stale_penalty=0.0, mode="greedy",
            max_rounds=max_rounds,
        ),
        B,
    )
    hit = (assigned >= 0) & cached_for_job[np.arange(B),
                                           np.clip(assigned, 0, N - 1)]
    out[assigned[hit], np.asarray(job_model)[hit]] = 1
    return out
