"""Pallas TPU kernels for the greedy solver's round loop.

Why these exist: the round loop is a handful of [N, J] reductions whose
producers are broadcasts of [J]/[N] vectors. Under plain XLA each reduction
materializes its producer to HBM (measured ~1.3ms/round at 12288x1024 on a
v5e — ~8 full HBM round-trips), because multi-consumer broadcast producers
defeat reduction fusion. Here each round becomes:

- ONE ``bid`` kernel: tiles the resident [N, J] cost field S through VMEM
  (TILE_N=128 sublanes x TILE_J lanes), fusing feasibility, the per-node
  priority fence, static-bound cost quantization, and the packed
  (cost | node) i32 min — S is read from HBM at most once per round and
  nothing [N, J]-sized is ever written back. The J axis is tiled so VMEM
  holds at most [128, 1024] f32 per block regardless of the job bucket —
  the 50k-job soak shape would otherwise blow the 16MB VMEM scoped limit.
  The fence minimum over ALL jobs (``minrank``) therefore arrives as an
  input (it only reads vectors; the caller computes it as a fused jnp
  reduction).
- TWO ``accept`` passes (first chance + second chance), each a verdict
  kernel (per-node bidder totals + winner + fit verdicts + consumed
  capacity in one sweep — the [TILE_N, TILE_J] broadcast lives only in
  VMEM, accumulating across J tiles) feeding a ``flags`` kernel (the
  per-job accept bit, ``core._dense_accept``'s [N, J] broadcast-compare
  + any). Fusing the fit/consumed [N]-vector math into the verdict sweep
  removes ~6 XLA fusions per accept from the dispatch-bound round.
- ONE ``fence`` kernel: the per-node fence minimum (``core._fence_minrank``),
  an [N, J] feasibility broadcast + rank min — under XLA another full
  [N, J] VPU pass per round even though its inputs are vectors.

Per-J-tile early-out (the round-3 speedup): every kernel takes a
scalar-prefetched per-tile activity vector. The priority fence means only
one fence class (~1/4 of jobs, when the backend priority-sorts the job
axis) can bid in any round, and late rounds are straggler tails of a few
hundred jobs — so most J tiles provably produce no bids (all-BIG output /
zero accept contribution). Inactive tiles skip their compute, and the bid
kernel also skips the S HBM read itself: its S BlockSpec index_map routes
an inactive tile to the previous active tile's block, and Mosaic's
pipeline elides the DMA when consecutive grid steps map to the same block
(measured on v5e: 11/12 tiles aliased -> ~8x less bid-kernel time).
Activity is computed from the same fence/placed vectors the kernels
already consume, so skipping is bit-identical to the dense evaluation
(an inactive tile's jobs all fail the in-kernel ``allowed`` mask anyway).

The jnp reference implementations live in ``core.py`` (`_round_bids_jnp`,
`_accept_reduce_jnp`) and remain the code path for CPU tests, sharded
(GSPMD) solves, and bucket shapes not divisible by 128. ``interpret=True``
runs these kernels on CPU for parity tests.

Design refs: /opt/skills/guides/pallas_guide.md (grid/BlockSpec, iota,
reduction patterns). No reference-repo counterpart exists: the reference
scheduler has no placement solver at all (SURVEY.md §0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_N = 128
# 1024 measured best on v5e: full HBM bandwidth on the S sweep (746GB/s vs
# 521GB/s at 512 — per-grid-step overhead bites below 1024) while keeping
# the early-out granularity fine enough that one fence class spans ~3 of
# 12 tiles at the 12288-job bucket.
MAX_TILE_J = 1024
# Plain Python scalars: module-level jnp constants would be captured by the
# kernel closures, which pallas_call rejects ("captures constants"). Packed
# values are non-negative int32 (i31): Mosaic has no unsigned reductions.
_I32MAX = 0x7FFFFFFF
_EPS = 1e-4
# Large-but-finite sentinel for "this job may not bid" (placed/invalid);
# finite so `rank <= minrank` comparisons stay well-defined.
RANK_INF = 1e9


def _require_aligned(N: int, J: int) -> None:
    """All round kernels share the same layout contract: node axis a
    multiple of TILE_N, job axis 128-lane aligned."""
    if N % TILE_N or J % 128:
        raise ValueError(
            f"pallas round kernels need 128-aligned axes, got N={N} J={J}; "
            "use accel='jnp' for unaligned bucket shapes"
        )


def _tile_j(J: int) -> int:
    """Largest J tile <= MAX_TILE_J that divides the bucket (buckets are
    128-aligned)."""
    if J <= MAX_TILE_J:
        return J
    for t in (MAX_TILE_J, 768, 512, 384, 256, 128):
        if J % t == 0:
            return t
    raise ValueError(f"no J tile divides {J}")


def tile_activity(
    active_j: jax.Array,  # bool[J] "this job may produce a bid"
    J: int,
) -> tuple[jax.Array, jax.Array]:
    """Per-J-tile (alias, act) vectors for the scalar-prefetch early-out.

    ``act[t]`` is 1 iff any job in tile t is active. ``alias[t]`` is the
    S-block index the bid kernel should load for tile t: t itself when
    active, else the nearest active tile at or before t (falling back to
    0 for a leading inactive run) — consecutive grid steps then map to
    the same block and Mosaic skips the DMA entirely.
    """
    tj = _tile_j(J)
    tiles = J // tj
    act = jnp.any(active_j.reshape(tiles, tj), axis=1)
    t_iota = jnp.arange(tiles, dtype=jnp.int32)
    alias = jnp.maximum(
        jax.lax.cummax(jnp.where(act, t_iota, jnp.int32(-1))), 0
    )
    return alias.astype(jnp.int32), act.astype(jnp.int32)


def _bid_kernel(
    alias_ref,  # i32[tiles_j] scalar-prefetch: S block to load per tile
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile has potential bidders
    d_ref,  # [1, TILE_J] f32 gpu demand
    md_ref,  # [1, TILE_J] f32 mem demand
    rankf_ref,  # [1, TILE_J] f32 fence rank, RANK_INF when may-not-bid
    cur_ref,  # [1, TILE_J] i32 incumbent node index, -1 = none
    gf_ref,  # [TILE_N, 1] f32 gpu free (invalid nodes pre-folded to -1)
    mf_ref,  # [TILE_N, 1] f32 mem free
    u_ref,  # [TILE_N, 1] f32 live best-fit pressure
    minrank_ref,  # [TILE_N, 1] f32 per-node fence minimum (over ALL jobs)
    s_ref,  # [TILE_N, TILE_J] f32 resident cost field tile (aliased when
    #         inactive — contents must not be read then)
    out_ref,  # [8, TILE_J] i32 per-16-node-group packed (cost | node) mins
    *,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
):
    del alias_ref  # consumed by the S BlockSpec index_map only
    tn = pl.program_id(0)
    tj = pl.program_id(1)
    big = jnp.int32(_I32MAX)
    rank_inf = jnp.float32(RANK_INF)

    # Inactive tile: every job in it fails the `allowed` mask below (its
    # rank exceeds every node's fence minimum and it has no home-bid
    # exemption — see core's activity rule), so the dense result is
    # all-BIG. Emit that directly; the S block under s_ref is an aliased
    # stand-in whose DMA the pipeline already skipped.
    @pl.when(act_ref[tj] == 0)
    def _inactive():
        out_ref[:] = jnp.full_like(out_ref, big)

    @pl.when(act_ref[tj] != 0)
    def _active():
        d = d_ref[:]
        md = md_ref[:]
        rankf = rankf_ref[:]
        gf = gf_ref[:]
        mf = mf_ref[:]

        feas = (d <= gf + _EPS) & (md <= mf + _EPS)  # [TILE_N, TILE_J]
        q = jnp.clip((s_ref[:] + u_ref[:] - q_lo) * q_scale, 0.0, q_max)
        n_glob = tn * TILE_N + jax.lax.broadcasted_iota(
            jnp.int32, feas.shape, 0
        )
        # Per-node priority fence: bid only if no higher-priority unplaced
        # job finds this node feasible anywhere in [0, J). RANK_INF rows
        # drop out. Incumbents are exempt on their OWN node
        # (core._round_bids_jnp twin).
        is_home = cur_ref[:] == n_glob
        allowed = (
            feas
            & ((rankf <= minrank_ref[:]) | is_home)
            & (rankf < rank_inf * 0.5)
        )
        packed = jnp.where(
            allowed,
            (q.astype(jnp.int32) << node_idx_bits) | n_glob,
            big,
        )
        # Eight 16-node group mins per tile: the TPU output block needs
        # >= 8 sublanes anyway, and finer groups give the second-chance
        # pass better alternates. Even a single-tile problem (N=128) has
        # 7 other groups.
        out_ref[:] = jnp.min(
            packed.reshape(8, TILE_N // 8, packed.shape[1]), axis=1
        )


def bid_reduce_pallas(
    s_t: jax.Array,  # [N, J] resident cost field
    u: jax.Array,  # [N]
    gf_eff: jax.Array,  # [N] (invalid nodes folded to -1)
    mf: jax.Array,  # [N]
    d: jax.Array,  # [J]
    md: jax.Array,  # [J]
    rankf_eff: jax.Array,  # [J] (RANK_INF when may-not-bid)
    minrank: jax.Array,  # [N] fence minimum over all jobs
    current_node: jax.Array,  # i32[J] incumbent node index, -1 = none
    tile_alias: jax.Array,  # i32[tiles_j] S block per tile (see
    #                         tile_activity)
    tile_act: jax.Array,  # i32[tiles_j] 1 = tile may produce bids
    *,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """At most one S read -> (primary, alternate) packed i32 bids per job.

    The alternate is the best node outside the primary's 16-node group —
    a cross-group second choice for the solver's second-chance pass.
    Group mins match core._round_bids_jnp exactly (parity-tested).
    Inactive J tiles (``tile_act`` 0) emit BIG without touching HBM.
    """
    N, J = s_t.shape
    _require_aligned(N, J)
    tiles_n = N // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    kern = functools.partial(
        _bid_kernel,
        q_lo=q_lo,
        q_scale=q_scale,
        q_max=q_max,
        node_idx_bits=node_idx_bits,
    )
    # grid (tn, tj): every (tn, tj) writes a disjoint output block, so
    # grid order is free; tj innermost keeps S reads sequential per node
    # tile AND makes aliased (inactive) tiles consecutive with the active
    # block they point at, which is what lets the pipeline elide their
    # DMAs.
    row = pl.BlockSpec(
        (1, tile_j), lambda tn, tj, alias, act: (0, tj),
        memory_space=pltpu.VMEM,
    )
    col = pl.BlockSpec(
        (TILE_N, 1), lambda tn, tj, alias, act: (tn, 0),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(tiles_n, tiles_j),
        in_specs=[
            row,  # d
            row,  # md
            row,  # rankf
            row,  # current_node
            col,  # gf
            col,  # mf
            col,  # u
            col,  # minrank
            pl.BlockSpec(
                (TILE_N, tile_j),
                lambda tn, tj, alias, act: (tn, alias[tj]),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (8, tile_j), lambda tn, tj, alias, act: (tn, tj),
            memory_space=pltpu.VMEM,
        ),
    )
    per_group = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((8 * tiles_n, J), jnp.int32),
        interpret=interpret,
    )(
        tile_alias,
        tile_act,
        d.reshape(1, J),
        md.reshape(1, J),
        rankf_eff.reshape(1, J),
        current_node.reshape(1, J),
        gf_eff.reshape(N, 1),
        mf.reshape(N, 1),
        u.reshape(N, 1),
        minrank.reshape(N, 1),
        s_t,
    )
    return bid_select_pallas(
        per_group, tile_alias, tile_act, interpret=interpret
    )


def _accept_verdict_kernel(
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile may hold bidders
    ch_ref,  # [1, TILE_J] i32 chosen node (N = no bid)
    key_ref,  # [1, TILE_J] i32 accept key
    d_ref,  # [1, TILE_J] f32 gpu demand
    md_ref,  # [1, TILE_J] f32 mem demand
    gf_ref,  # [TILE_N, 1] f32 gpu free (the capacities bids fit against)
    mf_ref,  # [TILE_N, 1] f32 mem free
    ug_ref,  # [TILE_N, 1] f32 out: capacity consumed (gpu)
    um_ref,  # [TILE_N, 1] f32 out: capacity consumed (mem)
    okall_ref,  # [TILE_N, 1] i32 out: node accepts all bidders
    okwin_ref,  # [TILE_N, 1] i32 out: node accepts its winner
    win_ref,  # [TILE_N, 1] i32 out: winning key
    tg_scr,  # [TILE_N, 1] f32 scratch: bidder gpu total
    tm_scr,  # [TILE_N, 1] f32 scratch
    wd_scr,  # [TILE_N, 1] f32 scratch: winner gpu demand
    wmd_scr,  # [TILE_N, 1] f32 scratch
    *,
    tiles_j: int,
):
    """Accept totals + fit verdicts + consumed capacity in ONE sweep —
    the accept_reduce kernel plus the ~6 inter-kernel [N]-vector fusions
    (fits_all/fits_win/used_*) that each cost dispatch latency in the
    round's critical path (docs/PROFILING.md: the solve is
    dispatch-bound, not bandwidth-bound)."""
    tn = pl.program_id(0)
    tj = pl.program_id(1)
    big = jnp.int32(_I32MAX)

    @pl.when(tj == 0)
    def _init():
        tg_scr[:] = jnp.zeros_like(tg_scr)
        tm_scr[:] = jnp.zeros_like(tm_scr)
        win_ref[:] = jnp.full_like(win_ref, big)
        wd_scr[:] = jnp.zeros_like(wd_scr)
        wmd_scr[:] = jnp.zeros_like(wmd_scr)

    @pl.when(act_ref[tj] != 0)
    def _accum():
        ch = ch_ref[:]
        key = key_ref[:]
        n_glob = tn * TILE_N + jax.lax.broadcasted_iota(
            jnp.int32, (TILE_N, ch.shape[1]), 0
        )
        mine = ch == n_glob
        tg = jnp.sum(jnp.where(mine, d_ref[:], 0.0), axis=1, keepdims=True)
        tm = jnp.sum(jnp.where(mine, md_ref[:], 0.0), axis=1, keepdims=True)
        win = jnp.min(jnp.where(mine, key, big), axis=1, keepdims=True)
        new_win = jnp.minimum(win_ref[:], win)
        winner = mine & (key == new_win)
        wd = jnp.sum(jnp.where(winner, d_ref[:], 0.0), axis=1, keepdims=True)
        wmd = jnp.sum(
            jnp.where(winner, md_ref[:], 0.0), axis=1, keepdims=True
        )
        take = win < win_ref[:]
        tg_scr[:] = tg_scr[:] + tg
        tm_scr[:] = tm_scr[:] + tm
        win_ref[:] = new_win
        wd_scr[:] = jnp.where(take, wd, wd_scr[:])
        wmd_scr[:] = jnp.where(take, wmd, wmd_scr[:])

    @pl.when(tj == tiles_j - 1)
    def _verdicts():
        gf = gf_ref[:]
        mf = mf_ref[:]
        fits_all = (tg_scr[:] <= gf + _EPS) & (tm_scr[:] <= mf + _EPS)
        has_win = win_ref[:] != big
        fits_win = (
            has_win
            & (wd_scr[:] <= gf + _EPS)
            & (wmd_scr[:] <= mf + _EPS)
        )
        okall_ref[:] = fits_all.astype(jnp.int32)
        okwin_ref[:] = fits_win.astype(jnp.int32)
        ug_ref[:] = jnp.where(
            fits_all, tg_scr[:], jnp.where(fits_win, wd_scr[:], 0.0)
        )
        um_ref[:] = jnp.where(
            fits_all, tm_scr[:], jnp.where(fits_win, wmd_scr[:], 0.0)
        )


def accept_phase_pallas(
    choice: jax.Array,  # i32[J] chosen node (N sentinel = no bid)
    accept_key: jax.Array,  # i32[J]
    d: jax.Array,  # f32[J]
    md: jax.Array,  # f32[J]
    gpu_free: jax.Array,  # f32[N]
    mem_free: jax.Array,  # f32[N]
    tile_act: jax.Array,  # i32[tiles_j]
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(accept bool[J], used_gpu f32[N], used_mem f32[N]) for one accept
    pass: the verdict kernel (totals + fits + consumed capacity in one
    sweep) feeds the flags kernel directly — no [N]-vector glue between
    launches. Parity twin of core._dense_accept."""
    J = choice.shape[0]
    N = gpu_free.shape[0]
    _require_aligned(N, J)
    tiles_n = N // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    row = pl.BlockSpec(
        (1, tile_j), lambda tn, tj, act: (0, tj), memory_space=pltpu.VMEM
    )
    col = pl.BlockSpec(
        (TILE_N, 1), lambda tn, tj, act: (tn, 0), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tiles_n, tiles_j),
        in_specs=[row, row, row, row, col, col],
        out_specs=[col] * 5,
        scratch_shapes=[
            pltpu.VMEM((TILE_N, 1), jnp.float32),
            pltpu.VMEM((TILE_N, 1), jnp.float32),
            pltpu.VMEM((TILE_N, 1), jnp.float32),
            pltpu.VMEM((TILE_N, 1), jnp.float32),
        ],
    )
    kern = functools.partial(_accept_verdict_kernel, tiles_j=tiles_j)
    ug, um, okall, okwin, win = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
        ],
        interpret=interpret,
    )(
        tile_act,
        choice.reshape(1, J),
        accept_key.reshape(1, J),
        d.reshape(1, J),
        md.reshape(1, J),
        gpu_free.reshape(N, 1),
        mem_free.reshape(N, 1),
    )
    accept = accept_flags_pallas(
        choice, accept_key, okall[:, 0], okwin[:, 0], win[:, 0], tile_act,
        interpret=interpret,
    )
    return accept, ug[:, 0], um[:, 0]


def _bid_select_kernel(
    alias_ref,  # i32[tiles_j] scalar-prefetch: per_group block per tile
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile may hold bids
    pg_ref,  # [G, TILE_J] i32 per-16-node-group packed mins
    prim_ref,  # [1, TILE_J] i32 out
    alt_ref,  # [1, TILE_J] i32 out
):
    del alias_ref
    tj = pl.program_id(0)
    big = jnp.int32(_I32MAX)

    @pl.when(act_ref[tj] == 0)
    def _inactive():
        prim_ref[:] = jnp.full_like(prim_ref, big)
        alt_ref[:] = jnp.full_like(alt_ref, big)

    @pl.when(act_ref[tj] != 0)
    def _active():
        pg = pg_ref[:]
        prim = jnp.min(pg, axis=0, keepdims=True)
        # Exclude the primary's group by VALUE, not argmin (Mosaic has no
        # i32 argmin): packed bids embed the node index and each group
        # covers a disjoint 16-node range, so a non-BIG group min is
        # globally unique per column — value exclusion selects exactly
        # the argmin group. All-BIG columns stay BIG either way.
        alt_ref[:] = jnp.min(
            jnp.where(pg == prim, big, pg), axis=0, keepdims=True
        )
        prim_ref[:] = prim


def bid_select_pallas(
    per_group: jax.Array,  # i32[G, J] per-16-node-group packed mins
    tile_alias: jax.Array,  # i32[tiles_j]
    tile_act: jax.Array,  # i32[tiles_j]
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """(primary, alternate) per job from the bid kernel's group mins.

    The jnp form is three reductions (min, argmin, masked min) over the
    same [G, J] producer — three HBM passes under XLA since per_group is
    a materialized kernel output. One Pallas sweep reads it once, and
    inactive J tiles (all-BIG columns) skip their read via the same
    alias trick the bid kernel uses. Must match the tail of
    core._round_bids_jnp bit-for-bit (parity-tested).
    """
    G, J = per_group.shape
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(tiles_j,),
        in_specs=[
            pl.BlockSpec(
                (G, tile_j), lambda tj, alias, act: (0, alias[tj]),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, tile_j), lambda tj, alias, act: (0, tj),
                memory_space=pltpu.VMEM,
            ),
        ] * 2,
    )
    prim, alt = pl.pallas_call(
        _bid_select_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((1, J), jnp.int32),
            jax.ShapeDtypeStruct((1, J), jnp.int32),
        ],
        interpret=interpret,
    )(tile_alias, tile_act, per_group)
    return prim[0], alt[0]


def _fence_kernel(
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile has unplaced jobs
    d_ref,  # [1, TILE_J] f32 gpu demand
    md_ref,  # [1, TILE_J] f32 mem demand
    rankf_ref,  # [1, TILE_J] f32 fence rank (RANK_INF = placed/invalid)
    gf_ref,  # [TILE_N, 1] f32 gpu free
    mf_ref,  # [TILE_N, 1] f32 mem free
    out_ref,  # [TILE_N, 1] f32 out: per-node fence minimum
):
    tj = pl.program_id(1)
    rank_inf = jnp.float32(RANK_INF)

    @pl.when(tj == 0)
    def _init():
        out_ref[:] = jnp.full_like(out_ref, rank_inf)

    # A tile whose jobs are all placed/invalid contributes only RANK_INF
    # (its rankf rows are RANK_INF), so skipping it is exact.
    @pl.when(act_ref[tj] != 0)
    def _accum():
        feas = (d_ref[:] <= gf_ref[:] + _EPS) & (md_ref[:] <= mf_ref[:] + _EPS)
        part = jnp.min(
            jnp.where(feas, rankf_ref[:], rank_inf), axis=1, keepdims=True
        )
        out_ref[:] = jnp.minimum(out_ref[:], part)


def fence_minrank_pallas(
    gpu_free: jax.Array,  # f32[N]
    mem_free: jax.Array,  # f32[N]
    gpu_demand: jax.Array,  # f32[J]
    mem_demand: jax.Array,  # f32[J]
    rankf_eff: jax.Array,  # f32[J] (RANK_INF = placed/invalid)
    tile_act: jax.Array,  # i32[tiles_j] 1 = tile has unplaced jobs
    *,
    interpret: bool = False,
) -> jax.Array:
    """Per-node fence minimum — Pallas twin of ``core._fence_minrank``.

    Skips J tiles whose jobs are all placed (their ranks are RANK_INF and
    cannot lower any node's minimum). With the job axis priority-sorted,
    placed jobs become a contiguous prefix as fence classes settle, so
    late rounds reduce over a small suffix instead of all J.
    """
    N = gpu_free.shape[0]
    J = gpu_demand.shape[0]
    _require_aligned(N, J)
    tiles_n = N // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    row = pl.BlockSpec(
        (1, tile_j), lambda tn, tj, act: (0, tj), memory_space=pltpu.VMEM
    )
    col = pl.BlockSpec(
        (TILE_N, 1), lambda tn, tj, act: (tn, 0), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tiles_n, tiles_j),
        in_specs=[row, row, row, col, col],
        out_specs=col,
    )
    out = pl.pallas_call(
        _fence_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, 1), jnp.float32),
        interpret=interpret,
    )(
        tile_act,
        gpu_demand.reshape(1, J),
        mem_demand.reshape(1, J),
        rankf_eff.reshape(1, J),
        gpu_free.reshape(N, 1),
        mem_free.reshape(N, 1),
    )
    return out[:, 0]


def _accept_flags_kernel(
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile has bidders
    ch_ref,  # [1, TILE_J] i32 chosen node (N = no bid)
    key_ref,  # [1, TILE_J] i32 accept key
    all_ref,  # [TILE_N, 1] i32 node accepts all bidders (fits_all)
    winok_ref,  # [TILE_N, 1] i32 node accepts its winner (fits_win)
    winkey_ref,  # [TILE_N, 1] i32 winning key per node
    acc_ref,  # [1, TILE_J] i32 out: job's bid accepted
):
    tn = pl.program_id(1)  # inner: accumulate into the resident out block
    tj = pl.program_id(0)

    @pl.when((tn == 0) & (act_ref[tj] == 0))
    def _inactive():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(act_ref[tj] != 0)
    def _active():
        ch = ch_ref[:]
        n_glob = tn * TILE_N + jax.lax.broadcasted_iota(
            jnp.int32, (TILE_N, ch.shape[1]), 0
        )
        mine = ch == n_glob
        ok = (all_ref[:] != 0) | (
            (winok_ref[:] != 0) & (winkey_ref[:] == key_ref[:])
        )
        hit = jnp.any(mine & ok, axis=0, keepdims=True).astype(jnp.int32)

        @pl.when(tn == 0)
        def _init():
            acc_ref[:] = hit

        @pl.when(tn != 0)
        def _accum():
            acc_ref[:] = acc_ref[:] | hit


def accept_flags_pallas(
    choice: jax.Array,  # i32[J]
    accept_key: jax.Array,  # i32[J]
    fits_all: jax.Array,  # bool[N]
    fits_win: jax.Array,  # bool[N]
    win_key: jax.Array,  # i32[N]
    tile_act: jax.Array,  # i32[tiles_j]
    *,
    interpret: bool = False,
) -> jax.Array:
    """Per-job accept bit — the Pallas twin of ``core._dense_accept``'s
    [N, J] broadcast-compare + any() (which XLA runs as a full second
    [N, J] VPU pass per accept). Grid is (tj, tn) with tn INNER so the
    [1, TILE_J] output block stays VMEM-resident across the node sweep
    (accumulating across a non-innermost dim would round-trip the block
    through HBM each step — and Pallas does not guarantee read-back of
    prior contents for non-consecutive revisits)."""
    J = choice.shape[0]
    N = fits_all.shape[0]
    _require_aligned(N, J)
    tiles_n = N // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    row = pl.BlockSpec(
        (1, tile_j), lambda tj, tn, act: (0, tj), memory_space=pltpu.VMEM
    )
    col = pl.BlockSpec(
        (TILE_N, 1), lambda tj, tn, act: (tn, 0), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tiles_j, tiles_n),
        in_specs=[row, row, col, col, col],
        out_specs=row,
    )
    acc = pl.pallas_call(
        _accept_flags_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, J), jnp.int32),
        interpret=interpret,
    )(
        tile_act,
        choice.reshape(1, J),
        accept_key.reshape(1, J),
        fits_all.astype(jnp.int32).reshape(N, 1),
        fits_win.astype(jnp.int32).reshape(N, 1),
        win_key.reshape(N, 1),
    )
    return acc[0] != 0
