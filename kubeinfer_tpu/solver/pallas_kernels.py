"""Pallas TPU kernels for the greedy solver's round loop.

Why these exist: the round loop is a handful of [N, J] reductions whose
producers are broadcasts of [J]/[N] vectors. Under plain XLA each reduction
materializes its producer to HBM (measured ~1.3ms/round at 12288x1024 on a
v5e — ~8 full HBM round-trips), because multi-consumer broadcast producers
defeat reduction fusion. Here each round becomes:

- ONE ``bid`` kernel: tiles the resident [N, J] cost field S through VMEM
  (TILE_N=128 sublanes x TILE_J lanes), fusing feasibility, the per-node
  priority fence, static-bound cost quantization, and the packed
  (cost | node) i32 min — S is read from HBM at most once per round and
  nothing [N, J]-sized is ever written back. The J axis is tiled so VMEM
  holds at most [128, 1024] f32 per block regardless of the job bucket —
  the 50k-job soak shape would otherwise blow the 16MB VMEM scoped limit.
  The fence minimum over ALL jobs (``minrank``) therefore arrives as an
  input (it only reads vectors; the caller computes it as a fused jnp
  reduction).
- TWO ``accept`` passes (first chance + second chance), each a verdict
  kernel (per-node bidder totals + winner + fit verdicts + consumed
  capacity in one sweep — the [TILE_N, TILE_J] broadcast lives only in
  VMEM, accumulating across J tiles) feeding a ``flags`` kernel (the
  per-job accept bit, ``core._dense_accept``'s [N, J] broadcast-compare
  + any). Fusing the fit/consumed [N]-vector math into the verdict sweep
  removes ~6 XLA fusions per accept from the dispatch-bound round.
- ONE ``fence`` kernel: the per-node fence minimum (``core._fence_minrank``),
  an [N, J] feasibility broadcast + rank min — under XLA another full
  [N, J] VPU pass per round even though its inputs are vectors.

Per-J-tile early-out (the round-3 speedup): every kernel takes a
scalar-prefetched per-tile activity vector. The priority fence means only
one fence class (~1/4 of jobs, when the backend priority-sorts the job
axis) can bid in any round, and late rounds are straggler tails of a few
hundred jobs — so most J tiles provably produce no bids (all-BIG output /
zero accept contribution). Inactive tiles skip their compute, and the bid
kernel also skips the S HBM read itself: its S BlockSpec index_map routes
an inactive tile to the previous active tile's block, and Mosaic's
pipeline elides the DMA when consecutive grid steps map to the same block
(measured on v5e: 11/12 tiles aliased -> ~8x less bid-kernel time).
Activity is computed from the same fence/placed vectors the kernels
already consume, so skipping is bit-identical to the dense evaluation
(an inactive tile's jobs all fail the in-kernel ``allowed`` mask anyway).

The jnp reference implementations live in ``core.py`` (`_round_bids_jnp`,
`_accept_reduce_jnp`) and remain the code path for CPU tests, sharded
(GSPMD) solves, and bucket shapes not divisible by 128. ``interpret=True``
runs these kernels on CPU for parity tests.

Design refs: /opt/skills/guides/pallas_guide.md (grid/BlockSpec, iota,
reduction patterns). No reference-repo counterpart exists: the reference
scheduler has no placement solver at all (SURVEY.md §0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept either so the
# kernels run on both sides of the rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

TILE_N = 128
# 1024 measured best on v5e: full HBM bandwidth on the S sweep (746GB/s vs
# 521GB/s at 512 — per-grid-step overhead bites below 1024) while keeping
# the early-out granularity fine enough that one fence class spans ~3 of
# 12 tiles at the 12288-job bucket.
MAX_TILE_J = 1024
# Plain Python scalars: module-level jnp constants would be captured by the
# kernel closures, which pallas_call rejects ("captures constants"). Packed
# values are non-negative int32 (i31): Mosaic has no unsigned reductions.
_I32MAX = 0x7FFFFFFF
_EPS = 1e-4
# Large-but-finite sentinel for "this job may not bid" (placed/invalid);
# finite so `rank <= minrank` comparisons stay well-defined.
RANK_INF = 1e9


def _require_aligned(N: int, J: int) -> None:
    """All round kernels share the same layout contract: node axis a
    multiple of TILE_N, job axis 128-lane aligned."""
    if N % TILE_N or J % 128:
        raise ValueError(
            f"pallas round kernels need 128-aligned axes, got N={N} J={J}; "
            "use accel='jnp' for unaligned bucket shapes"
        )


def _tile_j(J: int) -> int:
    """Largest J tile <= MAX_TILE_J that divides the bucket (buckets are
    128-aligned)."""
    if J <= MAX_TILE_J:
        return J
    for t in (MAX_TILE_J, 768, 512, 384, 256, 128):
        if J % t == 0:
            return t
    raise ValueError(f"no J tile divides {J}")


def tile_activity(
    active_j: jax.Array,  # bool[J] "this job may produce a bid"
    J: int,
) -> tuple[jax.Array, jax.Array]:
    """Per-J-tile (alias, act) vectors for the scalar-prefetch early-out.

    ``act[t]`` is 1 iff any job in tile t is active. ``alias[t]`` is the
    S-block index the bid kernel should load for tile t: t itself when
    active, else the nearest active tile at or before t (falling back to
    0 for a leading inactive run) — consecutive grid steps then map to
    the same block and Mosaic skips the DMA entirely.
    """
    tj = _tile_j(J)
    tiles = J // tj
    act = jnp.any(active_j.reshape(tiles, tj), axis=1)
    t_iota = jnp.arange(tiles, dtype=jnp.int32)
    alias = jnp.maximum(
        jax.lax.cummax(jnp.where(act, t_iota, jnp.int32(-1))), 0
    )
    return alias.astype(jnp.int32), act.astype(jnp.int32)


def _bid_kernel(
    alias_ref,  # i32[tiles_j] scalar-prefetch: S block to load per tile
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile has potential bidders
    d_ref,  # [1, TILE_J] f32 gpu demand
    md_ref,  # [1, TILE_J] f32 mem demand
    rankf_ref,  # [1, TILE_J] f32 fence rank, RANK_INF when may-not-bid
    cur_ref,  # [1, TILE_J] i32 incumbent node index, -1 = none
    gf_ref,  # [TILE_N, 1] f32 gpu free (invalid nodes pre-folded to -1)
    mf_ref,  # [TILE_N, 1] f32 mem free
    u_ref,  # [TILE_N, 1] f32 live best-fit pressure
    minrank_ref,  # [TILE_N, 1] f32 per-node fence minimum (over ALL jobs)
    s_ref,  # [TILE_N, TILE_J] f32 resident cost field tile (aliased when
    #         inactive — contents must not be read then)
    out_ref,  # [8, TILE_J] i32 per-16-node-group packed (cost | node) mins
    *,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
):
    del alias_ref  # consumed by the S BlockSpec index_map only
    tn = pl.program_id(0)
    tj = pl.program_id(1)
    big = jnp.int32(_I32MAX)
    rank_inf = jnp.float32(RANK_INF)

    # Inactive tile: every job in it fails the `allowed` mask below (its
    # rank exceeds every node's fence minimum and it has no home-bid
    # exemption — see core's activity rule), so the dense result is
    # all-BIG. Emit that directly; the S block under s_ref is an aliased
    # stand-in whose DMA the pipeline already skipped.
    @pl.when(act_ref[tj] == 0)
    def _inactive():
        out_ref[:] = jnp.full_like(out_ref, big)

    @pl.when(act_ref[tj] != 0)
    def _active():
        d = d_ref[:]
        md = md_ref[:]
        rankf = rankf_ref[:]
        gf = gf_ref[:]
        mf = mf_ref[:]

        feas = (d <= gf + _EPS) & (md <= mf + _EPS)  # [TILE_N, TILE_J]
        q = jnp.clip((s_ref[:] + u_ref[:] - q_lo) * q_scale, 0.0, q_max)
        n_glob = tn * TILE_N + jax.lax.broadcasted_iota(
            jnp.int32, feas.shape, 0
        )
        # Per-node priority fence: bid only if no higher-priority unplaced
        # job finds this node feasible anywhere in [0, J). RANK_INF rows
        # drop out. Incumbents are exempt on their OWN node
        # (core._round_bids_jnp twin).
        is_home = cur_ref[:] == n_glob
        allowed = (
            feas
            & ((rankf <= minrank_ref[:]) | is_home)
            & (rankf < rank_inf * 0.5)
        )
        packed = jnp.where(
            allowed,
            (q.astype(jnp.int32) << node_idx_bits) | n_glob,
            big,
        )
        # Eight 16-node group mins per tile: the TPU output block needs
        # >= 8 sublanes anyway, and finer groups give the second-chance
        # pass better alternates. Even a single-tile problem (N=128) has
        # 7 other groups.
        out_ref[:] = jnp.min(
            packed.reshape(8, TILE_N // 8, packed.shape[1]), axis=1
        )


def bid_reduce_pallas(
    s_t: jax.Array,  # [N, J] resident cost field
    u: jax.Array,  # [N]
    gf_eff: jax.Array,  # [N] (invalid nodes folded to -1)
    mf: jax.Array,  # [N]
    d: jax.Array,  # [J]
    md: jax.Array,  # [J]
    rankf_eff: jax.Array,  # [J] (RANK_INF when may-not-bid)
    minrank: jax.Array,  # [N] fence minimum over all jobs
    current_node: jax.Array,  # i32[J] incumbent node index, -1 = none
    tile_alias: jax.Array,  # i32[tiles_j] S block per tile (see
    #                         tile_activity)
    tile_act: jax.Array,  # i32[tiles_j] 1 = tile may produce bids
    *,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """At most one S read -> (primary, alternate) packed i32 bids per job.

    The alternate is the best node outside the primary's 16-node group —
    a cross-group second choice for the solver's second-chance pass.
    Group mins match core._round_bids_jnp exactly (parity-tested).
    Inactive J tiles (``tile_act`` 0) emit BIG without touching HBM.
    """
    N, J = s_t.shape
    _require_aligned(N, J)
    tiles_n = N // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    kern = functools.partial(
        _bid_kernel,
        q_lo=q_lo,
        q_scale=q_scale,
        q_max=q_max,
        node_idx_bits=node_idx_bits,
    )
    # grid (tn, tj): every (tn, tj) writes a disjoint output block, so
    # grid order is free; tj innermost keeps S reads sequential per node
    # tile AND makes aliased (inactive) tiles consecutive with the active
    # block they point at, which is what lets the pipeline elide their
    # DMAs.
    row = pl.BlockSpec(
        (1, tile_j), lambda tn, tj, alias, act: (0, tj),
        memory_space=pltpu.VMEM,
    )
    col = pl.BlockSpec(
        (TILE_N, 1), lambda tn, tj, alias, act: (tn, 0),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(tiles_n, tiles_j),
        in_specs=[
            row,  # d
            row,  # md
            row,  # rankf
            row,  # current_node
            col,  # gf
            col,  # mf
            col,  # u
            col,  # minrank
            pl.BlockSpec(
                (TILE_N, tile_j),
                lambda tn, tj, alias, act: (tn, alias[tj]),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (8, tile_j), lambda tn, tj, alias, act: (tn, tj),
            memory_space=pltpu.VMEM,
        ),
    )
    per_group = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((8 * tiles_n, J), jnp.int32),
        interpret=interpret,
    )(
        tile_alias,
        tile_act,
        d.reshape(1, J),
        md.reshape(1, J),
        rankf_eff.reshape(1, J),
        current_node.reshape(1, J),
        gf_eff.reshape(N, 1),
        mf.reshape(N, 1),
        u.reshape(N, 1),
        minrank.reshape(N, 1),
        s_t,
    )
    return bid_select_pallas(
        per_group, tile_alias, tile_act, interpret=interpret
    )


def _accept_verdict_kernel(
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile may hold bidders
    ch_ref,  # [1, TILE_J] i32 chosen node (N = no bid)
    key_ref,  # [1, TILE_J] i32 accept key
    d_ref,  # [1, TILE_J] f32 gpu demand
    md_ref,  # [1, TILE_J] f32 mem demand
    gf_ref,  # [TILE_N, 1] f32 gpu free (the capacities bids fit against)
    mf_ref,  # [TILE_N, 1] f32 mem free
    ug_ref,  # [TILE_N, 1] f32 out: capacity consumed (gpu)
    um_ref,  # [TILE_N, 1] f32 out: capacity consumed (mem)
    okall_ref,  # [TILE_N, 1] i32 out: node accepts all bidders
    okwin_ref,  # [TILE_N, 1] i32 out: node accepts its winner
    win_ref,  # [TILE_N, 1] i32 out: winning key
    tg_scr,  # [TILE_N, 1] f32 scratch: bidder gpu total
    tm_scr,  # [TILE_N, 1] f32 scratch
    wd_scr,  # [TILE_N, 1] f32 scratch: winner gpu demand
    wmd_scr,  # [TILE_N, 1] f32 scratch
    *,
    tiles_j: int,
):
    """Accept totals + fit verdicts + consumed capacity in ONE sweep —
    the accept_reduce kernel plus the ~6 inter-kernel [N]-vector fusions
    (fits_all/fits_win/used_*) that each cost dispatch latency in the
    round's critical path (docs/PROFILING.md: the solve is
    dispatch-bound, not bandwidth-bound)."""
    tn = pl.program_id(0)
    tj = pl.program_id(1)
    big = jnp.int32(_I32MAX)

    @pl.when(tj == 0)
    def _init():
        tg_scr[:] = jnp.zeros_like(tg_scr)
        tm_scr[:] = jnp.zeros_like(tm_scr)
        win_ref[:] = jnp.full_like(win_ref, big)
        wd_scr[:] = jnp.zeros_like(wd_scr)
        wmd_scr[:] = jnp.zeros_like(wmd_scr)

    @pl.when(act_ref[tj] != 0)
    def _accum():
        ch = ch_ref[:]
        key = key_ref[:]
        n_glob = tn * TILE_N + jax.lax.broadcasted_iota(
            jnp.int32, (TILE_N, ch.shape[1]), 0
        )
        mine = ch == n_glob
        tg = jnp.sum(jnp.where(mine, d_ref[:], 0.0), axis=1, keepdims=True)
        tm = jnp.sum(jnp.where(mine, md_ref[:], 0.0), axis=1, keepdims=True)
        win = jnp.min(jnp.where(mine, key, big), axis=1, keepdims=True)
        new_win = jnp.minimum(win_ref[:], win)
        winner = mine & (key == new_win)
        wd = jnp.sum(jnp.where(winner, d_ref[:], 0.0), axis=1, keepdims=True)
        wmd = jnp.sum(
            jnp.where(winner, md_ref[:], 0.0), axis=1, keepdims=True
        )
        take = win < win_ref[:]
        tg_scr[:] = tg_scr[:] + tg
        tm_scr[:] = tm_scr[:] + tm
        win_ref[:] = new_win
        wd_scr[:] = jnp.where(take, wd, wd_scr[:])
        wmd_scr[:] = jnp.where(take, wmd, wmd_scr[:])

    @pl.when(tj == tiles_j - 1)
    def _verdicts():
        gf = gf_ref[:]
        mf = mf_ref[:]
        fits_all = (tg_scr[:] <= gf + _EPS) & (tm_scr[:] <= mf + _EPS)
        has_win = win_ref[:] != big
        fits_win = (
            has_win
            & (wd_scr[:] <= gf + _EPS)
            & (wmd_scr[:] <= mf + _EPS)
        )
        okall_ref[:] = fits_all.astype(jnp.int32)
        okwin_ref[:] = fits_win.astype(jnp.int32)
        ug_ref[:] = jnp.where(
            fits_all, tg_scr[:], jnp.where(fits_win, wd_scr[:], 0.0)
        )
        um_ref[:] = jnp.where(
            fits_all, tm_scr[:], jnp.where(fits_win, wmd_scr[:], 0.0)
        )


def accept_phase_pallas(
    choice: jax.Array,  # i32[J] chosen node (N sentinel = no bid)
    accept_key: jax.Array,  # i32[J]
    d: jax.Array,  # f32[J]
    md: jax.Array,  # f32[J]
    gpu_free: jax.Array,  # f32[N]
    mem_free: jax.Array,  # f32[N]
    tile_act: jax.Array,  # i32[tiles_j]
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(accept bool[J], used_gpu f32[N], used_mem f32[N]) for one accept
    pass: the verdict kernel (totals + fits + consumed capacity in one
    sweep) feeds the flags kernel directly — no [N]-vector glue between
    launches. Parity twin of core._dense_accept."""
    J = choice.shape[0]
    N = gpu_free.shape[0]
    _require_aligned(N, J)
    tiles_n = N // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    row = pl.BlockSpec(
        (1, tile_j), lambda tn, tj, act: (0, tj), memory_space=pltpu.VMEM
    )
    col = pl.BlockSpec(
        (TILE_N, 1), lambda tn, tj, act: (tn, 0), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tiles_n, tiles_j),
        in_specs=[row, row, row, row, col, col],
        out_specs=[col] * 5,
        scratch_shapes=[
            pltpu.VMEM((TILE_N, 1), jnp.float32),
            pltpu.VMEM((TILE_N, 1), jnp.float32),
            pltpu.VMEM((TILE_N, 1), jnp.float32),
            pltpu.VMEM((TILE_N, 1), jnp.float32),
        ],
    )
    kern = functools.partial(_accept_verdict_kernel, tiles_j=tiles_j)
    ug, um, okall, okwin, win = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.int32),
        ],
        interpret=interpret,
    )(
        tile_act,
        choice.reshape(1, J),
        accept_key.reshape(1, J),
        d.reshape(1, J),
        md.reshape(1, J),
        gpu_free.reshape(N, 1),
        mem_free.reshape(N, 1),
    )
    accept = accept_flags_pallas(
        choice, accept_key, okall[:, 0], okwin[:, 0], win[:, 0], tile_act,
        interpret=interpret,
    )
    return accept, ug[:, 0], um[:, 0]


def _bid_select_kernel(
    alias_ref,  # i32[tiles_j] scalar-prefetch: per_group block per tile
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile may hold bids
    pg_ref,  # [G, TILE_J] i32 per-16-node-group packed mins
    prim_ref,  # [1, TILE_J] i32 out
    alt_ref,  # [1, TILE_J] i32 out
):
    del alias_ref
    tj = pl.program_id(0)
    big = jnp.int32(_I32MAX)

    @pl.when(act_ref[tj] == 0)
    def _inactive():
        prim_ref[:] = jnp.full_like(prim_ref, big)
        alt_ref[:] = jnp.full_like(alt_ref, big)

    @pl.when(act_ref[tj] != 0)
    def _active():
        pg = pg_ref[:]
        prim = jnp.min(pg, axis=0, keepdims=True)
        # Exclude the primary's group by VALUE, not argmin (Mosaic has no
        # i32 argmin): packed bids embed the node index and each group
        # covers a disjoint 16-node range, so a non-BIG group min is
        # globally unique per column — value exclusion selects exactly
        # the argmin group. All-BIG columns stay BIG either way.
        alt_ref[:] = jnp.min(
            jnp.where(pg == prim, big, pg), axis=0, keepdims=True
        )
        prim_ref[:] = prim


def bid_select_pallas(
    per_group: jax.Array,  # i32[G, J] per-16-node-group packed mins
    tile_alias: jax.Array,  # i32[tiles_j]
    tile_act: jax.Array,  # i32[tiles_j]
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """(primary, alternate) per job from the bid kernel's group mins.

    The jnp form is three reductions (min, argmin, masked min) over the
    same [G, J] producer — three HBM passes under XLA since per_group is
    a materialized kernel output. One Pallas sweep reads it once, and
    inactive J tiles (all-BIG columns) skip their read via the same
    alias trick the bid kernel uses. Must match the tail of
    core._round_bids_jnp bit-for-bit (parity-tested).
    """
    G, J = per_group.shape
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(tiles_j,),
        in_specs=[
            pl.BlockSpec(
                (G, tile_j), lambda tj, alias, act: (0, alias[tj]),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, tile_j), lambda tj, alias, act: (0, tj),
                memory_space=pltpu.VMEM,
            ),
        ] * 2,
    )
    prim, alt = pl.pallas_call(
        _bid_select_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((1, J), jnp.int32),
            jax.ShapeDtypeStruct((1, J), jnp.int32),
        ],
        interpret=interpret,
    )(tile_alias, tile_act, per_group)
    return prim[0], alt[0]


def _fence_kernel(
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile has unplaced jobs
    d_ref,  # [1, TILE_J] f32 gpu demand
    md_ref,  # [1, TILE_J] f32 mem demand
    rankf_ref,  # [1, TILE_J] f32 fence rank (RANK_INF = placed/invalid)
    gf_ref,  # [TILE_N, 1] f32 gpu free
    mf_ref,  # [TILE_N, 1] f32 mem free
    out_ref,  # [TILE_N, 1] f32 out: per-node fence minimum
):
    tj = pl.program_id(1)
    rank_inf = jnp.float32(RANK_INF)

    @pl.when(tj == 0)
    def _init():
        out_ref[:] = jnp.full_like(out_ref, rank_inf)

    # A tile whose jobs are all placed/invalid contributes only RANK_INF
    # (its rankf rows are RANK_INF), so skipping it is exact.
    @pl.when(act_ref[tj] != 0)
    def _accum():
        feas = (d_ref[:] <= gf_ref[:] + _EPS) & (md_ref[:] <= mf_ref[:] + _EPS)
        part = jnp.min(
            jnp.where(feas, rankf_ref[:], rank_inf), axis=1, keepdims=True
        )
        out_ref[:] = jnp.minimum(out_ref[:], part)


def fence_minrank_pallas(
    gpu_free: jax.Array,  # f32[N]
    mem_free: jax.Array,  # f32[N]
    gpu_demand: jax.Array,  # f32[J]
    mem_demand: jax.Array,  # f32[J]
    rankf_eff: jax.Array,  # f32[J] (RANK_INF = placed/invalid)
    tile_act: jax.Array,  # i32[tiles_j] 1 = tile has unplaced jobs
    *,
    interpret: bool = False,
) -> jax.Array:
    """Per-node fence minimum — Pallas twin of ``core._fence_minrank``.

    Skips J tiles whose jobs are all placed (their ranks are RANK_INF and
    cannot lower any node's minimum). With the job axis priority-sorted,
    placed jobs become a contiguous prefix as fence classes settle, so
    late rounds reduce over a small suffix instead of all J.
    """
    N = gpu_free.shape[0]
    J = gpu_demand.shape[0]
    _require_aligned(N, J)
    tiles_n = N // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    row = pl.BlockSpec(
        (1, tile_j), lambda tn, tj, act: (0, tj), memory_space=pltpu.VMEM
    )
    col = pl.BlockSpec(
        (TILE_N, 1), lambda tn, tj, act: (tn, 0), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tiles_n, tiles_j),
        in_specs=[row, row, row, col, col],
        out_specs=col,
    )
    out = pl.pallas_call(
        _fence_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, 1), jnp.float32),
        interpret=interpret,
    )(
        tile_act,
        gpu_demand.reshape(1, J),
        mem_demand.reshape(1, J),
        rankf_eff.reshape(1, J),
        gpu_free.reshape(N, 1),
        mem_free.reshape(N, 1),
    )
    return out[:, 0]


# --- Round-fusion mega-kernel (class-serialized greedy) ---------------------
#
# The pipelined round loop above is dispatch-bound, not bandwidth-bound
# (docs/PROFILING.md): ~47 XLA fusions + 7 Pallas launches per round at
# ~170-195us/round, of which the actual S traffic is ~15-25us. The fix is to
# stop paying per-round launches at all: serialize the priority fence classes
# (the job axis arrives priority-sorted from backends.py, so a fence class is
# a contiguous column window) and run EVERY settlement round of a class
# inside one grid step of ONE pallas_call, with the class's S window resident
# in VMEM and the capacity vectors resident across grid steps.
#
# Windows are VMEM-budget-sized, not priority-aligned, so a window can mix
# priority levels; the per-node fence therefore still runs INSIDE the
# window — as one [N,1] reduce over the resident block per round, costing
# nothing next to the old standalone fence kernel + launch. Cross-window
# inversion is prevented by the serialization itself (earlier windows hold
# all strictly-higher priority ranks when the job axis is sorted). The
# separate fence kernel, its launch, and the activity vectors disappear.
# The home-bid fence exemption is KEPT (an incumbent may always bid its own
# node): a fence-free-for-incumbents round is what holds survivor moves at
# ~0.2% under churn — dropping it was tried and measured at 6.1% moves on
# the 10k bench shape — at the price of the same documented inversion the
# pipelined path accepts (see _mega_round_math). The result is NOT
# bit-identical to the pipelined algorithm (later windows see
# post-settlement capacities instead of bidding early on unfenced nodes —
# if anything a closer match to serial FFD). It keeps the same hard
# guarantees: no overcommit ever, at exit no unplaced job finds any node
# feasible (capacities only shrink, so earlier windows' fixpoints survive
# later consumption), and no job is fenced out by an equal-or-lower rank.
#
# Parity contract: the kernel body and the pure-jnp twin (mega_rounds_jnp)
# share _mega_round_math, so interpret-mode output is bit-identical to the
# twin by construction (f32 demand sums are dyadic rationals — order-safe).

# VMEM budget for the resident S window. The round loop's live temporaries
# (packed bids, masks, accept reductions) cost ~5x the S window itself, so
# the whole kernel wants ~6-7x this in scoped VMEM — the explicit
# vmem_limit below raises Mosaic's 16MB default (v5e has 128MB physical
# VMEM; measured stack need at W=1024, N=1024 is ~27MB).
# Measured on v5e at 10k x 1k (scripts/mega_timing.py), final fenced
# kernel: W=1024 1.43ms / W=2048 ~1.20ms / W=3072 1.78ms — fewer, wider
# windows amortize per-round reduction latency until the pass cost (and,
# past W=2048, mixed-rank fence serialization) dominates. The fence-free
# prototype ranked the same W ordering at 1.15 / 1.00 / 1.15.
_MEGA_S_BYTES = 8 * 1024 * 1024
_MEGA_VMEM_LIMIT = 100 * 1024 * 1024


def mega_window(N: int, J: int) -> int | None:
    """Class-window width for the mega path: the largest 128-multiple
    dividing J whose [N, W] f32 S window fits the VMEM budget. None when
    no window fits (huge N) — callers fall back to the pipelined path.

    Unlike the tiled round kernels, mega takes the whole node axis in one
    block, so N only needs f32 sublane alignment (N % 8), not TILE_N.
    The one bucket below 128 (J=64) gets a single 64-wide window — a
    twin/interpret-only shape (Mosaic lanes want 128; `_resolve_accel`
    never routes it to the real kernel)."""
    if N % 8:
        return None
    fit = _MEGA_S_BYTES // (4 * N)
    wmax = min(J, fit // 128 * 128)
    if J % 128 == 0 and wmax >= 128:
        for w in range(wmax, 0, -128):
            if J % w == 0:
                return w
    if J == 64 and fit >= J:
        return J  # the one sub-128 bucket: a single 64-wide window
    return None  # N too large for any window: pipelined fallback


def _mega_round_math(
    Sq,  # [N, W] resident PRE-QUANTIZED cost window: (S - q_lo) * q_scale,
    #      computed once per window entry — saves an [N, W] ALU pass per
    #      round vs renormalizing S each time
    d,  # [1, W] gpu demand
    md,  # [1, W] mem demand
    key,  # [1, W] i32 accept key (rank | demand desc | index)
    rank,  # [1, W] f32 fence rank (class-compressed crank; RANK_INF for
    #        invalid jobs)
    cur,  # [1, W] i32 incumbent node index (-1 = none)
    may,  # [1, W] bool job may ever bid (valid)
    asg,  # [1, W] i32 assigned node, -1 = unplaced
    gf,  # [N, 1] gpu free (invalid nodes folded to -1)
    mf,  # [N, 1] mem free
    vg,  # [N, 1] fit-pressure weights (w_gpu / cap)
    vm,  # [N, 1]
    *,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
):
    """One serialized-class settlement round on resident values.

    Shared verbatim by the Mosaic kernel body and the jnp twin — parity by
    construction. Returns (asg, gf, mf, progress): in-window per-node
    priority fence (windows can mix fence classes — VMEM sizes them, not
    priority boundaries), bid (packed masked min over nodes), per-node
    joint-fit/winner accept (core._dense_accept's rule), capacity update.
    ``progress`` is False at the window fixpoint — additionally cut short
    when no unplaced demand fits the largest free node (saves the
    all-infeasible discovery round on exhausted-capacity windows, e.g.
    most of the 50k soak's tail)."""
    big = jnp.int32(_I32MAX)
    rank_inf = jnp.float32(RANK_INF)
    N = Sq.shape[0]
    unpl = may & (asg < 0)  # [1, W]
    feas = (d <= gf + _EPS) & (md <= mf + _EPS) & unpl  # [N, W]
    # Per-node fence over the resident window: job j may bid node n only
    # if no unplaced higher-rank job finds n feasible. The [N, W] fence
    # reduce only runs while the UNPLACED set actually spans more than
    # one rank — [1, W] min/max reduces detect that per round, so
    # single-class windows and straggler tails (conflict losers are
    # almost always one rank) skip it entirely.
    rank_eff = jnp.where(unpl, rank, rank_inf)
    r_lo = jnp.min(rank_eff)
    r_hi = jnp.max(jnp.where(unpl, rank, -rank_inf))
    minrank = jax.lax.cond(
        r_lo < r_hi,
        lambda: jnp.min(
            jnp.where(feas, rank_eff, rank_inf), axis=1, keepdims=True
        ),
        lambda: jnp.full((feas.shape[0], 1), rank_inf, jnp.float32),
    )
    n_glob = jax.lax.broadcasted_iota(jnp.int32, feas.shape, 0)
    # Home-bid fence exemption (same trade the pipelined path makes,
    # core._round_bids_jnp): an incumbent may always bid its OWN node —
    # rank-ordered acceptance there still lets a same-node higher-rank
    # bidder win, but without the exemption every fenced round strands
    # incumbents whose nodes interest a higher class, and survivor moves
    # under 10% churn measured 6.1% (BENCH r4 pre-fix) vs the ~0.2%
    # stability contract (BASELINE config 4). The cost is the one known
    # inversion: an incumbent's early home-grab can deflect a
    # higher-rank job that only discovers the node a round later.
    feas = feas & ((rank_eff <= minrank) | (cur == n_glob))
    # live best-fit pressure, pre-scaled into quantized units ([N, 1])
    uq = (vg * gf + vm * mf) * q_scale
    q = jnp.clip(Sq + uq, 0.0, q_max)
    packed = jnp.where(feas, (q.astype(jnp.int32) << node_idx_bits) | n_glob, big)
    prim = jnp.min(packed, axis=0, keepdims=True)  # [1, W]
    node_mask = jnp.int32((1 << node_idx_bits) - 1)
    choice = jnp.where(prim != big, prim & node_mask, jnp.int32(N))
    mine = choice == n_glob  # [N, W]; sentinel N matches no row
    tg = jnp.sum(jnp.where(mine, d, 0.0), axis=1, keepdims=True)  # [N, 1]
    tm = jnp.sum(jnp.where(mine, md, 0.0), axis=1, keepdims=True)
    win = jnp.min(jnp.where(mine, key, big), axis=1, keepdims=True)
    fits_all = (tg <= gf + _EPS) & (tm <= mf + _EPS)
    # Unlike the pipelined accept (whose second-chance pass re-checks
    # against post-first-pass capacities), every mega bid is made against
    # exactly the capacities this accept checks, so a contested node's
    # single winner always fits — no separate winner-fit test. One ``ok``
    # mask then drives the accept flags AND the consumed-capacity sums in
    # the same sweep (the pipelined kernels need separate winner-demand
    # reductions because their flags kernel runs in another launch).
    ok = mine & (fits_all | (key == win))
    accept = jnp.any(ok, axis=0, keepdims=True)
    used_g = jnp.sum(jnp.where(ok, d, 0.0), axis=1, keepdims=True)
    used_m = jnp.sum(jnp.where(ok, md, 0.0), axis=1, keepdims=True)
    asg = jnp.where(accept, choice, asg)
    gf = gf - used_g
    mf = mf - used_m
    # Fixpoint detection: accepts this round AND something still unplaced
    # AND the smallest remaining gpu demand fits the roomiest node (a cheap
    # O(N)+O(W) necessary condition for any further bid).
    still = may & (asg < 0)
    min_d = jnp.min(jnp.where(still, d, jnp.float32(3.4e38)))
    progress = (
        jnp.any(accept)
        & jnp.any(still)
        & (min_d <= jnp.max(gf) + _EPS)
    )
    return asg, gf, mf, progress


def _mega_kernel(
    d_ref,  # [1, W] f32 gpu demand (class window)
    md_ref,  # [1, W] f32 mem demand
    key_ref,  # [1, W] i32 accept key
    rank_ref,  # [1, W] f32 fence rank (RANK_INF for invalid)
    cur_ref,  # [1, W] i32 incumbent node index (-1 = none)
    asg0_ref,  # [1, W] i32 seeded assignment (-1 = unplaced) — churn
    #            re-solves seat joint-fitting incumbents up front
    may_ref,  # [1, W] i32 job validity (1 = may bid)
    gf0_ref,  # [N, 1] f32 starting gpu free (invalid nodes folded to -1)
    mf0_ref,  # [N, 1] f32 starting mem free
    vg_ref,  # [N, 1] f32 fit-pressure weights
    vm_ref,  # [N, 1] f32
    s_ref,  # [N, W] f32 resident cost window for this class
    asg_ref,  # [1, W] i32 out: assigned node (-1 unplaced)
    gf_ref,  # [N, 1] f32 out: free capacity, resident across classes
    mf_ref,  # [N, 1] f32 out
    rounds_ref,  # [1, 1] i32 out (SMEM): total settlement rounds
    capped_ref,  # [1, 1] i32 out (SMEM): 1 = some window hit max_rounds
    #              with progress still possible (budget exhaustion signal)
    *,
    max_rounds: int,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
):
    c = pl.program_id(0)

    @pl.when(c == 0)
    def _init():
        gf_ref[:] = gf0_ref[:]
        mf_ref[:] = mf0_ref[:]
        rounds_ref[0, 0] = 0
        capped_ref[0, 0] = 0

    d = d_ref[:]
    md = md_ref[:]
    key = key_ref[:]
    rank = rank_ref[:]
    cur = cur_ref[:]
    may = may_ref[:] != 0
    Sq = (s_ref[:] - q_lo) * q_scale  # once per window, not per round
    vg = vg_ref[:]
    vm = vm_ref[:]

    def cond(carry):
        _, _, _, r, prog = carry
        return prog & (r < max_rounds)

    def body(carry):
        asg, gf, mf, r, _ = carry
        asg, gf, mf, prog = _mega_round_math(
            Sq, d, md, key, rank, cur, may, asg, gf, mf, vg, vm,
            q_scale=q_scale, q_max=q_max,
            node_idx_bits=node_idx_bits,
        )
        return asg, gf, mf, r + jnp.int32(1), prog

    gf_in = gf_ref[:]
    mf_in = mf_ref[:]
    asg0 = asg0_ref[:]
    unpl0 = may & (asg0 < 0)
    init_prog = jnp.any(unpl0) & (
        jnp.min(jnp.where(unpl0, d, jnp.float32(3.4e38)))
        <= jnp.max(gf_in) + _EPS
    )
    asg, gf, mf, r, prog = jax.lax.while_loop(
        cond, body, (asg0, gf_in, mf_in, jnp.int32(0), init_prog)
    )
    asg_ref[:] = asg
    gf_ref[:] = gf
    mf_ref[:] = mf
    rounds_ref[0, 0] = rounds_ref[0, 0] + r
    # prog surviving the loop exit means the budget bound, not the
    # fixpoint — the caller's repair/fill safety net keys off this.
    capped_ref[0, 0] = capped_ref[0, 0] | prog.astype(jnp.int32)


def mega_solve_pallas(
    s_t: jax.Array,  # [N, J] resident cost field (priority-sorted J axis)
    d: jax.Array,  # f32[J]
    md: jax.Array,  # f32[J]
    accept_key: jax.Array,  # i32[J]
    rankf: jax.Array,  # f32[J] fence rank (RANK_INF for invalid)
    current_node: jax.Array,  # i32[J] incumbent node (-1 = none)
    asg_init: jax.Array,  # i32[J] seeded assignment (-1 = unplaced);
    #                       gf_eff/mf must already be net of seated jobs
    may_bid: jax.Array,  # bool[J] (valid jobs)
    gf_eff: jax.Array,  # f32[N] (invalid nodes folded to -1)
    mf: jax.Array,  # f32[N]
    vg: jax.Array,  # f32[N]
    vm: jax.Array,  # f32[N]
    *,
    max_rounds: int,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Whole greedy main loop in ONE pallas_call.

    Grid steps are contiguous windows of the priority-sorted job axis;
    each step runs its window's settlement rounds to a fixpoint over the
    VMEM-resident S window (with the per-node fence computed in-window)
    while the capacity vectors stay resident in revisited output blocks.
    Returns (assigned i32[J], gpu_free f32[N], mem_free f32[N],
    rounds i32, capped bool). ``max_rounds`` is a PER-WINDOW budget;
    ``capped`` reports any window exiting on it with progress still
    possible. Twin: ``mega_rounds_jnp``.
    """
    N, J = s_t.shape
    W = mega_window(N, J)
    if W is None:
        raise ValueError(f"no mega window for N={N} J={J}")
    n_classes = J // W
    row = pl.BlockSpec((1, W), lambda c: (0, c), memory_space=pltpu.VMEM)
    const_col = pl.BlockSpec(
        (N, 1), lambda c: (0, 0), memory_space=pltpu.VMEM
    )
    smem_scalar = pl.BlockSpec(
        (1, 1), lambda c: (0, 0), memory_space=pltpu.SMEM
    )
    kern = functools.partial(
        _mega_kernel,
        max_rounds=max_rounds,
        q_lo=q_lo,
        q_scale=q_scale,
        q_max=q_max,
        node_idx_bits=node_idx_bits,
    )
    asg, gf, mfo, rounds, capped = pl.pallas_call(
        kern,
        grid=(n_classes,),
        in_specs=[
            row,  # d
            row,  # md
            row,  # key
            row,  # rank
            row,  # cur
            row,  # asg0
            row,  # may
            const_col,  # gf0
            const_col,  # mf0
            const_col,  # vg
            const_col,  # vm
            pl.BlockSpec((N, W), lambda c: (0, c), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            row,
            const_col,
            const_col,
            smem_scalar,
            smem_scalar,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, J), jnp.int32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=_MEGA_VMEM_LIMIT
        ),
    )(
        d.reshape(1, J),
        md.reshape(1, J),
        accept_key.reshape(1, J),
        rankf.reshape(1, J),
        current_node.reshape(1, J),
        asg_init.reshape(1, J),
        may_bid.astype(jnp.int32).reshape(1, J),
        gf_eff.reshape(N, 1),
        mf.reshape(N, 1),
        vg.reshape(N, 1),
        vm.reshape(N, 1),
        s_t,
    )
    return asg[0], gf[:, 0], mfo[:, 0], rounds[0, 0], capped[0, 0] != 0


def mega_rounds_jnp(
    s_t: jax.Array,  # [N, J]
    d: jax.Array,  # f32[J]
    md: jax.Array,
    accept_key: jax.Array,
    rankf: jax.Array,
    current_node: jax.Array,
    asg_init: jax.Array,
    may_bid: jax.Array,
    gf_eff: jax.Array,
    mf: jax.Array,
    vg: jax.Array,
    vm: jax.Array,
    *,
    max_rounds: int,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Pure-jnp twin of ``mega_solve_pallas`` — identical class windows,
    identical round math (shared _mega_round_math), bit-identical output.
    The CPU/parity path for the class-serialized algorithm."""
    N, J = s_t.shape
    W = mega_window(N, J)
    if W is None:
        raise ValueError(f"no mega window for N={N} J={J}")
    n_classes = J // W
    d2 = d.reshape(1, J)
    md2 = md.reshape(1, J)
    key2 = accept_key.reshape(1, J)
    rank2 = rankf.reshape(1, J)
    cur2 = current_node.reshape(1, J)
    asg02 = asg_init.reshape(1, J)
    may2 = may_bid.reshape(1, J)
    gf0 = gf_eff.reshape(N, 1)
    mf0 = mf.reshape(N, 1)
    vg2 = vg.reshape(N, 1)
    vm2 = vm.reshape(N, 1)

    def class_body(c, carry):
        asg_full, gf, mf_c, rounds, capped = carry
        col = c * W
        Sw = (
            jax.lax.dynamic_slice(s_t, (0, col), (N, W)) - q_lo
        ) * q_scale
        dw = jax.lax.dynamic_slice(d2, (0, col), (1, W))
        mdw = jax.lax.dynamic_slice(md2, (0, col), (1, W))
        keyw = jax.lax.dynamic_slice(key2, (0, col), (1, W))
        rankw = jax.lax.dynamic_slice(rank2, (0, col), (1, W))
        curw = jax.lax.dynamic_slice(cur2, (0, col), (1, W))
        asg0w = jax.lax.dynamic_slice(asg02, (0, col), (1, W))
        mayw = jax.lax.dynamic_slice(may2, (0, col), (1, W))

        def cond(carry):
            _, _, _, r, prog = carry
            return prog & (r < max_rounds)

        def body(carry):
            asg, gf, mf_c, r, _ = carry
            asg, gf, mf_c, prog = _mega_round_math(
                Sw, dw, mdw, keyw, rankw, curw, mayw, asg, gf, mf_c,
                vg2, vm2,
                q_scale=q_scale, q_max=q_max,
                node_idx_bits=node_idx_bits,
            )
            return asg, gf, mf_c, r + jnp.int32(1), prog

        unpl0 = mayw & (asg0w < 0)
        init_prog = jnp.any(unpl0) & (
            jnp.min(jnp.where(unpl0, dw, jnp.float32(3.4e38)))
            <= jnp.max(gf) + _EPS
        )
        asg, gf, mf_c, r, prog = jax.lax.while_loop(
            cond, body, (asg0w, gf, mf_c, jnp.int32(0), init_prog)
        )
        asg_full = jax.lax.dynamic_update_slice(asg_full, asg, (0, col))
        return asg_full, gf, mf_c, rounds + r, capped | prog

    asg_full, gf, mf_out, rounds, capped = jax.lax.fori_loop(
        0, n_classes, class_body,
        (
            jnp.full((1, J), -1, jnp.int32),
            gf0,
            mf0,
            jnp.int32(0),
            jnp.bool_(False),
        ),
    )
    return asg_full[0], gf[:, 0], mf_out[:, 0], rounds, capped


def _accept_flags_kernel(
    act_ref,  # i32[tiles_j] scalar-prefetch: 1 = tile has bidders
    ch_ref,  # [1, TILE_J] i32 chosen node (N = no bid)
    key_ref,  # [1, TILE_J] i32 accept key
    all_ref,  # [TILE_N, 1] i32 node accepts all bidders (fits_all)
    winok_ref,  # [TILE_N, 1] i32 node accepts its winner (fits_win)
    winkey_ref,  # [TILE_N, 1] i32 winning key per node
    acc_ref,  # [1, TILE_J] i32 out: job's bid accepted
):
    tn = pl.program_id(1)  # inner: accumulate into the resident out block
    tj = pl.program_id(0)

    @pl.when((tn == 0) & (act_ref[tj] == 0))
    def _inactive():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(act_ref[tj] != 0)
    def _active():
        ch = ch_ref[:]
        n_glob = tn * TILE_N + jax.lax.broadcasted_iota(
            jnp.int32, (TILE_N, ch.shape[1]), 0
        )
        mine = ch == n_glob
        ok = (all_ref[:] != 0) | (
            (winok_ref[:] != 0) & (winkey_ref[:] == key_ref[:])
        )
        hit = jnp.any(mine & ok, axis=0, keepdims=True).astype(jnp.int32)

        @pl.when(tn == 0)
        def _init():
            acc_ref[:] = hit

        @pl.when(tn != 0)
        def _accum():
            acc_ref[:] = acc_ref[:] | hit


def accept_flags_pallas(
    choice: jax.Array,  # i32[J]
    accept_key: jax.Array,  # i32[J]
    fits_all: jax.Array,  # bool[N]
    fits_win: jax.Array,  # bool[N]
    win_key: jax.Array,  # i32[N]
    tile_act: jax.Array,  # i32[tiles_j]
    *,
    interpret: bool = False,
) -> jax.Array:
    """Per-job accept bit — the Pallas twin of ``core._dense_accept``'s
    [N, J] broadcast-compare + any() (which XLA runs as a full second
    [N, J] VPU pass per accept). Grid is (tj, tn) with tn INNER so the
    [1, TILE_J] output block stays VMEM-resident across the node sweep
    (accumulating across a non-innermost dim would round-trip the block
    through HBM each step — and Pallas does not guarantee read-back of
    prior contents for non-consecutive revisits)."""
    J = choice.shape[0]
    N = fits_all.shape[0]
    _require_aligned(N, J)
    tiles_n = N // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    row = pl.BlockSpec(
        (1, tile_j), lambda tj, tn, act: (0, tj), memory_space=pltpu.VMEM
    )
    col = pl.BlockSpec(
        (TILE_N, 1), lambda tj, tn, act: (tn, 0), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tiles_j, tiles_n),
        in_specs=[row, row, col, col, col],
        out_specs=row,
    )
    acc = pl.pallas_call(
        _accept_flags_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, J), jnp.int32),
        interpret=interpret,
    )(
        tile_act,
        choice.reshape(1, J),
        accept_key.reshape(1, J),
        fits_all.astype(jnp.int32).reshape(N, 1),
        fits_win.astype(jnp.int32).reshape(N, 1),
        win_key.reshape(N, 1),
    )
    return acc[0] != 0


# --- auction: the whole Jacobi loop in one launch ------------------------
#
# solve_auction's lax.while_loop costs ~40us of per-iteration launch /
# serialization overhead under XLA (measured r4: 4.78ms for 118 iterations
# at 1kx1k — the same dispatch-bound profile the greedy round loop had
# before the mega kernel). Here the loop runs INSIDE one pallas_call with
# the [J, N] benefit field VMEM-resident; every per-iteration product
# ([J, N] value/bid masks) lives and dies in VMEM. The jnp twin is
# core._auction_loop_jnp — bit-identical by construction: every float is
# either copied through a selection (max/min/where picks) or produced by
# the exact expression the twin uses (bid = price + (best_v - second_v)
# + eps), and all tie-breaks resolve to lowest-index in both.
#
# Scatter-free by necessity (Mosaic has no scatter): the twin's two
# .at[].set scatters (evictions, won-node writeback) become
# broadcast-compare + lane reductions over [J, N] — the same trade the
# accept-verdict kernels made (module docstring).

# Per-iteration live set: benefit + tiebreak inputs plus ~4 [J, N]
# selection temporaries Mosaic keeps concurrently (value, near/tb,
# bids_on, the evict/won compares). 12x input bytes is a conservative
# ceiling under the raised 100MB scoped limit.
_AUCTION_TEMPS = 12


def auction_fits(J: int, N: int) -> bool:
    """True when the one-launch auction's VMEM working set fits."""
    return _AUCTION_TEMPS * J * N * 4 <= _MEGA_VMEM_LIMIT


def _auction_kernel(
    eps_ref,  # SMEM f32 (1,1): runtime bid increment
    benefit_ref,  # VMEM f32 [J, N]; -1e9 marks infeasible
    tiebreak_ref,  # VMEM i32 [J, N] hash (core.py computes it once)
    valid_ref,  # VMEM i32 [J, 1]
    asg_ref,  # out VMEM i32 [J, 1]
    iters_ref,  # out SMEM i32 (1,1)
    *,
    max_iters: int,
    stale_iters: int,
    tie_tol: float,
    neg: float,
):
    J, N = benefit_ref.shape
    benefit = benefit_ref[...]
    tiebreak = tiebreak_ref[...]
    valid = valid_ref[...] != 0  # [J, 1]
    eps = eps_ref[0, 0]
    NEG = jnp.float32(neg)
    n2 = jax.lax.broadcasted_iota(jnp.int32, (J, N), 1)
    j2 = jax.lax.broadcasted_iota(jnp.int32, (J, N), 0)

    def cond(state):
        asg, owner, prices, it, progress, pending_best, stale = state
        pending = jnp.any((asg < 0) & valid)
        return (
            (progress != 0)
            & pending
            & (it < max_iters)
            & (stale < stale_iters)
        )

    def body(state):
        asg, owner, prices, it, _, pending_best, stale = state
        unassigned = (asg < 0) & valid  # [J, 1]
        value = jnp.where(unassigned, benefit - prices, NEG)  # [J, N]
        best_v = jnp.max(value, axis=1, keepdims=True)  # [J, 1]
        near = value >= best_v - jnp.float32(tie_tol)
        tb = jnp.where(near, tiebreak, -1)
        tb_max = jnp.max(tb, axis=1, keepdims=True)
        # argmax(tb, axis=1) with lowest-index ties, scatter-free
        best_n = jnp.min(
            jnp.where(tb == tb_max, n2, N), axis=1, keepdims=True
        )
        at_best = n2 == best_n  # [J, N]: job j's single bid target
        second_v = jnp.max(
            jnp.where(at_best, NEG, value), axis=1, keepdims=True
        )
        can_bid = unassigned & (best_v > NEG * 0.5)  # [J, 1]
        price_at_best = jnp.max(
            jnp.where(at_best, jnp.broadcast_to(prices, (J, N)), NEG),
            axis=1, keepdims=True,
        )  # gather prices[best_n] as a lane selection
        bid = jnp.where(
            can_bid, price_at_best + (best_v - second_v) + eps, NEG
        )  # [J, 1]

        bids_on = jnp.where(at_best & can_bid, bid, NEG)  # [J, N]
        win_bid = jnp.max(bids_on, axis=0, keepdims=True)  # [1, N]
        winner = jnp.min(
            jnp.where(bids_on == win_bid, j2, J), axis=0, keepdims=True
        )  # [1, N]: highest bid, lowest job index on float ties
        node_has_winner = win_bid > NEG * 0.5  # [1, N]

        # twin's eviction scatter: job j is evicted iff some re-won node
        # listed it as owner
        evict = (
            jnp.max(
                jnp.where(node_has_winner & (owner == j2), 1, 0),
                axis=1, keepdims=True,
            )
            > 0
        )  # [J, 1]
        asg = jnp.where(evict, -1, asg)
        owner = jnp.where(node_has_winner, winner, owner)
        prices = jnp.where(node_has_winner, win_bid, prices)
        # twin's won-node scatter: each winning job finds its (unique)
        # node by lane reduction
        won_node = jnp.min(
            jnp.where(node_has_winner & (winner == j2), n2, N),
            axis=1, keepdims=True,
        )  # [J, 1]
        asg = jnp.where(won_node < N, won_node, asg)
        n_pending = jnp.sum(((asg < 0) & valid).astype(jnp.int32))
        improved = n_pending < pending_best
        return (
            asg, owner, prices,
            it + jnp.int32(1),
            jnp.any(can_bid).astype(jnp.int32),
            jnp.minimum(n_pending, pending_best),
            jnp.where(improved, jnp.int32(0), stale + jnp.int32(1)),
        )

    init = (
        jnp.full((J, 1), -1, jnp.int32),
        jnp.full((1, N), -1, jnp.int32),
        jnp.zeros((1, N), jnp.float32),
        jnp.int32(0),
        jnp.int32(1),
        jnp.int32(J + 1),
        jnp.int32(0),
    )
    asg, _, _, it, _, _, _ = jax.lax.while_loop(cond, body, init)
    asg_ref[...] = asg
    iters_ref[0, 0] = it


def auction_solve(
    benefit: jax.Array,  # f32[J, N]
    tiebreak: jax.Array,  # i32[J, N]
    valid: jax.Array,  # bool[J]
    eps: jax.Array,  # f32 scalar (traced — a tunable request field)
    *,
    max_iters: int,
    stale_iters: int,
    tie_tol: float,
    neg: float,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """One-launch auction loop. Returns (assigned i32[J], iters i32).

    Twin: ``core._auction_loop_jnp`` (bit-identical; parity test in
    tests/test_solver_core.py). Callers gate on ``auction_fits`` and the
    J%8 / N%128 Mosaic layout requirements (core._auction_accel)."""
    J, N = benefit.shape
    kern = functools.partial(
        _auction_kernel,
        max_iters=max_iters,
        stale_iters=stale_iters,
        tie_tol=tie_tol,
        neg=neg,
    )
    full = pl.BlockSpec((J, N), lambda: (0, 0), memory_space=pltpu.VMEM)
    col = pl.BlockSpec((J, 1), lambda: (0, 0), memory_space=pltpu.VMEM)
    smem = pl.BlockSpec((1, 1), lambda: (0, 0), memory_space=pltpu.SMEM)
    asg, iters = pl.pallas_call(
        kern,
        in_specs=[smem, full, full, col],
        out_specs=[col, smem],
        out_shape=[
            jax.ShapeDtypeStruct((J, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=_MEGA_VMEM_LIMIT
        ),
    )(
        jnp.asarray(eps, jnp.float32).reshape(1, 1),
        benefit,
        tiebreak,
        valid.astype(jnp.int32).reshape(J, 1),
    )
    return asg[:, 0], iters[0, 0]


# --- Batched request routing: masked score row-argmax (router tier) ---------
#
# The fleet router's batched route solve (solver/routing.py) reduces to one
# primitive repeated every round: for each request row, the argmax over
# replicas of ``match_depth + per-replica bias`` under a hard eligibility
# mask, ties broken by the LOWEST replica index (the replica axis arrives
# name-sorted, so lowest index == lowest name — the router's documented
# tie-break). Under XLA the [B, R] score broadcast materializes per round;
# here it lives only in VMEM tiles, same rationale as the bid kernel above.
#
# Parity contract: the kernel and ``route_pick_jnp`` are bit-identical BY
# ARGUMENT, not by shared closure — the only arithmetic is one f32 add
# (match + bias, identical op in both); everything else is comparisons.
# A lexicographic max on (value, -index) is order-associative, so the
# kernel's sequential tile reduction (strict ``>`` keeps the earlier
# tile on equal values; within a tile the first index of the tile max
# wins) selects exactly the first index of the global row max — which is
# what the twin computes directly. tests/test_router_solver.py holds the
# bit-identity under interpret mode.

# Finite "-inf" for masked entries: Mosaic reductions over true -inf are
# fine, but a finite sentinel keeps the "no eligible replica" row exactly
# representable and comparable on both paths. Any real score is
# match + bias >= -(alpha * pressure_clip + stale + gamma) >> this.
ROUTE_NEG = -3e38


def _route_pick_kernel(
    match_ref,  # [TB, TR] i32 match depth in blocks; -1 = ineligible
    bias_ref,  # [1, TR] f32 per-replica bias (pressure/stale/price folded)
    active_ref,  # [TB, 1] i32 1 = row still unassigned this round
    val_ref,  # [TB, 1] f32 out: running row max
    idx_ref,  # [TB, 1] i32 out: running argmax (global replica index)
):
    tr = pl.program_id(1)
    neg = jnp.float32(ROUTE_NEG)

    @pl.when(tr == 0)
    def _init():
        val_ref[:] = jnp.full_like(val_ref, neg)
        idx_ref[:] = jnp.full_like(idx_ref, -1)

    ok = (match_ref[:] >= 0) & (active_ref[:] != 0)
    s = jnp.where(ok, match_ref[:].astype(jnp.float32) + bias_ref[:], neg)
    part_v = jnp.max(s, axis=1, keepdims=True)
    r_iota = (
        jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        + tr * s.shape[1]
    )
    part_i = jnp.min(
        jnp.where(s == part_v, r_iota, jnp.int32(_I32MAX)),
        axis=1, keepdims=True,
    )
    # strict >: an equal value in a LATER tile must not displace the
    # earlier (lower-index) holder. An all-masked tile has part_v == neg
    # and can never beat the init value, so idx stays -1 for dead rows.
    better = part_v > val_ref[:]
    idx_ref[:] = jnp.where(better, part_i, idx_ref[:])
    val_ref[:] = jnp.where(better, part_v, val_ref[:])


def route_pick_jnp(
    match: jax.Array,  # i32[B, R]; -1 = ineligible
    bias: jax.Array,  # f32[R]
    active: jax.Array,  # bool[B]
) -> tuple[jax.Array, jax.Array]:
    """jnp twin of ``route_pick_pallas``: (row max f32[B], first-index
    argmax i32[B], -1 when the row has no eligible replica)."""
    B, R = match.shape
    neg = jnp.float32(ROUTE_NEG)
    ok = (match >= 0) & active[:, None]
    s = jnp.where(ok, match.astype(jnp.float32) + bias[None, :], neg)
    v = jnp.max(s, axis=1)
    r_iota = jax.lax.broadcasted_iota(jnp.int32, (B, R), 1)
    idx = jnp.min(
        jnp.where(s == v[:, None], r_iota, jnp.int32(_I32MAX)), axis=1
    )
    idx = jnp.where(v > neg, idx, -1).astype(jnp.int32)
    return v, idx


def route_pick_pallas(
    match: jax.Array,  # i32[B, R]; -1 = ineligible
    bias: jax.Array,  # f32[R]
    active: jax.Array,  # bool[B]
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Masked row argmax — Pallas form of ``route_pick_jnp`` (see the
    section comment for why the two are bit-identical by argument)."""
    B, R = match.shape
    if B % 8 or R % 128:
        raise ValueError(
            f"route_pick_pallas needs B%8==0 and R%128==0, got B={B} "
            f"R={R}; use accel='jnp' for unaligned route buckets"
        )
    # problem.py buckets are all multiples of 64; 64 is the one bucket
    # below the 128 sublane tile (f32 min tile is (8, 128), so 64 rows
    # are legal — just a shorter block).
    tb = 128 if B % 128 == 0 else 64 if B % 64 == 0 else 8
    tr = _tile_j(R)
    row = pl.BlockSpec((1, tr), lambda b, r: (0, r), memory_space=pltpu.VMEM)
    blk = pl.BlockSpec(
        (tb, tr), lambda b, r: (b, r), memory_space=pltpu.VMEM
    )
    col = pl.BlockSpec((tb, 1), lambda b, r: (b, 0), memory_space=pltpu.VMEM)
    val, idx = pl.pallas_call(
        _route_pick_kernel,
        grid=(B // tb, R // tr),
        in_specs=[blk, row, col],
        out_specs=[col, col],
        out_shape=[
            jax.ShapeDtypeStruct((B, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
        ],
        interpret=interpret,
    )(match, bias.reshape(1, R), active.astype(jnp.int32).reshape(B, 1))
    return val[:, 0], idx[:, 0]
