"""Pallas TPU kernels for the greedy solver's round loop.

Why these exist: the round loop is a handful of [N, J] reductions whose
producers are broadcasts of [J]/[N] vectors. Under plain XLA each reduction
materializes its producer to HBM (measured ~1.3ms/round at 12288x1024 on a
v5e — ~8 full HBM round-trips), because multi-consumer broadcast producers
defeat reduction fusion. Here each round becomes:

- ONE ``bid`` kernel: tiles the resident [N, J] cost field S through VMEM
  (TILE_N=128 sublanes x TILE_J lanes), fusing feasibility, the per-node
  priority fence, static-bound cost quantization, and the packed
  (cost | node) i32 min — S is read from HBM exactly once per round and
  nothing [N, J]-sized is ever written back. The J axis is tiled so VMEM
  holds at most [128, 4096] f32 (4MB double-buffered) regardless of the
  job bucket — the 50k-job soak shape would otherwise blow the 16MB VMEM
  scoped limit. The fence minimum over ALL jobs (``minrank``) therefore
  arrives as an input (it only reads vectors; the caller computes it as a
  fused jnp reduction).
- TWO ``accept`` kernel calls (first chance + second chance): per-node
  column reductions (bidder demand totals + fused-key winner) whose inputs
  are four [J] vectors; the [TILE_N, TILE_J] broadcast lives only in VMEM,
  accumulating across J tiles (innermost grid dim, init at tile 0).

The jnp reference implementations live in ``core.py`` (`_round_bids_jnp`,
`_accept_reduce_jnp`) and remain the code path for CPU tests, sharded
(GSPMD) solves, and bucket shapes not divisible by 128. ``interpret=True``
runs these kernels on CPU for parity tests.

Design refs: /opt/skills/guides/pallas_guide.md (grid/BlockSpec, iota,
reduction patterns). No reference-repo counterpart exists: the reference
scheduler has no placement solver at all (SURVEY.md §0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_N = 128
MAX_TILE_J = 4096  # [128, 4096] f32 = 2MB/block, 4MB double-buffered
# Plain Python scalars: module-level jnp constants would be captured by the
# kernel closures, which pallas_call rejects ("captures constants"). Packed
# values are non-negative int32 (i31): Mosaic has no unsigned reductions.
_I32MAX = 0x7FFFFFFF
_EPS = 1e-4
# Large-but-finite sentinel for "this job may not bid" (placed/invalid);
# finite so `rank <= minrank` comparisons stay well-defined.
RANK_INF = 1e9


def _tile_j(J: int) -> int:
    """Largest J tile that divides the bucket (buckets are 128-aligned;
    >4096 buckets are all multiples of 2048)."""
    if J <= MAX_TILE_J:
        return J
    for t in (MAX_TILE_J, 3072, 2048, 1536, 1024, 512, 384, 256, 128):
        if J % t == 0:
            return t
    raise ValueError(f"no J tile divides {J}")


def _bid_kernel(
    d_ref,  # [1, TILE_J] f32 gpu demand
    md_ref,  # [1, TILE_J] f32 mem demand
    rankf_ref,  # [1, TILE_J] f32 fence rank, RANK_INF when may-not-bid
    cur_ref,  # [1, TILE_J] i32 incumbent node index, -1 = none
    gf_ref,  # [TILE_N, 1] f32 gpu free (invalid nodes pre-folded to -1)
    mf_ref,  # [TILE_N, 1] f32 mem free
    u_ref,  # [TILE_N, 1] f32 live best-fit pressure
    minrank_ref,  # [TILE_N, 1] f32 per-node fence minimum (over ALL jobs)
    s_ref,  # [TILE_N, TILE_J] f32 resident cost field tile
    out_ref,  # [8, TILE_J] i32 per-16-node-group packed (cost | node) mins
    *,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
):
    tn = pl.program_id(0)
    big = jnp.int32(_I32MAX)
    rank_inf = jnp.float32(RANK_INF)
    d = d_ref[:]
    md = md_ref[:]
    rankf = rankf_ref[:]
    gf = gf_ref[:]
    mf = mf_ref[:]

    feas = (d <= gf + _EPS) & (md <= mf + _EPS)  # [TILE_N, TILE_J]
    q = jnp.clip((s_ref[:] + u_ref[:] - q_lo) * q_scale, 0.0, q_max)
    n_glob = tn * TILE_N + jax.lax.broadcasted_iota(
        jnp.int32, feas.shape, 0
    )
    # Per-node priority fence: bid only if no higher-priority unplaced job
    # finds this node feasible anywhere in [0, J). RANK_INF rows drop out.
    # Incumbents are exempt on their OWN node (core._round_bids_jnp twin).
    is_home = cur_ref[:] == n_glob
    allowed = (
        feas
        & ((rankf <= minrank_ref[:]) | is_home)
        & (rankf < rank_inf * 0.5)
    )
    packed = jnp.where(
        allowed,
        (q.astype(jnp.int32) << node_idx_bits) | n_glob,
        big,
    )
    # Eight 16-node group mins per tile: the TPU output block needs >= 8
    # sublanes anyway, and finer groups give the second-chance pass better
    # alternates. Even a single-tile problem (N=128) has 7 other groups.
    out_ref[:] = jnp.min(
        packed.reshape(8, TILE_N // 8, packed.shape[1]), axis=1
    )


def bid_reduce_pallas(
    s_t: jax.Array,  # [N, J] resident cost field
    u: jax.Array,  # [N]
    gf_eff: jax.Array,  # [N] (invalid nodes folded to -1)
    mf: jax.Array,  # [N]
    d: jax.Array,  # [J]
    md: jax.Array,  # [J]
    rankf_eff: jax.Array,  # [J] (RANK_INF when may-not-bid)
    minrank: jax.Array,  # [N] fence minimum over all jobs
    current_node: jax.Array,  # i32[J] incumbent node index, -1 = none
    *,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """One S read -> (primary, alternate) packed i32 bids per job.

    The alternate is the best node outside the primary's 16-node group —
    a cross-group second choice for the solver's second-chance pass.
    Group mins match core._round_bids_jnp exactly (parity-tested).
    """
    N, J = s_t.shape
    if N % TILE_N or J % 128:
        raise ValueError(
            f"pallas round kernels need 128-aligned axes, got N={N} J={J}; "
            "use accel='jnp' for unaligned bucket shapes"
        )
    tiles_n = N // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    kern = functools.partial(
        _bid_kernel,
        q_lo=q_lo,
        q_scale=q_scale,
        q_max=q_max,
        node_idx_bits=node_idx_bits,
    )
    # grid (tn, tj): every (tn, tj) writes a disjoint output block, so
    # grid order is free; tj innermost keeps S reads sequential per node
    # tile.
    row = pl.BlockSpec(
        (1, tile_j), lambda tn, tj: (0, tj), memory_space=pltpu.VMEM
    )
    col = pl.BlockSpec(
        (TILE_N, 1), lambda tn, tj: (tn, 0), memory_space=pltpu.VMEM
    )
    per_group = pl.pallas_call(
        kern,
        grid=(tiles_n, tiles_j),
        in_specs=[
            row,  # d
            row,  # md
            row,  # rankf
            row,  # current_node
            col,  # gf
            col,  # mf
            col,  # u
            col,  # minrank
            pl.BlockSpec(
                (TILE_N, tile_j), lambda tn, tj: (tn, tj),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (8, tile_j), lambda tn, tj: (tn, tj), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((8 * tiles_n, J), jnp.int32),
        interpret=interpret,
    )(
        d.reshape(1, J),
        md.reshape(1, J),
        rankf_eff.reshape(1, J),
        current_node.reshape(1, J),
        gf_eff.reshape(N, 1),
        mf.reshape(N, 1),
        u.reshape(N, 1),
        minrank.reshape(N, 1),
        s_t,
    )
    prim = jnp.min(per_group, axis=0)  # [J]
    prim_group = jnp.argmin(per_group, axis=0)
    g_iota = jnp.arange(8 * tiles_n, dtype=jnp.int32)
    alt = jnp.min(
        jnp.where(
            g_iota[:, None] == prim_group[None, :],
            jnp.int32(_I32MAX),
            per_group,
        ),
        axis=0,
    )
    return prim, alt


def _accept_kernel(
    ch_ref,  # [1, TILE_J] i32 chosen node (N = no bid)
    key_ref,  # [1, TILE_J] i32 accept key
    d_ref,  # [1, TILE_J] f32
    md_ref,  # [1, TILE_J] f32
    tg_ref,  # [TILE_N, 1] f32 out: bidder gpu total
    tm_ref,  # [TILE_N, 1] f32 out: bidder mem total
    win_ref,  # [TILE_N, 1] i32 out: winning key
):
    tn = pl.program_id(0)
    tj = pl.program_id(1)
    big = jnp.int32(_I32MAX)
    ch = ch_ref[:]
    key = key_ref[:]
    n_glob = tn * TILE_N + jax.lax.broadcasted_iota(
        jnp.int32, (TILE_N, ch.shape[1]), 0
    )
    mine = ch == n_glob  # [TILE_N, TILE_J]; the N sentinel matches no node
    tg = jnp.sum(jnp.where(mine, d_ref[:], 0.0), axis=1, keepdims=True)
    tm = jnp.sum(jnp.where(mine, md_ref[:], 0.0), axis=1, keepdims=True)
    win = jnp.min(jnp.where(mine, key, big), axis=1, keepdims=True)

    # tj is the innermost grid dim: initialize at the first J tile, then
    # accumulate — the output block index is tj-independent, so Mosaic
    # keeps it resident in VMEM across the J sweep.
    @pl.when(tj == 0)
    def _init():
        tg_ref[:] = tg
        tm_ref[:] = tm
        win_ref[:] = win

    @pl.when(tj != 0)
    def _accum():
        tg_ref[:] = tg_ref[:] + tg
        tm_ref[:] = tm_ref[:] + tm
        win_ref[:] = jnp.minimum(win_ref[:], win)


def accept_reduce_pallas(
    choice: jax.Array,  # i32[J]
    accept_key: jax.Array,  # i32[J]
    d: jax.Array,  # f32[J]
    md: jax.Array,  # f32[J]
    num_nodes: int,
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-node (gpu total, mem total, winner key) over bidders."""
    J = choice.shape[0]
    if num_nodes % TILE_N or J % 128:
        raise ValueError(
            f"pallas round kernels need 128-aligned axes, got N={num_nodes} "
            f"J={J}; use accel='jnp' for unaligned bucket shapes"
        )
    tiles_n = num_nodes // TILE_N
    tile_j = _tile_j(J)
    tiles_j = J // tile_j
    row = pl.BlockSpec(
        (1, tile_j), lambda tn, tj: (0, tj), memory_space=pltpu.VMEM
    )
    col_out = pl.BlockSpec(
        (TILE_N, 1), lambda tn, tj: (tn, 0), memory_space=pltpu.VMEM
    )
    tg, tm, win = pl.pallas_call(
        _accept_kernel,
        grid=(tiles_n, tiles_j),
        in_specs=[row, row, row, row],
        out_specs=[col_out, col_out, col_out],
        out_shape=[
            jax.ShapeDtypeStruct((num_nodes, 1), jnp.float32),
            jax.ShapeDtypeStruct((num_nodes, 1), jnp.float32),
            jax.ShapeDtypeStruct((num_nodes, 1), jnp.int32),
        ],
        interpret=interpret,
    )(
        choice.reshape(1, J),
        accept_key.reshape(1, J),
        d.reshape(1, J),
        md.reshape(1, J),
    )
    return tg[:, 0], tm[:, 0], win[:, 0]
