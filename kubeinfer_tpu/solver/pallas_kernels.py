"""Pallas TPU kernels for the greedy solver's round loop.

Why these exist: the round loop is a handful of [N, J] reductions whose
producers are broadcasts of [J]/[N] vectors. Under plain XLA each reduction
materializes its producer to HBM (measured ~1.3ms/round at 12288x1024 on a
v5e — ~8 full HBM round-trips), because multi-consumer broadcast producers
defeat reduction fusion. Here each round becomes:

- ONE ``bid`` kernel: tiles the resident [N, J] cost field S through VMEM
  (TILE_N=128 sublanes x J lanes), fusing feasibility, the per-node
  priority fence, static-bound cost quantization, and the packed
  (cost | node) i32 min — S is read from HBM exactly once per round and
  nothing [N, J]-sized is ever written back.
- TWO ``accept`` kernel calls (first chance + second chance): per-node
  column reductions (bidder demand totals + fused-key winner) whose inputs
  are four [J] vectors; the [TILE_N, J] broadcast lives only in VMEM.

The jnp reference implementations live in ``core.py`` (`_round_bids_jnp`,
`_accept_reduce_jnp`) and remain the code path for CPU tests, sharded
(GSPMD) solves, and bucket shapes not divisible by 128. ``interpret=True``
runs these kernels on CPU for parity tests.

Design refs: /opt/skills/guides/pallas_guide.md (grid/BlockSpec, iota,
reduction patterns). No reference-repo counterpart exists: the reference
scheduler has no placement solver at all (SURVEY.md §0).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_N = 128
# Plain Python scalars: module-level jnp constants would be captured by the
# kernel closures, which pallas_call rejects ("captures constants"). Packed
# values are non-negative int32 (i31): Mosaic has no unsigned reductions.
_I32MAX = 0x7FFFFFFF
_EPS = 1e-4
# Large-but-finite sentinel for "this job may not bid" (placed/invalid);
# finite so `rank <= minrank` comparisons stay well-defined.
RANK_INF = 1e9


def _bid_kernel(
    d_ref,  # [1, J] f32 gpu demand
    md_ref,  # [1, J] f32 mem demand
    rankf_ref,  # [1, J] f32 fence rank, RANK_INF when may-not-bid
    gf_ref,  # [TILE_N, 1] f32 gpu free (invalid nodes pre-folded to -1)
    mf_ref,  # [TILE_N, 1] f32 mem free
    u_ref,  # [TILE_N, 1] f32 live best-fit pressure
    s_ref,  # [TILE_N, J] f32 resident cost field tile
    out_ref,  # [8, J] i32 per-16-node-group packed (cost | node) mins
    *,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
):
    t = pl.program_id(0)
    big = jnp.int32(_I32MAX)
    rank_inf = jnp.float32(RANK_INF)
    d = d_ref[:]
    md = md_ref[:]
    rankf = rankf_ref[:]
    gf = gf_ref[:]
    mf = mf_ref[:]

    feas = (d <= gf + _EPS) & (md <= mf + _EPS)  # [TILE_N, J]
    # Per-node priority fence: bid only if no higher-priority unplaced job
    # finds this node feasible. RANK_INF rows drop out of the min and the
    # <= check both.
    minrank = jnp.min(
        jnp.where(feas, rankf, rank_inf), axis=1, keepdims=True
    )  # [TILE_N, 1]
    allowed = feas & (rankf <= minrank) & (rankf < rank_inf * 0.5)

    q = jnp.clip((s_ref[:] + u_ref[:] - q_lo) * q_scale, 0.0, q_max)
    n_glob = t * TILE_N + jax.lax.broadcasted_iota(
        jnp.int32, feas.shape, 0
    )
    packed = jnp.where(
        allowed,
        (q.astype(jnp.int32) << node_idx_bits) | n_glob,
        big,
    )
    # Eight 16-node group mins per tile: the TPU output block needs >= 8
    # sublanes anyway, and finer groups give the second-chance pass better
    # alternates. Even a single-tile problem (N=128) has 7 other groups.
    out_ref[:] = jnp.min(
        packed.reshape(8, TILE_N // 8, packed.shape[1]), axis=1
    )


def bid_reduce_pallas(
    s_t: jax.Array,  # [N, J] resident cost field
    u: jax.Array,  # [N]
    gf_eff: jax.Array,  # [N] (invalid nodes folded to -1)
    mf: jax.Array,  # [N]
    d: jax.Array,  # [J]
    md: jax.Array,  # [J]
    rankf_eff: jax.Array,  # [J] (RANK_INF when may-not-bid)
    *,
    q_lo: float,
    q_scale: float,
    q_max: float,
    node_idx_bits: int,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """One S read -> (primary, alternate) packed i32 bids per job.

    The alternate is the best node outside the primary's 16-node group —
    a cross-group second choice for the solver's second-chance pass.
    Group mins match core._round_bids_jnp exactly (parity-tested).
    """
    N, J = s_t.shape
    if N % TILE_N or J % 128:
        raise ValueError(
            f"pallas round kernels need 128-aligned axes, got N={N} J={J}; "
            "use accel='jnp' for unaligned bucket shapes"
        )
    tiles = N // TILE_N
    kern = functools.partial(
        _bid_kernel,
        q_lo=q_lo,
        q_scale=q_scale,
        q_max=q_max,
        node_idx_bits=node_idx_bits,
    )
    row = pl.BlockSpec((1, J), lambda t: (0, 0), memory_space=pltpu.VMEM)
    col = pl.BlockSpec((TILE_N, 1), lambda t: (t, 0), memory_space=pltpu.VMEM)
    per_group = pl.pallas_call(
        kern,
        grid=(tiles,),
        in_specs=[
            row,  # d
            row,  # md
            row,  # rankf
            col,  # gf
            col,  # mf
            col,  # u
            pl.BlockSpec(
                (TILE_N, J), lambda t: (t, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec((8, J), lambda t: (t, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8 * tiles, J), jnp.int32),
        interpret=interpret,
    )(
        d.reshape(1, J),
        md.reshape(1, J),
        rankf_eff.reshape(1, J),
        gf_eff.reshape(N, 1),
        mf.reshape(N, 1),
        u.reshape(N, 1),
        s_t,
    )
    prim = jnp.min(per_group, axis=0)  # [J]
    prim_group = jnp.argmin(per_group, axis=0)
    g_iota = jnp.arange(8 * tiles, dtype=jnp.int32)
    alt = jnp.min(
        jnp.where(
            g_iota[:, None] == prim_group[None, :],
            jnp.int32(_I32MAX),
            per_group,
        ),
        axis=0,
    )
    return prim, alt


def _accept_kernel(
    ch_ref,  # [1, J] i32 chosen node (N = no bid)
    key_ref,  # [1, J] i32 accept key
    d_ref,  # [1, J] f32
    md_ref,  # [1, J] f32
    tg_ref,  # [TILE_N, 1] f32 out: bidder gpu total
    tm_ref,  # [TILE_N, 1] f32 out: bidder mem total
    win_ref,  # [TILE_N, 1] i32 out: winning key
):
    t = pl.program_id(0)
    big = jnp.int32(_I32MAX)
    ch = ch_ref[:]
    key = key_ref[:]
    n_glob = t * TILE_N + jax.lax.broadcasted_iota(
        jnp.int32, (TILE_N, ch.shape[1]), 0
    )
    mine = ch == n_glob  # [TILE_N, J]; the N sentinel matches no node
    tg_ref[:] = jnp.sum(jnp.where(mine, d_ref[:], 0.0), axis=1, keepdims=True)
    tm_ref[:] = jnp.sum(jnp.where(mine, md_ref[:], 0.0), axis=1, keepdims=True)
    win_ref[:] = jnp.min(jnp.where(mine, key, big), axis=1, keepdims=True)


def accept_reduce_pallas(
    choice: jax.Array,  # i32[J]
    accept_key: jax.Array,  # i32[J]
    d: jax.Array,  # f32[J]
    md: jax.Array,  # f32[J]
    num_nodes: int,
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-node (gpu total, mem total, winner key) over bidders."""
    J = choice.shape[0]
    if num_nodes % TILE_N or J % 128:
        raise ValueError(
            f"pallas round kernels need 128-aligned axes, got N={num_nodes} "
            f"J={J}; use accel='jnp' for unaligned bucket shapes"
        )
    tiles = num_nodes // TILE_N
    row = pl.BlockSpec((1, J), lambda t: (0, 0), memory_space=pltpu.VMEM)
    col_out = pl.BlockSpec(
        (TILE_N, 1), lambda t: (t, 0), memory_space=pltpu.VMEM
    )
    tg, tm, win = pl.pallas_call(
        _accept_kernel,
        grid=(tiles,),
        in_specs=[row, row, row, row],
        out_specs=[col_out, col_out, col_out],
        out_shape=[
            jax.ShapeDtypeStruct((num_nodes, 1), jnp.float32),
            jax.ShapeDtypeStruct((num_nodes, 1), jnp.float32),
            jax.ShapeDtypeStruct((num_nodes, 1), jnp.int32),
        ],
        interpret=interpret,
    )(
        choice.reshape(1, J),
        accept_key.reshape(1, J),
        d.reshape(1, J),
        md.reshape(1, J),
    )
    return tg[:, 0], tm[:, 0], win[:, 0]
