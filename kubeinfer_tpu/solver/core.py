"""Batched assignment solvers under ``jax.jit``.

Two device-side algorithms, selected per job via ``schedulerPolicy``:

``solve_greedy`` — parallel greedy with per-node conflict resolution.
  Each round, every unplaced replica bids on its argmin-cost feasible node
  ([J, N] masked reduction); contested nodes accept bidders in
  (priority desc, cost asc) order up to remaining capacity via a sorted
  segmented prefix-scan; capacities update and the loop repeats under
  ``lax.while_loop`` until a fixpoint or round budget. At a fixpoint every
  still-unplaced job provably had no feasible node left. This is the
  TPU-shaped replacement for a serial first-fit loop: rounds are O(J*N)
  dense vector ops (VPU/HBM-friendly) instead of 10k sequential decisions.
  Priority classes are released through a settlement gate (class k+1 bids
  only once every class-<=k job is placed or bid-less, see
  MAX_PRIORITY_CLASSES): per-node accept order alone can't stop a
  low-priority job from committing capacity on a node the high-priority
  class only discovers a round later.

``solve_auction`` — Bertsekas-style auction for one-replica-per-node
  instances (whole-node requests), giving Hungarian-quality assignments
  with bounded suboptimality J*eps. Dense bid matrix per iteration; pick it
  when quality beats cost (BASELINE.json config 3's "Hungarian" tier).

Design notes (SURVEY.md §7 hard parts 1-4):
- Everything is static-shape; no data-dependent Python control flow.
- Priority + preemption fall out of full re-solves: incumbents re-bid with a
  hysteresis (move-penalty) cost term, so placements are stable unless a
  higher-priority bidder genuinely needs the capacity.
- Gang all-or-nothing is a post-solve repair: incompletely-placed gangs are
  unwound and their capacity returned (one segmented reduction).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from kubeinfer_tpu.solver.problem import Problem

INFEASIBLE = jnp.float32(1e9)
_EPS = 1e-4  # capacity comparison slack for f32 fractional demands
# Floor on the tie-spreading scale. Even at weights.noise=0, perfectly tied
# jobs must not all bid one node per round (that caps placement at
# max_rounds nodes and silently under-schedules); a 1e-3 perturbation is far
# below any meaningful cost gap but keeps bids spread.
_MIN_TIE_NOISE = 1e-3
# Priority classes are released into the bidding through a settlement gate:
# class k+1 may bid only after every class-<=k job is placed or has no
# feasible bid. Without this, low-priority jobs commit capacity on nodes a
# high-priority job only discovers after losing a conflict — priority
# inversion under contention. Distinct priorities are quantile-compressed
# into at most this many classes: each class costs at least one extra
# [J, N] round on the device, and the per-node accept order still ranks
# exact priorities within a class.
MAX_PRIORITY_CLASSES = 4


@dataclass(frozen=True)
class ScoreWeights:
    """Cost-matrix weights. Lower cost = better placement.

    ``fit_gpu``/``fit_mem`` implement best-fit pressure: leftover capacity
    (normalized by node capacity, so each term is bounded in [0, 1]) is
    cost — tight fits win and fragmentation stays low, but no node is ever
    more than ~1.5 cost away from another on fit alone, which keeps the
    tie-spreading noise effective (see ``noise``).
    ``cache`` discounts nodes that already hold the replica's model (the
    whole point of the reference's shared-cache plane). ``move`` is the
    hysteresis penalty keeping re-solves from thrashing incumbents.
    ``topology`` penalizes leaving the replica's preferred topology group.
    """

    fit_gpu: float = 1.0
    fit_mem: float = 0.5
    cache: float = 5.0
    move: float = 8.0
    topology: float = 2.0
    # Tie-spreading temperature: deterministic Gumbel perturbation added to
    # the greedy cost matrix. Identical jobs see identical costs, so without
    # it the whole fleet bids the same argmin node every round and per-round
    # acceptance collapses to one node's capacity. Noise ~0.3 spreads bids
    # across near-tied nodes while leaving real cost gaps (cache hit = 5.0,
    # move = 8.0) intact: P(flip) < 1e-7. Floored at _MIN_TIE_NOISE (1e-3)
    # even when set to 0: fully deterministic cost-exact argmin is not
    # offered, because it caps placement at max_rounds nodes for tied
    # fleets; fit gaps below ~2e-2 may resolve either way under the floor.
    noise: float = 0.3


jax.tree_util.register_dataclass(
    ScoreWeights,
    data_fields=[],
    meta_fields=["fit_gpu", "fit_mem", "cache", "move", "topology", "noise"],
)


@dataclass
class Assignment:
    """Solver output: per-job node index (-1 = unplaced) + diagnostics."""

    node: jax.Array  # i32[J]
    gpu_free: jax.Array  # f32[N] capacity remaining after placement
    mem_free: jax.Array  # f32[N]
    rounds: jax.Array  # i32 rounds/iterations used
    placed: jax.Array  # i32 number of placed (valid) jobs


jax.tree_util.register_dataclass(
    Assignment,
    data_fields=["node", "gpu_free", "mem_free", "rounds", "placed"],
    meta_fields=[],
)


def _static_cost(p: Problem, w: ScoreWeights) -> jax.Array:
    """[J, N] cost terms that don't depend on remaining capacity."""
    jobs, nodes = p.jobs, p.nodes
    # cache affinity: cached[n, model_id[j]] -> [J, N]
    hit = jnp.take(nodes.cached, jobs.model_id, axis=1).T  # [J, N] bool
    cost = w.cache * (1.0 - hit.astype(jnp.float32))

    n_idx = jnp.arange(nodes.valid.shape[0], dtype=jnp.int32)
    has_home = jobs.current_node >= 0
    moved = has_home[:, None] & (jobs.current_node[:, None] != n_idx[None, :])
    cost = cost + w.move * moved.astype(jnp.float32)

    # preferred topology group = incumbent node's group (when placed)
    home = jnp.clip(jobs.current_node, 0, nodes.valid.shape[0] - 1)
    pref = jnp.where(has_home, nodes.topology[home], -1)
    topo_miss = (pref[:, None] >= 0) & (pref[:, None] != nodes.topology[None, :])
    cost = cost + w.topology * topo_miss.astype(jnp.float32)
    return cost


def _fit_cost(
    gpu_free: jax.Array,  # f32[N] free capacity the fit is scored against
    mem_free: jax.Array,
    p: Problem,
    w: ScoreWeights,
    inv_gpu_cap: jax.Array,  # f32[N] 1/capacity normalizers
    inv_mem_cap: jax.Array,
) -> jax.Array:
    """[J, N] best-fit pressure: normalized leftover capacity as cost."""
    jobs = p.jobs
    cost = w.fit_gpu * (
        (gpu_free[None, :] - jobs.gpu_demand[:, None]) * inv_gpu_cap[None, :]
    )
    return cost + w.fit_mem * (
        (mem_free[None, :] - jobs.mem_demand[:, None]) * inv_mem_cap[None, :]
    )


def _segmented_accept(
    choice: jax.Array,  # i32[J], node index or N (= no bid sentinel)
    bid_cost: jax.Array,  # f32[J] cost of the chosen node
    gpu_demand: jax.Array,
    mem_demand: jax.Array,
    priority: jax.Array,
    gpu_free: jax.Array,  # f32[N]
    mem_free: jax.Array,
    num_nodes: int,
) -> jax.Array:
    """Resolve per-node conflicts: accept bidders in (priority desc, demand
    asc, cost asc) order while the node's remaining capacity holds. Returns
    bool[J] accept mask (in original job order).

    Vectorized as: stable sort by the acceptance key; segmented prefix-sums
    of demand per node; a bidder is accepted iff every bidder at or before
    it in its segment fits (prefix-closed greedy). Demand-ascending within a
    priority class stops one oversized bidder from blocking a node's whole
    round.
    """
    J = choice.shape[0]
    order = jnp.lexsort((bid_cost, gpu_demand, -priority, choice))
    s_choice = choice[order]
    bidding = s_choice < num_nodes
    s_gpu = jnp.where(bidding, gpu_demand[order], 0.0)
    s_mem = jnp.where(bidding, mem_demand[order], 0.0)

    cum_gpu = jnp.cumsum(s_gpu)
    cum_mem = jnp.cumsum(s_mem)
    k = jnp.arange(J, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), s_choice[1:] != s_choice[:-1]]
    )
    seg_start = lax.cummax(jnp.where(is_start, k, -1))
    base_gpu = (cum_gpu - s_gpu)[seg_start]
    base_mem = (cum_mem - s_mem)[seg_start]
    within_gpu = cum_gpu - base_gpu
    within_mem = cum_mem - base_mem

    node_of = jnp.clip(s_choice, 0, num_nodes - 1)
    fit = (
        bidding
        & (within_gpu <= gpu_free[node_of] + _EPS)
        & (within_mem <= mem_free[node_of] + _EPS)
    )
    last_bad = lax.cummax(jnp.where(~fit, k, -1))
    s_accept = fit & (last_bad < seg_start)

    accept = jnp.zeros((J,), bool).at[order].set(s_accept)
    return accept


@functools.partial(jax.jit, static_argnames=("max_rounds",))
def solve_greedy(
    p: Problem,
    weights: ScoreWeights = ScoreWeights(),
    max_rounds: int = 64,
) -> Assignment:
    """Parallel greedy with conflict resolution (policy ``jax-greedy``)."""
    jobs, nodes = p.jobs, p.nodes
    J = jobs.valid.shape[0]
    N = nodes.valid.shape[0]
    static_cost = _static_cost(p, weights)
    node_valid_row = nodes.valid[None, :]
    inv_gpu_cap = 1.0 / jnp.maximum(nodes.gpu_capacity, 1.0)
    inv_mem_cap = 1.0 / jnp.maximum(nodes.mem_capacity, 1.0)

    # Dense priority rank (0 = highest priority class), quantile-compressed
    # to MAX_PRIORITY_CLASSES. Class k joins the bidding at round k.
    neg_p = jnp.where(jobs.valid, -jobs.priority, jnp.inf)
    order_p = jnp.argsort(neg_p)
    sorted_p = neg_p[order_p]
    is_new = jnp.concatenate(
        [jnp.zeros((1,), bool), sorted_p[1:] > sorted_p[:-1]]
    )
    dense_rank = jnp.cumsum(is_new.astype(jnp.int32))
    # Count classes over VALID jobs only: padded rows sort last (neg_p=+inf)
    # and would otherwise form a phantom class that shifts the scaled ranks
    # and can merge the top two real priority levels into one settlement
    # class (re-enabling the inversion the gate exists to prevent).
    last_valid = jnp.maximum(jnp.sum(jobs.valid.astype(jnp.int32)) - 1, 0)
    n_classes = dense_rank[last_valid] + 1
    # spread distinct levels evenly over the class budget (preserves order)
    dense_rank = (dense_rank * MAX_PRIORITY_CLASSES) // jnp.maximum(n_classes, 1)
    dense_rank = jnp.minimum(dense_rank, MAX_PRIORITY_CLASSES - 1)
    rank = jnp.zeros((J,), jnp.int32).at[order_p].set(dense_rank)
    max_rank = jnp.max(jnp.where(jobs.valid, rank, 0))

    # Tie-spreading field, sampled ONCE per solve: per-round threefry over
    # [J, N] would dominate the round cost on TPU (RNG is ALU-bound while
    # everything else here is HBM-bound). Rounds decorrelate by rotating
    # the field along the node axis instead (one cheap gather).
    base_noise = max(weights.noise, _MIN_TIE_NOISE) * jax.random.gumbel(
        jax.random.PRNGKey(0), (J, N), jnp.float32
    )

    def cond(state):
        assigned, gpu_free, mem_free, rounds, active_rank, progress = state
        pending = jnp.any((assigned < 0) & jobs.valid)
        return progress & pending & (rounds < max_rounds)

    def body(state):
        assigned, gpu_free, mem_free, rounds, active_rank, _ = state
        # Settlement gating: only classes <= active_rank may bid; the gate
        # advances when every released job is placed or bid-less. Gating by
        # round index alone is not enough — a high class can still be
        # resolving conflicts when the round counter releases the next
        # class, and the lower class then steals capacity the loser needs
        # (priority inversion).
        allowed = rank <= active_rank
        unassigned = (assigned < 0) & jobs.valid & allowed
        feas = (
            (jobs.gpu_demand[:, None] <= gpu_free[None, :] + _EPS)
            & (jobs.mem_demand[:, None] <= mem_free[None, :] + _EPS)
            & node_valid_row
            & unassigned[:, None]
        )
        fit_cost = _fit_cost(gpu_free, mem_free, p, weights, inv_gpu_cap, inv_mem_cap)
        tie_noise = jnp.roll(base_noise, rounds, axis=1)
        cost = jnp.where(feas, static_cost + fit_cost + tie_noise, INFEASIBLE)

        choice = jnp.argmin(cost, axis=1).astype(jnp.int32)
        # gather the winning cost instead of a second full [J, N] reduction
        best_cost = jnp.take_along_axis(cost, choice[:, None], axis=1)[:, 0]
        has_bid = best_cost < INFEASIBLE * 0.5
        choice = jnp.where(has_bid, choice, N)

        accept = _segmented_accept(
            choice, best_cost, jobs.gpu_demand, jobs.mem_demand,
            jobs.priority, gpu_free, mem_free, N,
        )
        assigned = jnp.where(accept, choice, assigned)
        used_gpu = jax.ops.segment_sum(
            jnp.where(accept, jobs.gpu_demand, 0.0), choice, num_segments=N + 1
        )[:N]
        used_mem = jax.ops.segment_sum(
            jnp.where(accept, jobs.mem_demand, 0.0), choice, num_segments=N + 1
        )[:N]
        # Gate advance: all released jobs placed or without a feasible bid.
        # (A loser that can re-bid keeps the gate closed; capacity is finite
        # so every class settles in finitely many rounds.)
        still_unassigned = (assigned < 0) & jobs.valid & allowed
        settled = ~jnp.any(still_unassigned & has_bid)
        advanced = settled & (active_rank <= max_rank)
        return (
            assigned,
            gpu_free - used_gpu,
            mem_free - used_mem,
            rounds + 1,
            jnp.where(advanced, active_rank + 1, active_rank),
            jnp.any(accept) | advanced,
        )

    init = (
        jnp.full((J,), -1, jnp.int32),
        nodes.gpu_free,
        nodes.mem_free,
        jnp.int32(0),
        jnp.int32(0),
        jnp.bool_(True),
    )
    assigned, gpu_free, mem_free, rounds, _, _ = lax.while_loop(cond, body, init)

    assigned, gpu_free, mem_free = _gang_repair(p, assigned)
    placed = jnp.sum((assigned >= 0) & jobs.valid).astype(jnp.int32)
    return Assignment(assigned, gpu_free, mem_free, rounds, placed)


def _gang_repair(p: Problem, assigned: jax.Array):
    """Unwind incompletely-placed gangs (all-or-nothing) and recompute
    capacity from scratch. Gang ids must lie in [0, J)."""
    jobs, nodes = p.jobs, p.nodes
    J = jobs.valid.shape[0]
    N = nodes.valid.shape[0]
    in_gang = (jobs.gang_id >= 0) & jobs.valid
    gid = jnp.clip(jobs.gang_id, 0, J - 1)
    need = jax.ops.segment_sum(in_gang.astype(jnp.int32), gid, num_segments=J)
    got = jax.ops.segment_sum(
        (in_gang & (assigned >= 0)).astype(jnp.int32), gid, num_segments=J
    )
    complete = got == need
    keep = (~in_gang) | complete[gid]
    assigned = jnp.where(keep, assigned, -1)

    seg = jnp.where(assigned >= 0, assigned, N)
    used_gpu = jax.ops.segment_sum(
        jnp.where(assigned >= 0, jobs.gpu_demand, 0.0), seg, num_segments=N + 1
    )[:N]
    used_mem = jax.ops.segment_sum(
        jnp.where(assigned >= 0, jobs.mem_demand, 0.0), seg, num_segments=N + 1
    )[:N]
    return assigned, nodes.gpu_free - used_gpu, nodes.mem_free - used_mem


@functools.partial(jax.jit, static_argnames=("max_iters",))
def solve_auction(
    p: Problem,
    weights: ScoreWeights = ScoreWeights(),
    eps: float = 0.01,
    max_iters: int = 512,
) -> Assignment:
    """Auction assignment (policy ``jax-auction``): one replica per node.

    Feasible means the whole remaining node capacity satisfies the demand;
    each node hosts at most one replica. Within-eps-optimal total cost for
    the jobs it places (standard auction guarantee: J*eps of optimal).

    Priority does NOT influence auction outcomes (a per-job constant in the
    benefit cancels out of the bid increments): when preemption matters,
    use ``jax-greedy`` (priority-gated rounds) or ``native-greedy``
    (priority-sorted serial pass).
    """
    jobs, nodes = p.jobs, p.nodes
    J = jobs.valid.shape[0]
    N = nodes.valid.shape[0]
    static_cost = _static_cost(p, weights)
    feas = (
        (jobs.gpu_demand[:, None] <= nodes.gpu_free[None, :] + _EPS)
        & (jobs.mem_demand[:, None] <= nodes.mem_free[None, :] + _EPS)
        & nodes.valid[None, :]
        & jobs.valid[:, None]
    )
    # benefit: higher is better; strictly bounded so -INF marks infeasible
    inv_gpu_cap = 1.0 / jnp.maximum(nodes.gpu_capacity, 1.0)
    inv_mem_cap = 1.0 / jnp.maximum(nodes.mem_capacity, 1.0)
    fit_cost = _fit_cost(
        nodes.gpu_free, nodes.mem_free, p, weights, inv_gpu_cap, inv_mem_cap
    )
    benefit = jnp.where(feas, -(static_cost + fit_cost), -INFEASIBLE)
    NEG = -INFEASIBLE

    def cond(state):
        assigned, owner, prices, it, progress = state
        pending = jnp.any((assigned < 0) & jobs.valid)
        return progress & pending & (it < max_iters)

    def body(state):
        assigned, owner, prices, it, _ = state
        unassigned = (assigned < 0) & jobs.valid
        value = jnp.where(unassigned[:, None], benefit - prices[None, :], NEG)
        top2, top2_idx = lax.top_k(value, 2)
        best_v, second_v = top2[:, 0], top2[:, 1]
        best_n = top2_idx[:, 0].astype(jnp.int32)
        can_bid = unassigned & (best_v > NEG * 0.5)
        # classic bid: price rise = value margin + eps
        bid = jnp.where(can_bid, prices[best_n] + (best_v - second_v) + eps, NEG)

        # per-node highest bid wins; ties broken by lowest job index
        bid_matrix = jnp.full((J, N), NEG, jnp.float32)
        j_idx = jnp.arange(J, dtype=jnp.int32)
        bid_matrix = bid_matrix.at[j_idx, jnp.clip(best_n, 0, N - 1)].set(
            jnp.where(can_bid, bid, NEG)
        )
        win_bid = jnp.max(bid_matrix, axis=0)
        winner = jnp.argmax(bid_matrix, axis=0).astype(jnp.int32)
        node_has_winner = win_bid > NEG * 0.5

        # Evict previous owners of re-won nodes. Non-events are routed to a
        # sentinel slot J so scatters never collide on a clipped index 0.
        evicted_owner = jnp.where(node_has_winner, owner, -1)
        evict_idx = jnp.where(evicted_owner >= 0, evicted_owner, J)
        evict_mask = jnp.zeros((J + 1,), bool).at[evict_idx].set(True)[:J]
        assigned = jnp.where(evict_mask, -1, assigned)

        owner = jnp.where(node_has_winner, winner, owner)
        prices = jnp.where(node_has_winner, win_bid, prices)
        # Each job bids on exactly one node, so winners are distinct jobs;
        # sentinel routing keeps no-winner nodes from clobbering job 0.
        win_idx = jnp.where(node_has_winner, winner, J)
        won_node = (
            jnp.full((J + 1,), -1, jnp.int32)
            .at[win_idx]
            .set(jnp.arange(N, dtype=jnp.int32))[:J]
        )
        assigned = jnp.where(won_node >= 0, won_node, assigned)
        return (assigned, owner, prices, it + 1, jnp.any(can_bid))

    init = (
        jnp.full((J,), -1, jnp.int32),
        jnp.full((N,), -1, jnp.int32),
        jnp.zeros((N,), jnp.float32),
        jnp.int32(0),
        jnp.bool_(True),
    )
    assigned, owner, prices, iters, _ = lax.while_loop(cond, body, init)

    assigned, gpu_free, mem_free = _gang_repair(p, assigned)
    placed = jnp.sum((assigned >= 0) & jobs.valid).astype(jnp.int32)
    return Assignment(assigned, gpu_free, mem_free, iters, placed)


def solve(p: Problem, policy: str = "jax-greedy", weights: ScoreWeights = ScoreWeights()) -> Assignment:
    """Dispatch by schedulerPolicy value (JAX policies only).

    ``native-greedy`` is the serial C++ baseline owned by the controller's
    backend layer, not this module — routing it here would silently run the
    wrong scorer, so it's rejected loudly, as is any unknown policy.
    """
    if policy == "jax-auction":
        return solve_auction(p, weights)
    if policy == "jax-greedy":
        return solve_greedy(p, weights)
    raise ValueError(
        f"unknown JAX solver policy {policy!r}; 'native-greedy' is dispatched "
        "by the controller's SchedulerBackend layer, not the JAX solver"
    )
